"""Informer/lister machinery (reference: pkg/client/informers/, listers/, and
the unstructured informer at pkg/util/unstructured/informer.go).

A ``SharedInformer`` runs a reflector thread (list + watch against the
backend), maintains a thread-safe ``Store`` keyed ``namespace/name``, and
dispatches add/update/delete handlers — the shape the v2 controller consumes
(pkg/controller.v2/controller.go:156-239).  ``SharedInformerFactory`` dedupes
informers per resource (factory.go behavior) and supports a resync period
(reference default 30 s: cmd/tf-operator/app/server.go:86) that re-delivers
every cached object as an update, driving the periodic reconcile.
"""

from __future__ import annotations

import logging
import threading
from k8s_tpu.analysis import checkedlock
from typing import Callable, Optional

from k8s_tpu import flight
from k8s_tpu.client.gvr import GVR

log = logging.getLogger(__name__)


def meta_namespace_key(obj: dict) -> str:
    """cache.MetaNamespaceKeyFunc: 'namespace/name' (or 'name')."""
    meta = obj.get("metadata") or {}
    ns, name = meta.get("namespace", ""), meta.get("name", "")
    return f"{ns}/{name}" if ns else name


def split_meta_namespace_key(key: str) -> tuple[str, str]:
    """cache.SplitMetaNamespaceKey."""
    if "/" in key:
        ns, _, name = key.partition("/")
        return ns, name
    return "", key


def _escalating_wait(n: int) -> float:
    """Relist-throttle schedule: 0.1 * 2^n seconds capped at 5 (exponent
    clamped well before int→float overflow could kill the reflector)."""
    return min(0.1 * (2 ** min(n, 10)), 5.0)


class Store:
    """Thread-safe object cache keyed by namespace/name, with optional
    secondary indexes (client-go cache.Indexers): ``add_index`` registers a
    function obj -> [index keys]; ``by_index`` answers point queries
    without scanning the cache.  Indexes turn the controller's
    pods-for-job lookup from O(all pods) into O(gang size) — the scale
    fix past 200 concurrent jobs."""

    def __init__(self):
        self._lock = checkedlock.make_rlock("informer.store")
        self._items: dict[str, dict] = {}
        self._index_funcs: dict[str, Callable[[dict], list[str]]] = {}
        # index name -> index key -> set of object keys
        self._indexes: dict[str, dict[str, set[str]]] = {}

    def add_index(self, name: str, fn: Callable[[dict], list[str]]) -> None:
        with self._lock:
            self._index_funcs[name] = fn
            idx: dict[str, set[str]] = {}
            for key, obj in self._items.items():
                for ik in fn(obj):
                    idx.setdefault(ik, set()).add(key)
            self._indexes[name] = idx

    def _index_add(self, key: str, obj: dict) -> None:
        for name, fn in self._index_funcs.items():
            idx = self._indexes[name]
            for ik in fn(obj):
                idx.setdefault(ik, set()).add(key)

    def _index_remove(self, key: str, obj: dict) -> None:
        for name, fn in self._index_funcs.items():
            idx = self._indexes[name]
            for ik in fn(obj):
                bucket = idx.get(ik)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del idx[ik]

    def replace(self, objs: list[dict]) -> None:
        with self._lock:
            self._items = {meta_namespace_key(o): o for o in objs}
            for name, fn in self._index_funcs.items():
                idx: dict[str, set[str]] = {}
                for key, obj in self._items.items():
                    for ik in fn(obj):
                        idx.setdefault(ik, set()).add(key)
                self._indexes[name] = idx

    def add(self, obj: dict) -> None:
        with self._lock:
            key = meta_namespace_key(obj)
            old = self._items.get(key)
            if old is not None:
                self._index_remove(key, old)
            self._items[key] = obj
            self._index_add(key, obj)

    def delete(self, obj: dict) -> None:
        with self._lock:
            key = meta_namespace_key(obj)
            old = self._items.pop(key, None)
            if old is not None:
                self._index_remove(key, old)

    def get_by_key(self, key: str) -> Optional[dict]:
        with self._lock:
            return self._items.get(key)

    def list(self) -> list[dict]:
        with self._lock:
            return list(self._items.values())

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._items.keys())

    def by_index(self, name: str, index_key: str) -> list[dict]:
        """Objects whose index function emitted ``index_key`` (no-copy,
        same read-only contract as Lister.list)."""
        with self._lock:
            keys = self._indexes.get(name, {}).get(index_key)
            if not keys:
                return []
            return [self._items[k] for k in keys if k in self._items]


class SharedInformer:
    """List+watch reflector with handler fan-out over one resource."""

    def __init__(self, backend, resource: GVR, namespace: Optional[str] = None,
                 resync_period: float = 30.0):
        self.backend = backend
        self.resource = resource
        self.namespace = namespace
        self.resync_period = resync_period
        self.store = Store()
        self._handlers: list[dict[str, Callable]] = []
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._active_watch = None
        self._watch_lock = checkedlock.make_lock("informer.watch")
        # Why the NEXT relist will run (flight-recorder watch health):
        # "initial" for the first list, then set by whichever failure path
        # invalidates the resume point (410 vs transport/stream error).
        self._next_relist_reason = flight.RELIST_INITIAL
        self._streams_opened = 0
        # set by _consume_watch when the CURRENT stream delivered a
        # server-sent ERROR frame — distinguishes an errored stream from a
        # clean end, which reasons alone can't (the post-relist default
        # reason is already "error")
        self._stream_error_frame = False

    # handler dict keys: on_add(obj), on_update(old, new), on_delete(obj)
    def add_event_handler(self, on_add=None, on_update=None, on_delete=None) -> None:
        self._handlers.append(
            {"add": on_add, "update": on_update, "delete": on_delete}
        )

    def _dispatch(self, kind: str, *args) -> None:
        for h in self._handlers:
            fn = h.get(kind)
            if fn is None:
                continue
            try:
                fn(*args)
            except Exception:  # a broken handler must not kill the reflector
                log.exception("informer handler error (%s %s)", kind, self.resource.plural)

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_for_cache_sync(self, timeout: float = 30.0) -> bool:
        return self._synced.wait(timeout)

    def run(self) -> None:
        """Start reflector + resync threads (returns immediately)."""
        t = threading.Thread(target=self._reflector_loop, daemon=True,
                             name=f"informer-{self.resource.plural}")
        t.start()
        self._threads.append(t)
        if self.resync_period and self.resync_period > 0:
            rt = threading.Thread(target=self._resync_loop, daemon=True,
                                  name=f"resync-{self.resource.plural}")
            rt.start()
            self._threads.append(rt)

    def stop(self) -> None:
        self._stop.set()
        # Close any in-flight watch so a reflector blocked on a socket read
        # (REST backend) unblocks instead of leaking the thread + connection.
        with self._watch_lock:
            if self._active_watch is not None:
                try:
                    self._active_watch.stop()
                # except-ok: best-effort close on shutdown; the socket may
                # already be torn down
                except Exception:
                    pass

    def _relist(self) -> Optional[int]:
        """Full list + cache diff; returns the collection resourceVersion to
        resume the watch from (None if the backend can't provide one)."""
        rv: Optional[int] = None
        if hasattr(self.backend, "list_with_rv"):
            objs, rv = self.backend.list_with_rv(self.resource, self.namespace)
        else:
            objs = self.backend.list(self.resource, self.namespace)
        # Snapshot the pre-relist cache so handlers see REAL old
        # objects: update handlers compare resourceVersions (a
        # same-object echo would suppress changes recovered across a
        # watch gap) and delete handlers need labels/ownerRefs to
        # unwind expectations.
        old_objs = {meta_namespace_key(o): o for o in self.store.list()}
        self.store.replace(objs)
        for o in objs:
            key = meta_namespace_key(o)
            if key in old_objs:
                self._dispatch("update", old_objs[key], o)
            else:
                self._dispatch("add", o)
        new_keys = {meta_namespace_key(o) for o in objs}
        # relist-detected deletions, dispatched with the last-known
        # full object (cache.DeletedFinalStateUnknown analogue)
        for key in set(old_objs) - new_keys:
            self._dispatch("delete", old_objs[key])
        self._synced.set()
        # Recorded AFTER the list succeeded (a failed list is a retry, not a
        # relist), with the reason that invalidated the previous resume
        # point; any LATER unattributed gap defaults to "error".
        flight.WATCH.record_relist(self.resource.plural,
                                   self._next_relist_reason)
        self._next_relist_reason = flight.RELIST_ERROR
        return rv

    def _reflector_loop(self) -> None:
        backoff = 0.1
        expired_in_row = 0
        # consecutive non-410 stream gaps (ERROR frames, broken rv
        # tracking): escalated separately from ``backoff``, which resets
        # after every successful relist and so can never escalate across
        # relist cycles
        stream_gaps_in_row = 0
        # opaque rv (str from real servers, int from the fake's list_with_rv);
        # None → a full relist is required
        last_rv = None
        while not self._stop.is_set():
            # Which phase of the cycle an exception came from.  "relist"
            # means the list attempt itself raised (a retry of the pending
            # relist); anything later is a watch/stream failure — inferring
            # this from ``last_rv is None`` would misclassify every watch
            # failure of a resume-free backend, where last_rv is ALWAYS
            # None, as a harmless relist retry.
            cycle_phase = "relist"
            try:
                if last_rv is None:
                    last_rv = self._relist()
                # rv=None from the list means the backend cannot mint
                # resume points at all (rest.py list_with_rv's documented
                # degradation) — every clean stream end then relists BY
                # DESIGN and must not be treated as a gap below
                resume_supported = last_rv is not None
                backoff = 0.1
                cycle_phase = "watch"
                w = self.backend.watch(
                    self.resource, self.namespace, resource_version=last_rv
                )
                # watch-stream health: every reopen after the first is a
                # restart (server watch-timeout recycling in the steady
                # state; a restart SPIKE means streams are dying early)
                self._streams_opened += 1
                if self._streams_opened > 1:
                    flight.WATCH.record_restart(self.resource.plural)
                stream_token = flight.WATCH.stream_started(
                    self.resource.plural)
                self._stream_error_frame = False
                with self._watch_lock:
                    self._active_watch = w
                try:
                    # A cleanly-ended watch (server-side timeoutSeconds)
                    # resumes from the last delivered event's rv — the
                    # steady state does NO relisting.  Only a gap (410
                    # Expired, no rv support, transport error) falls back.
                    last_rv = self._consume_watch(w, last_rv)
                finally:
                    flight.WATCH.stream_ended(self.resource.plural,
                                              stream_token)
                    with self._watch_lock:
                        self._active_watch = None
                    w.stop()
                if last_rv is not None:
                    expired_in_row = 0
                    stream_gaps_in_row = 0
                elif self._next_relist_reason == flight.RELIST_EXPIRED:
                    # mid-stream 410 ERROR frame: the SAME compaction
                    # signal as a 410 raised on the watch request — it
                    # must share the same backoff accounting, or a server
                    # whose history can't hold one watch cycle induces a
                    # hot zero-sleep relist loop through this path
                    expired_in_row += 1
                    if expired_in_row > 1:
                        self._stop.wait(_escalating_wait(expired_in_row))
                elif resume_supported or self._stream_error_frame:
                    # non-410 gap (error frame, rv tracking broke): its own
                    # escalating wait — a server erroring every stream must
                    # not full-LIST a 5k-object collection 10x/sec forever.
                    # An error FRAME throttles even in resume-free mode:
                    # no-rv doesn't make a server error healthy.
                    stream_gaps_in_row += 1
                    self._stop.wait(_escalating_wait(stream_gaps_in_row))
                else:
                    # resume-free mode, clean stream end: the per-cycle
                    # relist is the healthy steady state — no backoff, the
                    # gap counters RESET (they measure consecutive gaps,
                    # not lifetime totals — without this, isolated errors
                    # hours apart would each stall the full 5s cap), and
                    # the relist attributed distinctly so
                    # watch_relists_total never reads as a failure storm
                    expired_in_row = 0
                    stream_gaps_in_row = 0
                    self._next_relist_reason = flight.RELIST_NO_RV
            except Exception as e:
                if self._stop.is_set():
                    return
                # A failure in the RELIST ATTEMPT itself is a retry of the
                # pending relist, not a new gap — it must not overwrite the
                # pending reason, or a flaky first list would record the
                # initial (or 410) relist as "error".
                was_relisting = cycle_phase == "relist"
                last_rv = None  # any failure invalidates the resume point
                if getattr(e, "code", None) == 410:
                    log.info(
                        "watch rv expired for %s; relisting", self.resource.plural
                    )
                    self._next_relist_reason = flight.RELIST_EXPIRED
                    # first 410 relists immediately (expected after a churn
                    # burst); repeats back off — a server whose history
                    # can't hold one watch cycle must not induce a hot
                    # O(N)-list loop
                    expired_in_row += 1
                    if expired_in_row > 1:
                        # stop()-aware wait: a plain sleep would hold the
                        # reflector thread (and teardown) up to 5s
                        self._stop.wait(_escalating_wait(expired_in_row))
                    continue
                if not was_relisting:
                    self._next_relist_reason = flight.RELIST_ERROR
                    # a DYING watch (raised, e.g. proxy/LB connection kill)
                    # is a stream gap exactly like an ERROR frame: it must
                    # escalate across relist cycles — ``backoff`` alone
                    # resets after every successful relist and would relist
                    # a 5k-object collection 10x/sec forever
                    stream_gaps_in_row += 1
                log.exception("reflector relist for %s", self.resource.plural)
                self._stop.wait(max(backoff,
                                    _escalating_wait(stream_gaps_in_row)
                                    if not was_relisting else 0.0))
                backoff = min(backoff * 2, 5.0)

    def _consume_watch(self, w, last_rv: Optional[int]) -> Optional[int]:
        """Dispatch watch events until the stream ends; returns the rv of the
        last event seen (the resume point), or None if rv tracking broke."""
        while not self._stop.is_set():
            item = w.next(timeout=0.2)
            if item is None:
                if getattr(w, "stopped", False):
                    return last_rv
                continue
            event_type, obj = item
            flight.WATCH.record_event(self.resource.plural, event_type)
            if event_type == "ERROR":
                # server-sent error frame (e.g. 410 mid-stream): relist.
                # The frame's object is a Status whose code says why — a
                # mid-stream 410 is the same compaction signal as a 410 on
                # the watch request itself and is attributed the same way.
                self._stream_error_frame = True
                self._next_relist_reason = (
                    flight.RELIST_EXPIRED
                    if (obj or {}).get("code") == 410
                    else flight.RELIST_ERROR)
                return None
            if last_rv is not None:
                # rv is opaque (K8s API contract): carry the string through
                # to the next watch's resume parameter untouched — only the
                # backend that MINTED the rv may interpret it (the fake
                # int()s its own numeric rvs; a real apiserver just echoes)
                last_rv = (obj.get("metadata") or {}).get("resourceVersion") \
                    or None
            old = self.store.get_by_key(meta_namespace_key(obj))
            if event_type == "ADDED":
                self.store.add(obj)
                self._dispatch("add", obj)
            elif event_type == "MODIFIED":
                self.store.add(obj)
                self._dispatch("update", old if old is not None else obj, obj)
            elif event_type == "DELETED":
                self.store.delete(obj)
                self._dispatch("delete", obj)
        return last_rv

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period):
            for o in self.store.list():
                self._dispatch("update", o, o)


class Lister:
    """Read-only view over an informer's store (reference: pkg/client/listers).

    ``get`` returns a **copy** — it is the mutation seam: sync_tfjob
    defaults and status-updates the object it gets, and the typed
    ``from_dict`` wrappers alias nested dicts, so an uncopied get would
    write through into the cache.

    ``list`` returns the **cached objects themselves** under client-go's
    contract: listed objects MUST be treated as read-only (adoption,
    status derivation, and preemption checks all are).  Copying here was
    the operator's scale bottleneck — every reconcile deep-copied the
    whole namespace (O(jobs²) at the 100-concurrent design point; see
    BASELINE.md).  The reflector never mutates a stored object in place
    (watch events replace whole objects), so readers race only the
    key→object map, never an object's interior.  The stress tier's
    store-convergence check compares cache contents against the
    backend, so a consumer that mutates a listed object fails it."""

    def __init__(self, informer: SharedInformer):
        self._informer = informer

    def get(self, namespace: str, name: str) -> Optional[dict]:
        import copy

        key = f"{namespace}/{name}" if namespace else name
        obj = self._informer.store.get_by_key(key)
        return copy.deepcopy(obj) if obj is not None else None

    def list(self, namespace: Optional[str] = None, label_selector=None) -> list[dict]:
        from k8s_tpu.client.selectors import labels_match, parse_label_selector

        required = parse_label_selector(label_selector)
        out = []
        for o in self._informer.store.list():
            if namespace and (o.get("metadata") or {}).get("namespace") != namespace:
                continue
            if required and not labels_match(o, required):
                continue
            out.append(o)
        return out

    def by_index(self, name: str, index_key: str) -> list[dict]:
        """Point query against a registered store index (read-only
        objects, like ``list``)."""
        return self._informer.store.by_index(name, index_key)


# standard index functions (client-go's cache.Indexers equivalents)

OWNER_INDEX = "controller-uid"
ORPHAN_INDEX = "orphans-by-namespace"
FLEET_SCRAPE_INDEX = "fleet-scrape"
FLEET_SCRAPE_KEY = "scrapeable"


def index_by_controller_uid(obj: dict) -> list[str]:
    """Index key: the owning controller's uid (at most one per object)."""
    for ref in (obj.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("controller"):
            uid = ref.get("uid")
            return [uid] if uid else []
    return []


def index_orphans_by_namespace(obj: dict) -> list[str]:
    """Index key: namespace, only for objects with NO controller owner —
    the (normally tiny) adoption-candidate set."""
    for ref in (obj.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("controller"):
            return []
    return [(obj.get("metadata") or {}).get("namespace", "")]


def index_fleet_scrape_pods(obj: dict) -> list[str]:
    """Index key: the constant ``FLEET_SCRAPE_KEY`` for pods declaring a
    fleet scrape port (ISSUE 8).  The fleet plane's per-cycle discovery
    is then a point query over the (normally small) serving subset
    instead of an O(all cached pods) scan — at a 5k-pod training fleet
    with a handful of serving jobs, the scrape cycle reads only the
    serving pods.  The predicate is the SAME one discovery applies
    (``fleet.scrape_port``), so indexed and discoverable cannot drift."""
    from k8s_tpu.fleet.discovery import scrape_port

    return [FLEET_SCRAPE_KEY] if scrape_port(obj) is not None else []


class SharedInformerFactory:
    """Dedupe informers per resource (reference: externalversions/factory.go)."""

    def __init__(self, backend, namespace: Optional[str] = None, resync_period: float = 30.0):
        self.backend = backend
        self.namespace = namespace
        self.resync_period = resync_period
        self._informers: dict = {}

    def informer_for(self, resource: GVR) -> SharedInformer:
        key = (resource.group, resource.plural)
        if key not in self._informers:
            self._informers[key] = SharedInformer(
                self.backend, resource, self.namespace, self.resync_period
            )
        return self._informers[key]

    def lister_for(self, resource: GVR) -> Lister:
        return Lister(self.informer_for(resource))

    def start(self) -> None:
        for inf in self._informers.values():
            if not inf._threads:
                inf.run()

    def stop(self) -> None:
        for inf in self._informers.values():
            inf.stop()

    def wait_for_cache_sync(self, timeout: float = 30.0) -> bool:
        return all(i.wait_for_cache_sync(timeout) for i in self._informers.values())

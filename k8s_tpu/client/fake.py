"""In-memory fake apiserver (the fake-clientset test tier of SURVEY.md §4).

Plays the role of ``k8s.io/client-go/kubernetes/fake.NewSimpleClientset`` plus
the generated ``tfJobFake.Clientset`` (pkg/client/clientset/versioned/fake/):
full CRUD + watch over unstructured objects, an action log for assertions
(``Actions()`` in the Go fakes), label-selector list filtering, and
owner-reference garbage collection so e2e-style tests can assert cascade
deletion (test/e2e/main.go:151-186 behavior).

Storage is keyed by (group, plural) — API versions are representations of the
same resource, as in a real apiserver.
"""

from __future__ import annotations

import copy as _copy_mod
import functools
import itertools
import queue
from k8s_tpu.analysis import checkedlock
import time
from dataclasses import dataclass
from typing import Any, Iterable, Optional

from k8s_tpu import flight
from k8s_tpu.api.meta import now_rfc3339
from k8s_tpu.client import errors
from k8s_tpu.client.gvr import GVR
from k8s_tpu.client.selectors import labels_match, parse_label_selector
from k8s_tpu.client import strategic_merge as strategic_merge_mod

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


def _accounted(verb: str):
    """Flight-recorder accounting for one backend-protocol method (ISSUE 7):
    the fake records the same ``apiserver_requests_total{verb,resource,code}``
    substrate the REST client does, so benches against the in-process
    cluster measure exactly what a deployed operator would export.  The
    ``flight.account`` reentrancy guard keeps composite calls (patch =
    get + merge + update) at ONE count for the outermost verb — what a real
    apiserver would have seen on the wire."""

    # wire-parity success codes: a real apiserver answers 201 to a create
    success_code = 201 if verb == "POST" else 200

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, resource, *args, **kwargs):
            if not self.account_flight:
                # server-side store of the HTTP apiserver fixture: the
                # REST client already accounted this request on the wire —
                # counting the store call too would double it
                return fn(self, resource, *args, **kwargs)
            with flight.account(verb, resource.plural,
                                success_code=success_code):
                return fn(self, resource, *args, **kwargs)
        return wrapper
    return deco


@dataclass
class Action:
    """One recorded API call, for test assertions (Go fake Actions())."""

    verb: str
    resource: str  # plural
    namespace: str
    name: str = ""
    obj: Optional[dict] = None


class _Watch:
    """A single watcher: an iterator over (event_type, obj) tuples."""

    def __init__(self, cluster: "FakeCluster", key, namespace: Optional[str]):
        self._q: "queue.Queue[Optional[tuple[str, dict]]]" = queue.Queue()
        self._cluster = cluster
        self._key = key
        self._namespace = namespace
        self.stopped = False

    def _emit(self, event_type: str, obj: dict) -> None:
        ns = (obj.get("metadata") or {}).get("namespace", "")
        if self._namespace is None or ns == self._namespace:
            self._q.put((event_type, obj))

    def stop(self) -> None:
        self.stopped = True
        self._q.put(None)
        self._cluster._remove_watch(self._key, self)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is None:
            raise StopIteration
        return item

    def next(self, timeout: Optional[float] = None):
        """Non-magic accessor with timeout, for tests."""
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is None:
            return None
        return item


class FakeCluster:
    """Thread-safe in-memory cluster state implementing the API backend
    protocol consumed by ``k8s_tpu.client.clientset.Clientset``."""

    # Events retained per resource for resourceVersion-resumed watches; a
    # resume older than the window gets 410 Expired (etcd's compaction
    # analogue — small enough that tests can actually hit the 410 path).
    EVENT_HISTORY_LIMIT = 2048

    def __init__(self, copy_on_io: bool = True):
        self._lock = checkedlock.make_rlock("fake.store")
        self._store: dict[tuple[str, str], dict[tuple[str, str], dict]] = {}
        self._watches: dict[tuple[str, str], list[_Watch]] = {}
        self._uid_counter = itertools.count(1)
        self._rv = 0
        # per-resource event log [(rv, type, obj)] + highest rv trimmed out
        self._events: dict[tuple[str, str], list[tuple[int, str, dict]]] = {}
        self._events_trimmed: dict[tuple[str, str], int] = {}
        self.actions: list[Action] = []
        # copy_on_io=False shares stored dicts across the IO boundary instead
        # of deep-copying (~5 deepcopies per create, the dominant per-request
        # cost under the wire bench).  ONLY safe when every consumer treats
        # returned objects as immutable — i.e. behind the HTTP apiserver
        # (e2e.apiserver), where objects are serialized immediately and the
        # store itself never mutates a bucket entry in place (update/patch
        # REPLACE entries, so history/watch refs stay frozen).  In-process
        # fake-mode callers mutate returned dicts freely; they keep the
        # default.
        self._copy = _copy_mod.deepcopy if copy_on_io else (lambda x: x)
        # Injected per-create/per-delete latency (seconds): models the
        # apiserver round trip for benches/tests measuring the operator's
        # creation and teardown fan-outs.  Slept OUTSIDE the store lock,
        # exactly as concurrent real requests overlap their RTTs on the
        # wire.
        self.create_delay_s = 0.0
        self.delete_delay_s = 0.0
        # Flight-recorder call accounting (ISSUE 7).  True for in-process
        # backends (the call IS the apiserver request); the HTTP apiserver
        # fixture flips it off because the REST client accounts the same
        # requests on the wire side.
        self.account_flight = True

    def _next_rv(self) -> int:
        with self._lock:
            self._rv += 1
            return self._rv

    def latest_rv(self) -> int:
        """The cluster-wide resourceVersion high-water mark (etcd revision
        analogue) — what a List response advertises for watch resumption."""
        with self._lock:
            return self._rv

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _key(resource: GVR) -> tuple[str, str]:
        return (resource.group, resource.plural)

    def _bucket(self, resource: GVR) -> dict[tuple[str, str], dict]:
        return self._store.setdefault(self._key(resource), {})

    def _record(self, verb, resource: GVR, namespace, name="", obj=None):
        self.actions.append(Action(verb, resource.plural, namespace or "", name, obj))

    def _notify(self, resource: GVR, event_type: str, obj: dict) -> None:
        key = self._key(resource)
        try:
            rv = int((obj.get("metadata") or {}).get("resourceVersion", 0))
        except (TypeError, ValueError):
            rv = 0
        hist = self._events.setdefault(key, [])
        # private copy: live watchers receive ``obj`` itself, and a consumer
        # mutating its event must not corrupt what a later rv-resumed watch
        # replays
        hist.append((rv, event_type, self._copy(obj)))
        if len(hist) > self.EVENT_HISTORY_LIMIT:
            overflow = len(hist) - self.EVENT_HISTORY_LIMIT
            self._events_trimmed[key] = max(
                self._events_trimmed.get(key, 0), hist[overflow - 1][0]
            )
            del hist[:overflow]
        for w in list(self._watches.get(key, [])):
            w._emit(event_type, obj)

    def _remove_watch(self, key, w) -> None:
        with self._lock:
            if w in self._watches.get(key, []):
                self._watches[key].remove(w)

    def clear_actions(self) -> None:
        with self._lock:
            self.actions = []

    # -- CRUD ----------------------------------------------------------------

    @_accounted("POST")
    def create(self, resource: GVR, namespace: str, obj: dict) -> dict:
        if self.create_delay_s:
            time.sleep(self.create_delay_s)
        with self._lock:
            # A real apiserver never mutates the caller's submitted object;
            # work on a copy so server-assigned fields (uid, rv) don't leak
            # back and mask conflict-handling bugs under the fake.
            obj = self._copy(obj)
            meta = obj.setdefault("metadata", {})
            name = meta.get("name", "")
            if not name and meta.get("generateName"):
                name = meta["generateName"] + f"{next(self._uid_counter):05d}"
                meta["name"] = name
            if not name:
                raise errors.invalid("metadata.name is required")
            if resource.namespaced:
                self._check_namespace_match(meta, namespace, resource)
                meta.setdefault("namespace", namespace or "default")
            ns = meta.get("namespace", "") if resource.namespaced else ""
            bucket = self._bucket(resource)
            if (ns, name) in bucket:
                raise errors.already_exists(f"{resource.plural} {ns}/{name} already exists")
            meta.setdefault("uid", f"uid-{next(self._uid_counter)}")
            meta["resourceVersion"] = str(self._next_rv())
            meta.setdefault("creationTimestamp", now_rfc3339())
            obj.setdefault("apiVersion", resource.api_version)
            obj.setdefault("kind", resource.kind)
            stored = obj
            bucket[(ns, name)] = stored
            self._record("create", resource, ns, name, self._copy(stored))
            self._notify(resource, ADDED, self._copy(stored))
            return self._copy(stored)

    @_accounted("GET")
    def get(self, resource: GVR, namespace: str, name: str) -> dict:
        with self._lock:
            ns = namespace if resource.namespaced else ""
            obj = self._bucket(resource).get((ns or "", name))
            self._record("get", resource, ns, name)
            if obj is None:
                raise errors.not_found(f"{resource.plural} {ns}/{name} not found")
            return self._copy(obj)

    @_accounted("LIST")
    def list(
        self,
        resource: GVR,
        namespace: Optional[str] = None,
        label_selector=None,
        field_selector: Optional[dict] = None,
    ) -> list[dict]:
        with self._lock:
            required = parse_label_selector(label_selector)
            out = []
            for (ns, _name), obj in self._bucket(resource).items():
                if namespace is not None and resource.namespaced and ns != namespace:
                    continue
                if not labels_match(obj, required):
                    continue
                if field_selector and not self._fields_match(obj, field_selector):
                    continue
                out.append(self._copy(obj))
            self._record("list", resource, namespace or "")
            out.sort(key=lambda o: (o["metadata"].get("namespace", ""), o["metadata"]["name"]))
            return out

    @staticmethod
    def _fields_match(obj: dict, selector: dict) -> bool:
        for path, want in selector.items():
            cur: Any = obj
            for part in path.split("."):
                cur = (cur or {}).get(part)
            if cur != want:
                return False
        return True

    @_accounted("PUT")
    def update(self, resource: GVR, namespace: str, obj: dict) -> dict:
        with self._lock:
            meta = obj.get("metadata") or {}
            name = meta.get("name", "")
            if resource.namespaced:
                self._check_namespace_match(meta, namespace, resource)
            ns = (meta.get("namespace", namespace) or "") if resource.namespaced else ""
            bucket = self._bucket(resource)
            current = bucket.get((ns, name))
            if current is None:
                raise errors.not_found(f"{resource.plural} {ns}/{name} not found")
            sent_rv = meta.get("resourceVersion")
            cur_rv = current["metadata"].get("resourceVersion")
            if sent_rv and sent_rv != cur_rv:
                raise errors.conflict(
                    f"operation cannot be fulfilled on {resource.plural} {ns}/{name}: "
                    f"object has been modified (sent rv {sent_rv}, current {cur_rv})"
                )
            stored = self._copy(obj)
            stored["metadata"]["uid"] = current["metadata"]["uid"]
            stored["metadata"]["creationTimestamp"] = current["metadata"].get(
                "creationTimestamp", ""
            )
            stored["metadata"]["resourceVersion"] = str(self._next_rv())
            bucket[(ns, name)] = stored
            self._record("update", resource, ns, name, self._copy(stored))
            self._notify(resource, MODIFIED, self._copy(stored))
            return self._copy(stored)

    @_accounted("PATCH")
    def patch_merge(self, resource: GVR, namespace: str, name: str, patch: dict) -> dict:
        """Strategic-merge-lite: recursive dict merge (lists replaced)."""
        with self._lock:
            # The merge target must be a PRIVATE copy: a patch is logically
            # replace-after-merge, and merging into the stored dict in place
            # would corrupt watch-history aliases.  With copy_on_io=True,
            # get() already returned one — don't pay a second deepcopy on
            # the hottest verb of the reconcile/kubelet loops.
            current = self.get(resource, namespace, name)
            self._check_patch_rv_precondition(patch, current, resource, name)
            if self._copy is not _copy_mod.deepcopy:
                current = _copy_mod.deepcopy(current)

            def merge(dst, src):
                for k, v in src.items():
                    if isinstance(v, dict) and isinstance(dst.get(k), dict):
                        merge(dst[k], v)
                    elif v is None:
                        dst.pop(k, None)
                    else:
                        dst[k] = v

            merge(current, patch)
            self._require_patch_metadata(current, resource, name)
            current["metadata"].pop("resourceVersion", None)  # patch never conflicts here
            self._record("patch", resource, namespace, name, patch)
            return self.update(resource, namespace, current)

    @staticmethod
    def _check_namespace_match(meta: dict, namespace: str,
                               resource: GVR) -> None:
        """Real apiservers 400 when the body names a DIFFERENT namespace
        than the request targets (an unset body namespace defaults from
        the request).  Enforced in the store so the in-process clientset
        and the HTTP fixture agree — a divergence here would let an
        in-process test pass code a real apiserver rejects."""
        body_ns = meta.get("namespace") or ""
        if body_ns and namespace and body_ns != namespace:
            raise errors.bad_request(
                f"the namespace of the object ({body_ns}) does not match "
                f"the namespace on the request ({namespace}) for "
                f"{resource.plural}")

    @staticmethod
    def _check_patch_rv_precondition(patch: dict, current: dict,
                                     resource: GVR, name: str) -> None:
        """A patch CARRYING metadata.resourceVersion makes it a precondition
        (real apiserver semantics for merge + strategic patches): mismatch
        is 409 Conflict.  Patches without an rv never conflict.  A patch
        renaming or re-namespacing the object is rejected outright —
        name/namespace are immutable, and honoring the body name would
        route the write to a DIFFERENT bucket key."""
        meta = patch.get("metadata")
        if isinstance(meta, dict):
            for field in ("name", "namespace"):
                if field not in meta:
                    continue
                sent_id = meta[field]  # None = merge-delete: also immutable
                cur_id = (current.get("metadata") or {}).get(field)
                if sent_id != cur_id:
                    raise errors.invalid(
                        f"metadata.{field} is immutable: patch on "
                        f"{resource.plural} {name!r} may not change it "
                        f"({cur_id!r} -> {sent_id!r})")
        sent = meta.get("resourceVersion") if isinstance(meta, dict) else None
        cur = (current.get("metadata") or {}).get("resourceVersion")
        if sent is not None and str(sent) != str(cur):
            raise errors.conflict(
                f"operation cannot be fulfilled on {resource.plural} "
                f"{name!r}: the object has been modified (patch rv {sent}, "
                f"current {cur})")

    @staticmethod
    def _require_patch_metadata(merged: dict, resource: GVR, name: str) -> None:
        """A patch that nulls out metadata (or replaces the object without
        one) must 422 like a real apiserver, not KeyError in the handler
        thread (the connection would die with no Status body)."""
        if not isinstance(merged.get("metadata"), dict):
            raise errors.invalid(
                f"patch on {resource.plural} {name!r} may not remove "
                "object metadata")

    # API groups whose types carry strategic-merge struct tags.  Custom
    # resources have no Go structs to tag: a real apiserver answers 415
    # UnsupportedMediaType to a strategic patch on a CRD, and so does this
    # store — silently merging would let the operator ship a patch type a
    # real cluster rejects.
    _STRATEGIC_GROUPS = frozenset({"", "apps", "batch", "policy", "extensions"})

    @_accounted("PATCH")
    def patch_strategic(self, resource: GVR, namespace: str, name: str,
                        patch: dict) -> dict:
        """application/strategic-merge-patch+json (client/strategic_merge)."""
        if resource.group not in self._STRATEGIC_GROUPS:
            raise errors.unsupported_media_type(
                f"strategic merge patch is not supported for custom "
                f"resource {resource.group}/{resource.plural}; use "
                "application/merge-patch+json")
        with self._lock:
            current = self.get(resource, namespace, name)
            self._check_patch_rv_precondition(patch, current, resource, name)
            try:
                merged = strategic_merge_mod.strategic_merge(current, patch)
            except strategic_merge_mod.StrategicMergeError as e:
                raise errors.invalid(str(e))
            # strategic_merge is pure, but metadata may still alias the
            # store under copy_on_io=False; update() stores a private copy
            # only when copy_on_io is on, so re-copy the merged tree here
            if self._copy is not _copy_mod.deepcopy:
                merged = _copy_mod.deepcopy(merged)
            self._require_patch_metadata(merged, resource, name)
            merged["metadata"].pop("resourceVersion", None)
            self._record("patch", resource, namespace, name, patch)
            return self.update(resource, namespace, merged)

    @_accounted("DELETE")
    def delete(
        self,
        resource: GVR,
        namespace: str,
        name: str,
        propagation: str = "Background",
    ) -> None:
        if self.delete_delay_s:
            time.sleep(self.delete_delay_s)
        with self._lock:
            ns = (namespace or "") if resource.namespaced else ""
            bucket = self._bucket(resource)
            obj = bucket.pop((ns, name), None)
            self._record("delete", resource, ns, name)
            if obj is None:
                raise errors.not_found(f"{resource.plural} {ns}/{name} not found")
            # deletion is a state change: the DELETED event gets its own rv
            # (as in etcd) so rv-resumed watches can order it correctly.
            # Re-stamp on a fresh top-two-level copy, never in place: with
            # copy_on_io=False the popped dict is aliased by watch history
            # and already-delivered events, whose rvs must stay frozen.
            obj = dict(obj)
            obj["metadata"] = dict(obj["metadata"])
            obj["metadata"]["resourceVersion"] = str(self._next_rv())
            self._notify(resource, DELETED, obj)
        # cascade OUTSIDE the lock: every dependent delete sleeps the
        # injected delete_delay_s RTT, and a GC wave under the store lock
        # would freeze the whole fake apiserver for N x RTT
        if propagation in ("Background", "Foreground"):
            self._gc_dependents(obj["metadata"].get("uid"), ns)

    # NOT @_accounted: the REST client implements delete_collection as
    # 1 LIST + N individual DELETEs on the wire, and so does this method
    # via its inner list()/delete() calls — letting those account
    # naturally keeps the fake's substrate identical to the deployed one
    # (a single outer DELETE would hide the LIST from steady-state proofs).
    def delete_collection(self, resource: GVR, namespace: str, label_selector=None) -> int:
        # enumerate under the lock, delete OUTSIDE it: each inner delete
        # sleeps the injected RTT (delete_delay_s), and N sleeps while
        # holding the store lock would stall every other API call for the
        # whole wave (the blocking-under-lock class k8s_tpu.analysis
        # gates on).  A real apiserver's LIST + N DELETEs aren't atomic
        # either.
        with self._lock:
            victims = self.list(resource, namespace, label_selector)
        deleted = 0
        for v in victims:
            # Use each victim's own namespace: with namespace=None the
            # caller's argument is not a valid delete target.
            vns = v["metadata"].get("namespace", "")
            try:
                self.delete(resource, vns, v["metadata"]["name"])
                deleted += 1
            except errors.ApiError:
                pass
        return deleted

    def _gc_dependents(self, owner_uid: Optional[str], namespace: str) -> None:
        """Owner-reference GC: cascade-delete dependents of a deleted owner.

        Scans the store under the lock but issues the deletes unlocked —
        ``delete()`` sleeps the injected ``delete_delay_s`` RTT, and a
        cascade must not serialize the whole cluster behind it."""
        if not owner_uid:
            return
        victims: list[tuple[GVR, str, str]] = []
        with self._lock:
            for key in list(self._store):
                bucket = self._store[key]
                for (ns, name), obj in list(bucket.items()):
                    refs = (obj.get("metadata") or {}).get("ownerReferences") or []
                    if any(r.get("uid") == owner_uid for r in refs):
                        group, plural = key
                        gvr = GVR(group, obj.get("apiVersion", "v1").split("/")[-1], plural,
                                  obj.get("kind", ""))
                        victims.append((gvr, ns, name))
        for gvr, ns, name in victims:
            try:
                self.delete(gvr, ns, name)
            except errors.ApiError:
                pass

    # -- watch ---------------------------------------------------------------

    @_accounted("LIST")
    def list_with_rv(
        self,
        resource: GVR,
        namespace: Optional[str] = None,
        label_selector=None,
        field_selector: Optional[dict] = None,
    ) -> tuple[list[dict], int]:
        """List plus the collection resourceVersion to resume a watch from —
        the ListMeta.resourceVersion contract real apiservers provide."""
        with self._lock:
            items = self.list(resource, namespace, label_selector, field_selector)
            return items, self.latest_rv()

    @_accounted("WATCH")
    def watch(
        self,
        resource: GVR,
        namespace: Optional[str] = None,
        resource_version: Optional[int] = None,
    ) -> _Watch:
        """Open a watch.  With ``resource_version``, replay retained events
        with rv > resource_version before going live (atomically, under the
        cluster lock, so no event is missed or duplicated).  A resume older
        than the retained window raises 410 Expired."""
        with self._lock:
            key = self._key(resource)
            w = _Watch(self, key, namespace)
            if resource_version is not None:
                # rvs are opaque to clients; this backend minted them as
                # ints, so it may (and must) interpret them numerically here
                resource_version = int(resource_version)
                if resource_version < self._events_trimmed.get(key, 0):
                    raise errors.expired(
                        f"resourceVersion {resource_version} is too old "
                        f"(retained history starts after "
                        f"{self._events_trimmed.get(key, 0)})"
                    )
                for rv, event_type, obj in self._events.get(key, []):
                    if rv > resource_version:
                        w._emit(event_type, self._copy(obj))
            self._watches.setdefault(key, []).append(w)
            return w

    # -- test conveniences ---------------------------------------------------

    def objects(self, resource: GVR) -> Iterable[dict]:
        with self._lock:
            return [self._copy(o) for o in self._bucket(resource).values()]

    def set_pod_phase(self, namespace: str, name: str, phase: str, **status_kw) -> dict:
        """Simulate kubelet: flip a pod's status.phase (and extra status keys)."""
        from k8s_tpu.client.gvr import PODS

        pod = _copy_mod.deepcopy(self.get(PODS, namespace, name))
        pod.setdefault("status", {})["phase"] = phase
        pod["status"].update(status_kw)
        return self.update(PODS, namespace, pod)

"""Label-selector parsing/matching shared by the fake, REST, and lister tiers."""

from __future__ import annotations


def parse_label_selector(selector) -> dict[str, str]:
    """Accept 'a=b,c=d' strings or dicts; returns the required label map."""
    if not selector:
        return {}
    if isinstance(selector, dict):
        return dict(selector)
    out = {}
    for part in selector.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"unsupported label selector term: {part!r}")
        k, _, v = part.partition("=")
        out[k.strip()] = v.lstrip("=").strip()  # tolerate 'a==b'
    return out


def labels_match(obj: dict, required: dict[str, str]) -> bool:
    labels = (obj.get("metadata") or {}).get("labels") or {}
    return all(labels.get(k) == v for k, v in required.items())

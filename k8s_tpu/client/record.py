"""Event recorder (k8s.io/client-go/tools/record equivalent).

K8s Events are load-bearing telemetry in this system: the e2e harness asserts
on pod/service create events (py/test_runner.py:301-332), so controllers must
record them faithfully (pkg/trainer/replicas.go:470-506,
pkg/controller.v2/service_control.go:96-112).
"""

from __future__ import annotations

import logging
import time

from k8s_tpu.api.meta import now_rfc3339
from k8s_tpu.client.clientset import Clientset

log = logging.getLogger(__name__)

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


class EventRecorder:
    """Records events attached to an involved object, apiserver-backed."""

    def __init__(self, clientset: Clientset, component: str):
        self.clientset = clientset
        self.component = component

    def event(self, involved: dict, event_type: str, reason: str, message: str) -> None:
        meta = involved.get("metadata") or {}
        ns = meta.get("namespace", "default")
        # Nanosecond suffix like client-go: unique across operator restarts
        # and replicas, where a per-process counter would collide.
        n = time.time_ns()
        ev = {
            "metadata": {"name": f"{meta.get('name', 'unknown')}.{n:x}", "namespace": ns},
            "involvedObject": {
                "kind": involved.get("kind", ""),
                "namespace": ns,
                "name": meta.get("name", ""),
                "uid": meta.get("uid", ""),
                "apiVersion": involved.get("apiVersion", ""),
            },
            "reason": reason,
            "message": message,
            "type": event_type,
            "source": {"component": self.component},
            "firstTimestamp": now_rfc3339(),
            "lastTimestamp": now_rfc3339(),
            "count": 1,
        }
        try:
            self.clientset.events(ns).create(ev)
        except Exception:
            log.exception("failed to record event %s/%s", reason, message)

    def eventf(self, involved: dict, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(involved, event_type, reason, fmt % args if args else fmt)


class FakeRecorder:
    """record.NewFakeRecorder equivalent: captures events in-memory."""

    def __init__(self):
        self.events: list[str] = []

    def event(self, involved: dict, event_type: str, reason: str, message: str) -> None:
        self.events.append(f"{event_type} {reason} {message}")

    def eventf(self, involved: dict, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(involved, event_type, reason, fmt % args if args else fmt)

"""Event recorder (k8s.io/client-go/tools/record equivalent).

K8s Events are load-bearing telemetry in this system: the e2e harness asserts
on pod/service create events (py/test_runner.py:301-332), so controllers must
record them faithfully (pkg/trainer/replicas.go:470-506,
pkg/controller.v2/service_control.go:96-112).

Flight-recorder integration (ISSUE 7): every recorded event also lands on
the involved object's lifecycle timeline (``flight.TIMELINE``), and the
recorder exports ``events_recorded_total`` / ``events_dropped_total`` /
``events_aggregated_total`` through ``flight.EVENTS`` — a queue-overflow
drop is *counted*, never raised, so the reconcile path can't be failed by
its own telemetry.
"""

from __future__ import annotations

import logging
import queue
import threading
from k8s_tpu.analysis import checkedlock
import time
from collections import OrderedDict

from k8s_tpu import flight
from k8s_tpu.api.meta import now_rfc3339
from k8s_tpu.client.clientset import Clientset

log = logging.getLogger(__name__)

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


def _timeline_event(involved: dict, event_type: str, reason: str,
                    message: str) -> None:
    """Mirror one recorder event onto the involved object's flight-recorder
    timeline (no-op while the recorder is inactive)."""
    meta = involved.get("metadata") or {}
    ns = meta.get("namespace", "default")
    name = meta.get("name", "")
    if not name:
        return
    flight.timeline(f"{ns}/{name}", "event", reason=reason, message=message,
                    type=event_type, involved_kind=involved.get("kind", ""))


class EventRecorder:
    """Records events attached to an involved object, apiserver-backed."""

    def __init__(self, clientset: Clientset, component: str):
        self.clientset = clientset
        self.component = component

    def event(self, involved: dict, event_type: str, reason: str, message: str) -> None:
        _timeline_event(involved, event_type, reason, message)
        flight.EVENTS.record_recorded()
        self._post(involved, event_type, reason, message)

    def _build_event(self, involved: dict, event_type: str, reason: str,
                     message: str) -> tuple[str, dict]:
        meta = involved.get("metadata") or {}
        ns = meta.get("namespace", "default")
        # Nanosecond suffix like client-go: unique across operator restarts
        # and replicas, where a per-process counter would collide.
        n = time.time_ns()
        ev = {
            "metadata": {"name": f"{meta.get('name', 'unknown')}.{n:x}", "namespace": ns},
            "involvedObject": {
                "kind": involved.get("kind", ""),
                "namespace": ns,
                "name": meta.get("name", ""),
                "uid": meta.get("uid", ""),
                "apiVersion": involved.get("apiVersion", ""),
            },
            "reason": reason,
            "message": message,
            "type": event_type,
            "source": {"component": self.component},
            "firstTimestamp": now_rfc3339(),
            "lastTimestamp": now_rfc3339(),
            "count": 1,
        }
        return ns, ev

    def _post(self, involved: dict, event_type: str, reason: str,
              message: str):
        """Create the Event on the apiserver; returns the created object or
        None (failures are logged AND counted as drops, never raised — a
        send failure is a lost event, and 'drops counted, never raised'
        has no silent third outcome)."""
        ns, ev = self._build_event(involved, event_type, reason, message)
        try:
            return self.clientset.events(ns).create(ev)
        except Exception:
            flight.EVENTS.record_dropped()
            log.exception("failed to record event %s/%s", reason, message)
            return None

    def eventf(self, involved: dict, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(involved, event_type, reason, fmt % args if args else fmt)


class AsyncEventRecorder(EventRecorder):
    """EventRecorder that posts from a background sink thread — the
    client-go EventBroadcaster architecture (record.NewBroadcaster +
    StartRecordingToSink): recording an event is a buffered enqueue, never
    an API round-trip on the reconcile hot path.

    Measured motivation: under the 200-gang-job wire bench, synchronous
    event POSTs were ~9 of the ~27 HTTP requests per job *inside* the
    reconcile loop.

    The sink aggregates EXACT repeats — same involved object, type, reason
    AND message — by bumping ``count``/``lastTimestamp`` on the existing
    Event object (client-go EventLogger dedup semantics) instead of
    creating a new one.  Distinct messages are never merged: the e2e
    harness parses pod names out of messages, so cross-object aggregation
    (client-go's 10-similar-events aggregator) is deliberately not
    modeled.

    Overflow drops the newest event with a log line and a counter bump
    (``events_dropped_total``), exactly like client-go's full buffered
    channel.  ``flush()`` waits for the queue to drain (tests; controller
    shutdown).
    """

    QUEUE_SIZE = 4096
    # Aggregation cache: at most this many distinct (object, reason,
    # message) keys remembered, each for at most AGG_TTL_S since its first
    # post — bounded memory, and a key that went quiet re-creates fresh
    # (matching the apiserver's own event TTL behavior).
    AGG_MAX_KEYS = 1024
    AGG_TTL_S = 600.0

    def __init__(self, clientset: Clientset, component: str):
        super().__init__(clientset, component)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.QUEUE_SIZE)
        self._unfinished = 0
        self._closed = False
        self._cond = checkedlock.make_condition("record.queue")
        # touched only by the sink thread — no lock needed
        self._agg: "OrderedDict[tuple, dict]" = OrderedDict()
        self._thread = threading.Thread(
            target=self._sink, daemon=True, name=f"event-sink-{component}")
        self._thread.start()

    def event(self, involved: dict, event_type: str, reason: str, message: str) -> None:
        _timeline_event(involved, event_type, reason, message)
        try:
            with self._cond:
                if self._closed:
                    # late event after shutdown: still a drop, still
                    # counted — "drops counted, never raised" has no
                    # silent third outcome
                    flight.EVENTS.record_dropped()
                    return
                self._q.put_nowait((involved, event_type, reason, message))
                self._unfinished += 1
            flight.EVENTS.record_recorded()
        except queue.Full:
            flight.EVENTS.record_dropped()
            log.warning("event queue full; dropping %s %s", reason, message)

    def _sink(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._post_aggregated(*item)
            finally:
                with self._cond:
                    self._unfinished -= 1
                    self._cond.notify_all()

    def _agg_key(self, involved: dict, event_type: str, reason: str,
                 message: str) -> tuple:
        meta = involved.get("metadata") or {}
        return (meta.get("namespace", "default"), involved.get("kind", ""),
                meta.get("name", ""), meta.get("uid", ""),
                event_type, reason, message)

    def _post_aggregated(self, involved: dict, event_type: str, reason: str,
                         message: str) -> None:
        """One sink-side send: an exact repeat within the TTL bumps the
        existing Event's count/lastTimestamp via PATCH; anything else (or a
        failed bump — the event may have been GC'd) creates fresh."""
        key = self._agg_key(involved, event_type, reason, message)
        now = time.monotonic()
        ent = self._agg.get(key)
        if ent is not None and now - ent["t0"] <= self.AGG_TTL_S:
            try:
                self.clientset.events(ent["ns"]).patch(ent["name"], {
                    "count": ent["count"] + 1,
                    "lastTimestamp": now_rfc3339(),
                })
                ent["count"] += 1
                self._agg.move_to_end(key)
                flight.EVENTS.record_aggregated()
                return
            except Exception:  # noqa: BLE001 - event gone/GC'd: create fresh
                self._agg.pop(key, None)
        created = self._post(involved, event_type, reason, message)
        if created is not None:
            self._agg[key] = {
                "name": created["metadata"]["name"],
                "ns": created["metadata"].get("namespace", "default"),
                "count": 1,
                "t0": now,
            }
            self._agg.move_to_end(key)
            while len(self._agg) > self.AGG_MAX_KEYS:
                self._agg.popitem(last=False)

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every recorded event has been posted (or timeout)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._unfinished > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def close(self, timeout: float = 10.0) -> bool:
        """Drain, then terminate the sink thread.  Without this every
        recorder instance would leak its thread for process lifetime (a
        test suite builds controllers by the dozen)."""
        drained = self.flush(timeout)
        with self._cond:
            if self._closed:
                return drained
            self._closed = True
        try:
            # never block here: if flush timed out with the queue still
            # full (sink wedged on a dead apiserver), a blocking put would
            # hang shutdown indefinitely; the sink is a daemon thread and
            # event() drops everything once _closed is set
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=5)
        return drained


class FakeRecorder:
    """record.NewFakeRecorder equivalent: captures events in-memory."""

    def __init__(self):
        self.events: list[str] = []

    def event(self, involved: dict, event_type: str, reason: str, message: str) -> None:
        self.events.append(f"{event_type} {reason} {message}")

    def eventf(self, involved: dict, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(involved, event_type, reason, fmt % args if args else fmt)

"""Event recorder (k8s.io/client-go/tools/record equivalent).

K8s Events are load-bearing telemetry in this system: the e2e harness asserts
on pod/service create events (py/test_runner.py:301-332), so controllers must
record them faithfully (pkg/trainer/replicas.go:470-506,
pkg/controller.v2/service_control.go:96-112).
"""

from __future__ import annotations

import logging
import queue
import threading
import time

from k8s_tpu.api.meta import now_rfc3339
from k8s_tpu.client.clientset import Clientset

log = logging.getLogger(__name__)

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


class EventRecorder:
    """Records events attached to an involved object, apiserver-backed."""

    def __init__(self, clientset: Clientset, component: str):
        self.clientset = clientset
        self.component = component

    def event(self, involved: dict, event_type: str, reason: str, message: str) -> None:
        meta = involved.get("metadata") or {}
        ns = meta.get("namespace", "default")
        # Nanosecond suffix like client-go: unique across operator restarts
        # and replicas, where a per-process counter would collide.
        n = time.time_ns()
        ev = {
            "metadata": {"name": f"{meta.get('name', 'unknown')}.{n:x}", "namespace": ns},
            "involvedObject": {
                "kind": involved.get("kind", ""),
                "namespace": ns,
                "name": meta.get("name", ""),
                "uid": meta.get("uid", ""),
                "apiVersion": involved.get("apiVersion", ""),
            },
            "reason": reason,
            "message": message,
            "type": event_type,
            "source": {"component": self.component},
            "firstTimestamp": now_rfc3339(),
            "lastTimestamp": now_rfc3339(),
            "count": 1,
        }
        try:
            self.clientset.events(ns).create(ev)
        except Exception:
            log.exception("failed to record event %s/%s", reason, message)

    def eventf(self, involved: dict, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(involved, event_type, reason, fmt % args if args else fmt)


class AsyncEventRecorder(EventRecorder):
    """EventRecorder that posts from a background sink thread — the
    client-go EventBroadcaster architecture (record.NewBroadcaster +
    StartRecordingToSink): recording an event is a buffered enqueue, never
    an API round-trip on the reconcile hot path.

    Measured motivation: under the 200-gang-job wire bench, synchronous
    event POSTs were ~9 of the ~27 HTTP requests per job *inside* the
    reconcile loop.  Event content is unchanged (one event per message —
    the harness parses pod names out of messages, so no cross-object
    aggregation); only the posting moves off-thread.

    Overflow drops the newest event with a log line, exactly like
    client-go's full buffered channel.  ``flush()`` waits for the queue to
    drain (tests; controller shutdown).
    """

    QUEUE_SIZE = 4096

    def __init__(self, clientset: Clientset, component: str):
        super().__init__(clientset, component)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.QUEUE_SIZE)
        self._unfinished = 0
        self._closed = False
        self._cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._sink, daemon=True, name=f"event-sink-{component}")
        self._thread.start()

    def event(self, involved: dict, event_type: str, reason: str, message: str) -> None:
        try:
            with self._cond:
                if self._closed:
                    return
                self._q.put_nowait((involved, event_type, reason, message))
                self._unfinished += 1
        except queue.Full:
            log.warning("event queue full; dropping %s %s", reason, message)

    def _sink(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                super().event(*item)
            finally:
                with self._cond:
                    self._unfinished -= 1
                    self._cond.notify_all()

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every recorded event has been posted (or timeout)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._unfinished > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def close(self, timeout: float = 10.0) -> bool:
        """Drain, then terminate the sink thread.  Without this every
        recorder instance would leak its thread for process lifetime (a
        test suite builds controllers by the dozen)."""
        drained = self.flush(timeout)
        with self._cond:
            if self._closed:
                return drained
            self._closed = True
        try:
            # never block here: if flush timed out with the queue still
            # full (sink wedged on a dead apiserver), a blocking put would
            # hang shutdown indefinitely; the sink is a daemon thread and
            # event() drops everything once _closed is set
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=5)
        return drained


class FakeRecorder:
    """record.NewFakeRecorder equivalent: captures events in-memory."""

    def __init__(self):
        self.events: list[str] = []

    def event(self, involved: dict, event_type: str, reason: str, message: str) -> None:
        self.events.append(f"{event_type} {reason} {message}")

    def eventf(self, involved: dict, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(involved, event_type, reason, fmt % args if args else fmt)

"""Trace export: bounded in-memory ring buffer with JSON serialization.

The exporter is the /debug/traces data source — a deployed operator's
last-N interesting traces, queryable without any external collector.
Kept deliberately simple: finished root span trees are serialized to
plain dicts at export time (immutable snapshots — a served trace can
never be half-mutated by a live span) and stored FIFO; when the buffer
is full the oldest trace is evicted, including under concurrent
writers (one lock covers the append+evict pair).
"""

from __future__ import annotations

import json
from k8s_tpu.analysis import checkedlock
import urllib.parse
from collections import deque

DEFAULT_CAPACITY = 256


class RingBufferExporter:
    """Bounded FIFO of finished root span trees (as JSON-able dicts)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = checkedlock.make_lock("trace.export")
        self._traces: deque[dict] = deque(maxlen=capacity)
        self._exported = 0
        self._evicted = 0

    def export(self, root) -> None:
        """Store one finished root (a Span or an already-built dict).
        Serialization happens outside the lock; the append+evict pair is
        atomic under it, so eviction order stays FIFO no matter how many
        threads finish roots concurrently."""
        trace = root if isinstance(root, dict) else root.to_dict()
        with self._lock:
            if len(self._traces) == self._traces.maxlen:
                self._evicted += 1
            self._traces.append(trace)
            self._exported += 1

    def snapshot(self) -> list[dict]:
        """Oldest-first copy of the buffered traces."""
        with self._lock:
            return list(self._traces)

    def stats(self) -> dict:
        with self._lock:
            return {"buffered": len(self._traces),
                    "capacity": self.capacity,
                    "exported_total": self._exported,
                    "evicted_total": self._evicted}

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


def select_traces(traces: list[dict], limit: int = 50,
                  job: str | None = None) -> list[dict]:
    """The /debug/traces view: slowest-first, optionally filtered to roots
    whose ``job`` attribute matches (exact or substring — callers pass
    "ns/name" or just the name)."""
    if job:
        traces = [t for t in traces
                  if job in str((t.get("attributes") or {}).get("job", ""))]
    traces = sorted(traces, key=lambda t: -t.get("duration_ms", 0.0))
    return traces[:max(limit, 0)]


def debug_traces_response(tracer, query_string: str = "") -> tuple[int, str, str]:
    """(status, body, content_type) for a /debug/traces endpoint — shared
    by the metrics server and the dashboard backend so both speak the same
    contract.  Tracing off is a 404 with an explicit "tracing disabled"
    body (distinguishable from a route typo's bare 404).

    Query params: ``n`` (max traces, default 50), ``job`` (filter).
    """
    if not tracer.enabled:
        return (404,
                "tracing disabled: set K8S_TPU_TRACE_SAMPLE to a rate in "
                "(0, 1] to enable span export\n",
                "text/plain")
    q = urllib.parse.parse_qs(query_string or "")
    try:
        limit = int(q.get("n", ["50"])[0])
    except ValueError:
        limit = 50
    job = (q.get("job", [None])[0]) or None
    traces = select_traces(tracer.exporter.snapshot(), limit=limit, job=job)
    body = json.dumps({
        "traces": traces,
        "count": len(traces),
        "exporter": tracer.exporter.stats(),
        "sample_rate": tracer.sample_rate,
        "slow_threshold_ms": round(tracer.slow_threshold_s * 1e3, 3),
    })
    return 200, body, "application/json"

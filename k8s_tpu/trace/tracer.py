"""Tracing core: thread-safe spans with contextvar parenting.

Design goals (ISSUE 2 / SURVEY.md §5 observability):

- **Zero-cost when off.** ``K8S_TPU_TRACE_SAMPLE`` unset or 0 makes
  ``start_span`` return one shared no-op span — no allocation, no
  contextvar write — so the reconcile hot path pays one float compare.
- **Contextvar parenting.** The current span lives in a ``ContextVar``,
  so spans nest correctly across the reconcile thread pools from PR 1
  when tasks are wrapped with :func:`bind_current_context` (each task
  gets its own ``Context`` copy; a shared copy cannot be entered
  concurrently).
- **Head + tail sampling.** When tracing is on, every root is recorded
  and the keep decision happens at root finish: head-sampled (trace-id
  coin flip at rate ``K8S_TPU_TRACE_SAMPLE``), slower than
  ``K8S_TPU_TRACE_SLOW_MS`` (default 250), or any span in the tree
  errored.  p99 outliers and failures are therefore always captured even
  at a 1% head rate.

Stdlib-only by policy (enforced by ``harness/py_checks.py``): this
package is imported by the REST client and ops tooling, which must never
grow a third-party dependency through it.
"""

from __future__ import annotations

import contextvars
import os
import random
from k8s_tpu.analysis import checkedlock
import time
from typing import Optional

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "k8s_tpu_trace_span", default=None
)

DEFAULT_SLOW_THRESHOLD_S = 0.25


def _new_id(bits: int) -> str:
    """Random lowercase-hex id (W3C trace-context format: 128-bit trace
    ids, 64-bit span ids)."""
    return f"{random.getrandbits(bits):0{bits // 4}x}"


def _sample_rate_from_env() -> float:
    """K8S_TPU_TRACE_SAMPLE clamped to [0, 1]; garbage disables (the safe
    default for a knob that buys overhead)."""
    raw = os.environ.get("K8S_TPU_TRACE_SAMPLE", "")
    try:
        rate = float(raw)
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


def _slow_threshold_from_env() -> float:
    raw = os.environ.get("K8S_TPU_TRACE_SLOW_MS", "")
    try:
        ms = float(raw)
    except ValueError:
        return DEFAULT_SLOW_THRESHOLD_S
    return max(ms, 0.0) / 1000.0


class Span:
    """One timed operation in a trace tree.

    Context-manager use (``with tracer.start_span("sync"): ...``) sets the
    span current for its block so children parent to it; manual use
    (construct, then :meth:`finish`) records the span without making it
    current — the REST client's per-attempt spans work this way.  A span
    attaches itself to its parent at finish; a finished root hands its
    whole tree to the tracer for the keep/drop decision.
    """

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "head_sampled",
        "attributes", "events", "children", "status", "status_message",
        "start_wall", "start", "end", "_tracer", "_parent", "_token",
        "_lock",
    )

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional["Span"], trace_id: str,
                 head_sampled: bool, attributes: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id(64)
        self.parent_id = parent.span_id if parent is not None else None
        self.head_sampled = head_sampled
        self.attributes: dict = dict(attributes or {})
        self.events: list[dict] = []
        self.children: list[Span] = []
        self.status = "ok"
        self.status_message = ""
        self.start_wall = time.time()
        self.start = time.monotonic()
        self.end: Optional[float] = None
        self._tracer = tracer
        self._parent = parent
        self._token = None
        self._lock = checkedlock.make_lock("trace.span")

    # -- recording -----------------------------------------------------------

    @property
    def duration_s(self) -> float:
        end = self.end if self.end is not None else time.monotonic()
        return end - self.start

    def set_attribute(self, key: str, value) -> None:
        with self._lock:
            self.attributes[key] = value

    def add_event(self, name: str, **attributes) -> None:
        evt = {"name": name,
               "offset_ms": round((time.monotonic() - self.start) * 1e3, 3)}
        if attributes:
            evt["attributes"] = attributes
        with self._lock:
            self.events.append(evt)

    def set_error(self, exc_or_message) -> None:
        with self._lock:
            self.status = "error"
            if isinstance(exc_or_message, BaseException):
                self.status_message = (
                    f"{type(exc_or_message).__name__}: {exc_or_message}")
            else:
                self.status_message = str(exc_or_message)

    def has_error(self) -> bool:
        """True when this span or any descendant recorded an error."""
        with self._lock:
            if self.status == "error":
                return True
            children = list(self.children)
        return any(c.has_error() for c in children)

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "Span":
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.set_error(exc)
        self.finish()
        return False

    def finish(self) -> None:
        if self.end is not None:
            return  # idempotent
        self.end = time.monotonic()
        if self._token is not None:
            try:
                _current_span.reset(self._token)
            except ValueError:
                # finished from a different Context (executor task that
                # outlived its copy); the copy dies with the task anyway
                pass
            self._token = None
        if self._parent is not None:
            with self._parent._lock:
                self._parent.children.append(self)
        else:
            self._tracer._finish_root(self)

    # -- serialization -------------------------------------------------------

    def to_dict(self) -> dict:
        """JSON-able snapshot of the subtree rooted here."""
        with self._lock:
            attributes = dict(self.attributes)
            events = list(self.events)
            children = list(self.children)
            # status and status_message are written together under the
            # lock (set_error); snapshot them in the same critical
            # section so a concurrent set_error can't tear the pair
            status = self.status
            status_message = self.status_message
        out = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_unix": round(self.start_wall, 6),
            "duration_ms": round(self.duration_s * 1e3, 3),
            "status": status,
            "attributes": attributes,
            "events": events,
            "children": [c.to_dict() for c in
                         sorted(children, key=lambda c: c.start)],
        }
        if status_message:
            out["status_message"] = status_message
        return out


class _NoopSpan:
    """Shared do-nothing span returned whenever tracing is off."""

    trace_id = None
    span_id = None
    parent_id = None
    head_sampled = False
    status = "ok"
    duration_s = 0.0
    attributes: dict = {}
    events: list = []
    children: list = []

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attribute(self, key, value) -> None:
        pass

    def add_event(self, name, **attributes) -> None:
        pass

    def set_error(self, exc_or_message) -> None:
        pass

    def finish(self) -> None:
        pass

    def has_error(self) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Span factory + sampling policy + exporter binding (thread-safe)."""

    def __init__(self, sample_rate: Optional[float] = None,
                 slow_threshold_s: Optional[float] = None, exporter=None):
        from k8s_tpu.trace.export import RingBufferExporter

        self.exporter = exporter if exporter is not None else RingBufferExporter()
        self.sample_rate = (_sample_rate_from_env()
                            if sample_rate is None else sample_rate)
        self.slow_threshold_s = (_slow_threshold_from_env()
                                 if slow_threshold_s is None
                                 else slow_threshold_s)

    def configure(self, sample_rate: Optional[float] = None,
                  slow_threshold_s: Optional[float] = None,
                  exporter=None) -> "Tracer":
        """Re-apply settings; None re-reads the environment (so a test or
        binary that just set ``K8S_TPU_TRACE_SAMPLE`` can pick it up on an
        already-imported module)."""
        self.sample_rate = (_sample_rate_from_env()
                            if sample_rate is None else
                            min(max(sample_rate, 0.0), 1.0))
        self.slow_threshold_s = (_slow_threshold_from_env()
                                 if slow_threshold_s is None
                                 else slow_threshold_s)
        if exporter is not None:
            self.exporter = exporter
        return self

    @property
    def enabled(self) -> bool:
        return self.sample_rate > 0.0

    def start_span(self, name: str, **attributes):
        """A child of the current span, or a new root.  Enter it (``with``)
        to make it current for its block; an un-entered span still records
        and attaches to its construction-time parent on finish()."""
        if not self.enabled:
            return NOOP_SPAN
        parent = _current_span.get()
        if parent is None or parent is NOOP_SPAN:
            return Span(self, name, None, _new_id(128),
                        random.random() < self.sample_rate, attributes)
        return Span(self, name, parent, parent.trace_id,
                    parent.head_sampled, attributes)

    def start_span_under(self, parent_ctx, name: str, **attributes):
        """A span explicitly parented under a REMOTE context —
        ``parent_ctx`` is the ``(trace_id, span_id, sampled)`` tuple
        :func:`k8s_tpu.trace.parse_traceparent` returns (the serving
        ingress's inbound W3C header, or a server span handed across
        threads to the engine).  ``None`` falls back to
        :meth:`start_span`, so call sites need no branching.

        The span joins the remote TRACE (same trace_id, parent_id = the
        remote span id) but is a local root: it finishes through the
        tail-based keep decision with the inbound sampled flag as its
        head-sampling vote, so a sampled upstream keeps the local
        subtree and an unsampled one still keeps slow/errored spans."""
        if parent_ctx is None:
            return self.start_span(name, **attributes)
        if not self.enabled:
            return NOOP_SPAN
        trace_id, parent_span_id, sampled = parent_ctx
        parent = _current_span.get()
        if parent is not None and parent is not NOOP_SPAN \
                and parent.trace_id == trace_id:
            # already inside the same trace (the handler thread's server
            # span): nest normally instead of forking a second root
            return Span(self, name, parent, trace_id,
                        parent.head_sampled, attributes)
        span = Span(self, name, None, trace_id, bool(sampled), attributes)
        span.parent_id = parent_span_id
        return span

    def record_span(self, name: str, duration_s: float, **attributes):
        """Record an already-elapsed interval ending now as a child of the
        current span (e.g. the workqueue wait that preceded a sync).
        Returns the span, or None when tracing is off / no span is
        current — a parentless retroactive interval is not a trace."""
        if not self.enabled:
            return None
        parent = _current_span.get()
        if parent is None or parent is NOOP_SPAN:
            return None
        span = Span(self, name, parent, parent.trace_id,
                    parent.head_sampled, attributes)
        span.start -= duration_s
        span.start_wall -= duration_s
        span.finish()
        return span

    def _finish_root(self, root: Span) -> None:
        """Tail-based keep decision: head-sampled, slow, or errored."""
        if (root.head_sampled
                or root.duration_s >= self.slow_threshold_s
                or root.has_error()):
            self.exporter.export(root)


def current_span():
    """The active span, or None (never the no-op span)."""
    span = _current_span.get()
    return None if span is None or span is NOOP_SPAN else span


def current_trace_id() -> Optional[str]:
    span = current_span()
    return span.trace_id if span is not None else None


def bind_current_context(fn):
    """Wrap ``fn`` so it runs under a *copy* of the calling context —
    the bridge that carries span parenting onto ThreadPoolExecutor tasks.
    Each call copies its own Context: one Context object cannot be entered
    by two tasks concurrently, so bind once per submitted task."""
    ctx = contextvars.copy_context()

    def _bound(*args, **kwargs):
        return ctx.run(fn, *args, **kwargs)

    return _bound

"""W3C trace-context propagation (https://www.w3.org/TR/trace-context/).

Only the ``traceparent`` header is implemented — the piece that lets an
apiserver audit log line or kubelet log be joined back to the operator
span that caused it.  ``tracestate`` is deliberately omitted (nothing in
this control plane consumes it).
"""

from __future__ import annotations

import re
from typing import Optional

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    """``00-<trace-id>-<parent-span-id>-<flags>`` (version 00)."""
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def parse_traceparent(header: Optional[str]):
    """(trace_id, span_id, sampled) or None for anything malformed.

    Per spec: version ff is invalid, as are all-zero trace/span ids.
    Uppercase hex is rejected (the spec requires lowercase on the wire).
    """
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 0x01)

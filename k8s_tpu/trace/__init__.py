"""End-to-end reconcile tracing (ISSUE 2; the per-stage attribution half
of the observability story — metrics answer "how slow", spans answer
"*where* did this 300ms sync go").

Module-level convenience API over one process-wide :class:`Tracer`:

    from k8s_tpu import trace

    with trace.span("sync_tfjob", job=key):
        trace.record_span("queue_wait", wait_s)   # retroactive child
        ...

Sampling knobs (read at import; ``trace.configure()`` re-reads):

- ``K8S_TPU_TRACE_SAMPLE``  — head sample rate in [0, 1]; 0/unset = off.
- ``K8S_TPU_TRACE_SLOW_MS`` — tail keep-if-slow threshold (default 250);
  slow or errored traces are always kept once tracing is on.

This package is stdlib-only by policy (``harness/py_checks.py`` gates it):
the REST client imports it on the request hot path.
"""

from __future__ import annotations

from typing import Optional

from k8s_tpu.trace.export import (  # noqa: F401 (public surface)
    RingBufferExporter,
    debug_traces_response,
    select_traces,
)
from k8s_tpu.trace.propagation import (  # noqa: F401
    format_traceparent,
    parse_traceparent,
)
from k8s_tpu.trace.tracer import (  # noqa: F401
    NOOP_SPAN,
    Span,
    Tracer,
    bind_current_context,
    current_span,
    current_trace_id,
)

# The process-wide tracer every instrumentation site records through
# (operator binaries inherit env config; tests call configure()).
TRACER = Tracer()


def configure(sample_rate: Optional[float] = None,
              slow_threshold_s: Optional[float] = None,
              exporter=None) -> Tracer:
    """Reconfigure the global tracer; None args re-read the environment."""
    return TRACER.configure(sample_rate=sample_rate,
                            slow_threshold_s=slow_threshold_s,
                            exporter=exporter)


def enabled() -> bool:
    return TRACER.enabled


def span(name: str, **attributes):
    """Start a span on the global tracer (context manager)."""
    return TRACER.start_span(name, **attributes)


def record_span(name: str, duration_s: float, **attributes):
    """Retroactive child of the current span (interval ending now)."""
    return TRACER.record_span(name, duration_s, **attributes)


def span_under(parent_ctx, name: str, **attributes):
    """A span under an explicit remote ``(trace_id, span_id, sampled)``
    context (the :func:`parse_traceparent` shape) — how the serving
    ingress parents the engine's prefill/decode/exclusive spans under an
    inbound W3C ``traceparent`` across threads and processes.  ``None``
    falls back to :func:`span`."""
    return TRACER.start_span_under(parent_ctx, name, **attributes)


def span_context(span) -> Optional[tuple]:
    """The ``(trace_id, span_id, sampled)`` tuple of a live span, or
    None for a no-op/absent span — the hand-off shape for parenting
    work on another thread under it."""
    if span is None or getattr(span, "trace_id", None) is None:
        return None
    return span.trace_id, span.span_id, span.head_sampled


def current_traceparent() -> Optional[str]:
    """W3C traceparent for the current span, or None."""
    sp = current_span()
    if sp is None:
        return None
    return format_traceparent(sp.trace_id, sp.span_id, sp.head_sampled)


def debug_traces(limit: int = 50, job: Optional[str] = None) -> list[dict]:
    """Buffered traces, slowest-first (the /debug/traces view)."""
    return select_traces(TRACER.exporter.snapshot(), limit=limit, job=job)

// Concurrency stress harness for the native runtime — the `go test -race`
// analogue SURVEY.md §5 calls for (the reference leaned on Go's race
// detector; CI here builds this twice: plain, and with -fsanitize=thread).
//
// Invariants hammered:
//  - workqueue: one key is NEVER processed by two workers concurrently
//    (client-go's core guarantee, pkg/controller/controller.go:77-95), every
//    produced key is eventually processed, and the queue drains to empty.
//  - expectations: balanced expect/observe from many threads always ends
//    satisfied, never lost-update into a stuck unsatisfied record.
//
// Exits 0 on success; asserts (SIGABRT) on an invariant violation; under
// TSan, any data race fails the run via halt_on_error=1.

#include "runtime.cc"

#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <thread>

namespace {

constexpr int kKeys = 16;
constexpr int kProducers = 4;
constexpr int kWorkers = 6;
constexpr int kOpsPerProducer = 400;

std::string key_name(int k) { return "ns/job-" + std::to_string(k); }

// xorshift per-thread PRNG (rand() is not thread-safe)
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed * 2654435769u + 1) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
  int below(int n) { return static_cast<int>(next() % n); }
};

void stress_workqueue() {
  RateLimitingQueue q(0.0005, 0.05, 1e6, 1e6);
  std::atomic<int> in_flight[kKeys];
  std::atomic<long> processed[kKeys];
  for (int i = 0; i < kKeys; i++) {
    in_flight[i].store(0);
    processed[i].store(0);
  }
  auto producer = [&](int id) {
    Rng rng(id + 1);
    for (int i = 0; i < kOpsPerProducer; i++) {
      int k = rng.below(kKeys);
      switch (rng.below(3)) {
        case 0: q.add(key_name(k)); break;
        case 1: q.add_rate_limited(key_name(k)); break;
        default: q.add_after(key_name(k), 0.0002 * rng.below(10)); break;
      }
      if (rng.below(7) == 0) q.forget(key_name(k));
      if (rng.below(50) == 0)
        std::this_thread::sleep_for(std::chrono::microseconds(100));
    }
  };

  auto worker = [&] {
    char buf[256];
    Rng rng(reinterpret_cast<uintptr_t>(&buf));
    for (;;) {
      int rc = q.get(0.2, buf, sizeof(buf));
      if (rc == -1) return;  // shutdown
      if (rc == 0) continue; // timeout — recheck shutdown via next get
      std::string item(buf);
      int k = std::atoi(item.c_str() + item.rfind('-') + 1);
      assert(k >= 0 && k < kKeys);
      // THE invariant: nobody else is processing this key right now
      int was = in_flight[k].fetch_add(1);
      assert(was == 0 && "key processed by two workers concurrently");
      if (rng.below(4) == 0)
        std::this_thread::sleep_for(std::chrono::microseconds(rng.below(300)));
      processed[k].fetch_add(1);
      in_flight[k].fetch_sub(1);
      q.done(item);
    }
  };

  std::vector<std::thread> threads;
  for (int i = 0; i < kWorkers; i++) threads.emplace_back(worker);
  std::vector<std::thread> producers;
  for (int i = 0; i < kProducers; i++) producers.emplace_back(producer, i);
  for (auto& t : producers) t.join();

  // drain: every key added at least once must eventually be processed, and
  // the queue (incl. the delay heap, max delay 50ms) must empty out
  double deadline = now_s() + 10.0;
  for (;;) {
    bool done = q.size() == 0;
    {
      std::lock_guard<std::mutex> l(q.mu);
      done = done && q.heap.empty() && q.processing.empty() && q.queue.empty();
    }
    if (done) break;
    assert(now_s() < deadline && "queue failed to drain");
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  q.shut_down();
  for (auto& t : threads) t.join();

  long total = 0;
  for (int i = 0; i < kKeys; i++) {
    assert(processed[i].load() > 0 && "key never processed");
    total += processed[i].load();
  }
  std::printf("workqueue stress OK: %ld processings over %d keys\n", total, kKeys);
}

void stress_expectations() {
  ControllerExpectations exp(300.0);
  constexpr int kThreads = 8;
  constexpr int kRounds = 300;

  auto hammer = [&](int id) {
    Rng rng(id + 101);
    for (int r = 0; r < kRounds; r++) {
      std::string key = key_name(rng.below(kKeys));
      int n = 1 + rng.below(4);
      exp.expect(key, n, 0);
      for (int i = 0; i < n; i++) exp.lower(key, -1, 0);
      int d = 1 + rng.below(3);
      exp.expect(key, 0, d);
      for (int i = 0; i < d; i++) exp.lower(key, 0, -1);
      exp.satisfied(key);  // concurrent reads race against the writers
    }
  };

  std::vector<std::thread> threads;
  for (int i = 0; i < kThreads; i++) threads.emplace_back(hammer, i);
  for (auto& t : threads) t.join();

  // balanced expect/observe must end satisfied for every key
  for (int k = 0; k < kKeys; k++) {
    assert(exp.satisfied(key_name(k)) && "balanced expectations unsatisfied");
  }
  std::printf("expectations stress OK: %d threads x %d rounds\n", kThreads, kRounds);
}

}  // namespace

int main() {
  stress_workqueue();
  stress_expectations();
  std::printf("native concurrency stress PASS\n");
  return 0;
}

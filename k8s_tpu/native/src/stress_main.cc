// Concurrency stress harness for the native runtime — the `go test -race`
// analogue SURVEY.md §5 calls for (the reference leaned on Go's race
// detector; CI here builds this twice: plain, and with -fsanitize=thread).
//
// Invariants hammered:
//  - workqueue: one key is NEVER processed by two workers concurrently
//    (client-go's core guarantee, pkg/controller/controller.go:77-95), every
//    produced key is eventually processed, and the queue drains to empty.
//  - expectations: balanced expect/observe from many threads always ends
//    satisfied, never lost-update into a stuck unsatisfied record.
//
// Exits 0 on success; asserts (SIGABRT) on an invariant violation; under
// TSan, any data race fails the run via halt_on_error=1.

#include "runtime.cc"
#include "dataloader.cc"

#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <thread>

namespace {

constexpr int kKeys = 16;
constexpr int kProducers = 4;
constexpr int kWorkers = 6;
constexpr int kOpsPerProducer = 400;

std::string key_name(int k) { return "ns/job-" + std::to_string(k); }

// xorshift per-thread PRNG (rand() is not thread-safe)
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed * 2654435769u + 1) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
  int below(int n) { return static_cast<int>(next() % n); }
};

void stress_workqueue() {
  RateLimitingQueue q(0.0005, 0.05, 1e6, 1e6);
  std::atomic<int> in_flight[kKeys];
  std::atomic<long> processed[kKeys];
  for (int i = 0; i < kKeys; i++) {
    in_flight[i].store(0);
    processed[i].store(0);
  }
  auto producer = [&](int id) {
    Rng rng(id + 1);
    for (int i = 0; i < kOpsPerProducer; i++) {
      int k = rng.below(kKeys);
      switch (rng.below(3)) {
        case 0: q.add(key_name(k)); break;
        case 1: q.add_rate_limited(key_name(k)); break;
        default: q.add_after(key_name(k), 0.0002 * rng.below(10)); break;
      }
      if (rng.below(7) == 0) q.forget(key_name(k));
      if (rng.below(50) == 0)
        std::this_thread::sleep_for(std::chrono::microseconds(100));
    }
  };

  auto worker = [&] {
    char buf[256];
    Rng rng(reinterpret_cast<uintptr_t>(&buf));
    for (;;) {
      int rc = q.get(0.2, buf, sizeof(buf));
      if (rc == -1) return;  // shutdown
      if (rc == 0) continue; // timeout — recheck shutdown via next get
      std::string item(buf);
      int k = std::atoi(item.c_str() + item.rfind('-') + 1);
      assert(k >= 0 && k < kKeys);
      // THE invariant: nobody else is processing this key right now
      int was = in_flight[k].fetch_add(1);
      assert(was == 0 && "key processed by two workers concurrently");
      if (rng.below(4) == 0)
        std::this_thread::sleep_for(std::chrono::microseconds(rng.below(300)));
      processed[k].fetch_add(1);
      in_flight[k].fetch_sub(1);
      q.done(item);
    }
  };

  std::vector<std::thread> threads;
  for (int i = 0; i < kWorkers; i++) threads.emplace_back(worker);
  std::vector<std::thread> producers;
  for (int i = 0; i < kProducers; i++) producers.emplace_back(producer, i);
  for (auto& t : producers) t.join();

  // drain: every key added at least once must eventually be processed, and
  // the queue (incl. the delay heap, max delay 50ms) must empty out
  double deadline = now_s() + 10.0;
  for (;;) {
    bool done = q.size() == 0;
    {
      std::lock_guard<std::mutex> l(q.mu);
      done = done && q.heap.empty() && q.processing.empty() && q.queue.empty();
    }
    if (done) break;
    assert(now_s() < deadline && "queue failed to drain");
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  q.shut_down();
  for (auto& t : threads) t.join();

  long total = 0;
  for (int i = 0; i < kKeys; i++) {
    assert(processed[i].load() > 0 && "key never processed");
    total += processed[i].load();
  }
  std::printf("workqueue stress OK: %ld processings over %d keys\n", total, kKeys);
}

void stress_expectations() {
  ControllerExpectations exp(300.0);
  constexpr int kThreads = 8;
  constexpr int kRounds = 300;

  auto hammer = [&](int id) {
    Rng rng(id + 101);
    for (int r = 0; r < kRounds; r++) {
      std::string key = key_name(rng.below(kKeys));
      int n = 1 + rng.below(4);
      exp.expect(key, n, 0);
      for (int i = 0; i < n; i++) exp.lower(key, -1, 0);
      int d = 1 + rng.below(3);
      exp.expect(key, 0, d);
      for (int i = 0; i < d; i++) exp.lower(key, 0, -1);
      exp.satisfied(key);  // concurrent reads race against the writers
    }
  };

  std::vector<std::thread> threads;
  for (int i = 0; i < kThreads; i++) threads.emplace_back(hammer, i);
  for (auto& t : threads) t.join();

  // balanced expect/observe must end satisfied for every key
  for (int k = 0; k < kKeys; k++) {
    assert(exp.satisfied(key_name(k)) && "balanced expectations unsatisfied");
  }
  std::printf("expectations stress OK: %d threads x %d rounds\n", kThreads, kRounds);
}

void stress_dataloader() {
  // One file of sequential uint32 values; windows submitted from one
  // thread while this thread consumes — ordering and content must hold
  // under races between readers, submitter, and consumer.
  constexpr int kValues = 1 << 16;
  constexpr int kWindow = 256;            // values per window
  constexpr int kWindowBytes = kWindow * 4;
  char path[] = "/tmp/k8stpu_dl_stress_XXXXXX";
  int fd = mkstemp(path);
  assert(fd >= 0);
  {
    std::vector<uint32_t> vals(kValues);
    for (int i = 0; i < kValues; i++) vals[i] = (uint32_t)i;
    ssize_t n = write(fd, vals.data(), vals.size() * 4);
    assert(n == (ssize_t)(vals.size() * 4));
  }
  close(fd);

  void* h = dl_new(/*n_slots=*/8, kWindowBytes, /*n_threads=*/3);
  assert(h != nullptr);
  int fid = dl_register_file(h, path);
  assert(fid == 0);

  constexpr int kWindows = kValues / kWindow;
  std::thread submitter([&] {
    for (int w = 0; w < kWindows; w++) {
      for (;;) {
        int rc = dl_submit(h, fid, (uint64_t)w * kWindowBytes, kWindowBytes);
        assert(rc >= 0);
        if (rc == 1) break;
        std::this_thread::yield();  // ring full: consumer will drain
      }
    }
  });

  std::vector<char> buf(kWindowBytes);
  int consumed = 0;
  while (consumed < kWindows) {
    int64_t n = dl_next(h, buf.data(), kWindowBytes, 5000);
    if (n == -2) {  // nothing in flight yet
      std::this_thread::yield();
      continue;
    }
    assert(n == kWindowBytes);
    const uint32_t* vals = reinterpret_cast<const uint32_t*>(buf.data());
    for (int i = 0; i < kWindow; i++) {
      assert(vals[i] == (uint32_t)(consumed * kWindow + i));  // in order
    }
    consumed++;
  }
  assert(dl_error(h) == 0);
  assert(dl_inflight(h) == 0);
  submitter.join();
  dl_free(h);
  unlink(path);
  std::printf("dataloader stress OK: %d ordered windows x 3 reader threads\n",
              kWindows);
}

}  // namespace

int main() {
  stress_workqueue();
  stress_expectations();
  stress_dataloader();
  std::printf("native concurrency stress PASS\n");
  return 0;
}

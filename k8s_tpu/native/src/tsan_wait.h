// TSan-safe condition-variable timed waits.
//
// libstdc++ on glibc >= 2.30 implements condition_variable::wait_for (and
// steady-clock wait_until) with pthread_cond_clockwait, which gcc's libtsan
// does not intercept (GCC PR sanitizer/98712).  TSan then misses the unlock
// performed inside the wait and reports a spurious "double lock of a mutex"
// when the wait re-acquires — which is exactly what the stress harness's
// gating `go test -race` analogue would trip over on every run.  Under
// -fsanitize=thread we therefore route timed waits through a system_clock
// wait_until, whose pthread_cond_timedwait path IS intercepted.  The only
// behavioural difference — sensitivity to wall-clock steps during the wait —
// is confined to sanitizer builds.

#ifndef K8S_TPU_NATIVE_TSAN_WAIT_H_
#define K8S_TPU_NATIVE_TSAN_WAIT_H_

#include <chrono>
#include <condition_variable>
#include <mutex>

template <class Rep, class Period>
inline std::cv_status tsan_safe_wait_for(
    std::condition_variable& cv, std::unique_lock<std::mutex>& lock,
    const std::chrono::duration<Rep, Period>& dur) {
#if defined(__SANITIZE_THREAD__)
  return cv.wait_until(
      lock, std::chrono::system_clock::now() +
                std::chrono::duration_cast<std::chrono::system_clock::duration>(dur));
#else
  return cv.wait_for(lock, dur);
#endif
}

template <class Rep, class Period, class Pred>
inline bool tsan_safe_wait_for(std::condition_variable& cv,
                               std::unique_lock<std::mutex>& lock,
                               const std::chrono::duration<Rep, Period>& dur,
                               Pred pred) {
#if defined(__SANITIZE_THREAD__)
  return cv.wait_until(
      lock,
      std::chrono::system_clock::now() +
          std::chrono::duration_cast<std::chrono::system_clock::duration>(dur),
      pred);
#else
  return cv.wait_for(lock, dur, pred);
#endif
}

#endif  // K8S_TPU_NATIVE_TSAN_WAIT_H_

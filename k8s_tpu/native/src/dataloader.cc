// Native data loader: threaded pread() over registered files with an
// ordered slot ring.
//
// Role: the input-pipeline stage of the runtime (the reference delegates
// this to TensorFlow's C++ tf.data machinery; here the Python token-shard
// dataset (k8s_tpu/models/dataset.py) submits (file, offset, length)
// window descriptors and consumes them in submission order).  Python's
// mmap path page-faults while HOLDING the GIL, so a training step and its
// input pipeline serialize; these reads happen on C++ threads with no GIL
// anywhere near them.
//
// Ordering contract: windows are delivered in submission order.  The
// caller bounds in-flight submissions to the slot count (dl_submit returns
// 0 when the ring is full), which guarantees slot seq % n_slots is free by
// the time its descriptor is admitted.
//
// Plain C ABI over ctypes, matching runtime.cc (no pybind11 in the image).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

#include "tsan_wait.h"

namespace {

struct Desc {
  uint64_t seq;
  int file_id;
  uint64_t offset;
  uint64_t nbytes;
};

struct Slot {
  std::vector<char> buf;
  uint64_t nbytes = 0;
  // 0 = empty, 1 = ready
  std::atomic<int> ready{0};
};

struct Loader {
  std::mutex mu;
  std::condition_variable work_cv;   // readers wait for descriptors
  std::condition_variable ready_cv;  // consumer waits for its slot
  std::vector<int> fds;
  std::vector<Slot> slots;
  std::deque<Desc> pending;
  std::vector<std::thread> threads;
  uint64_t submit_seq = 0;
  uint64_t consume_seq = 0;
  bool stopping = false;
  std::atomic<bool> error{false};

  explicit Loader(int n_slots, uint64_t max_item_bytes, int n_threads)
      : slots(n_slots) {
    for (auto& s : slots) s.buf.resize(max_item_bytes);
    for (int i = 0; i < n_threads; i++) {
      threads.emplace_back([this] { this->reader_loop(); });
    }
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lock(mu);
      stopping = true;
    }
    work_cv.notify_all();
    ready_cv.notify_all();
    for (auto& t : threads) t.join();
    for (int fd : fds) {
      if (fd >= 0) ::close(fd);
    }
  }

  void reader_loop() {
    for (;;) {
      Desc d;
      int fd = -1;
      {
        std::unique_lock<std::mutex> lock(mu);
        work_cv.wait(lock, [this] { return stopping || !pending.empty(); });
        if (stopping) return;
        d = pending.front();
        pending.pop_front();
        // copy the fd under mu: dl_register_file may reallocate the vector
        if (d.file_id >= 0 && d.file_id < (int)fds.size()) fd = fds[d.file_id];
      }
      Slot& slot = slots[d.seq % slots.size()];
      uint64_t got = 0;
      if (fd < 0 || d.nbytes > slot.buf.size()) {
        error.store(true);
      } else {
        while (got < d.nbytes) {
          ssize_t n = ::pread(fd, slot.buf.data() + got, d.nbytes - got,
                              (off_t)(d.offset + got));
          if (n <= 0) {  // EOF mid-window or IO error: poison the loader
            error.store(true);
            break;
          }
          got += (uint64_t)n;
        }
      }
      slot.nbytes = got;
      {
        // publish under mu: a lock-free store+notify can slip between the
        // consumer's predicate check and its block (lost wakeup), stalling
        // dl_next for its whole timeout
        std::lock_guard<std::mutex> lock(mu);
        slot.ready.store(1, std::memory_order_release);
      }
      ready_cv.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* dl_new(int n_slots, uint64_t max_item_bytes, int n_threads) {
  if (n_slots < 1 || max_item_bytes == 0 || n_threads < 1) return nullptr;
  return new Loader(n_slots, max_item_bytes, n_threads);
}

void dl_free(void* h) { delete static_cast<Loader*>(h); }

// Returns a file id, or -1 on open failure.
int dl_register_file(void* h, const char* path) {
  Loader* L = static_cast<Loader*>(h);
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  std::lock_guard<std::mutex> lock(L->mu);
  L->fds.push_back(fd);
  return (int)L->fds.size() - 1;
}

// Returns 1 when accepted, 0 when the ring is full (caller must consume
// first), -1 when the loader is stopped/poisoned.
int dl_submit(void* h, int file_id, uint64_t offset, uint64_t nbytes) {
  Loader* L = static_cast<Loader*>(h);
  if (L->error.load()) return -1;
  {
    std::lock_guard<std::mutex> lock(L->mu);
    if (L->stopping) return -1;
    if (L->submit_seq - L->consume_seq >= L->slots.size()) return 0;
    L->pending.push_back(Desc{L->submit_seq, file_id, offset, nbytes});
    L->submit_seq++;
  }
  L->work_cv.notify_one();
  return 1;
}

// Copies the next window (submission order) into out.  Returns the byte
// count, 0 on timeout, -1 on error/stop, -2 when nothing is in flight.
int64_t dl_next(void* h, char* out, uint64_t out_cap, int timeout_ms) {
  Loader* L = static_cast<Loader*>(h);
  uint64_t seq;
  {
    std::lock_guard<std::mutex> lock(L->mu);
    if (L->consume_seq == L->submit_seq) return -2;
    seq = L->consume_seq;
  }
  Slot& slot = L->slots[seq % L->slots.size()];
  {
    std::unique_lock<std::mutex> lock(L->mu);
    bool ok = tsan_safe_wait_for(
        L->ready_cv, lock, std::chrono::milliseconds(timeout_ms), [&] {
          return L->stopping || L->error.load() ||
                 slot.ready.load(std::memory_order_acquire) != 0;
        });
    if (!ok) return 0;  // timeout
    if (L->stopping) return -1;
  }
  // Any read failure poisons the whole loader: a training input stream
  // with a silently skipped or truncated window is worse than a crash.
  if (L->error.load()) return -1;
  uint64_t n = slot.nbytes;
  if (n > out_cap) return -1;
  std::memcpy(out, slot.buf.data(), n);
  slot.ready.store(0, std::memory_order_release);
  {
    std::lock_guard<std::mutex> lock(L->mu);
    L->consume_seq++;
  }
  return (int64_t)n;
}

int dl_error(void* h) { return static_cast<Loader*>(h)->error.load() ? 1 : 0; }

uint64_t dl_inflight(void* h) {
  Loader* L = static_cast<Loader*>(h);
  std::lock_guard<std::mutex> lock(L->mu);
  return L->submit_seq - L->consume_seq;
}

}  // extern "C"

// Native runtime core for the TPU job operator.
//
// The reference operator's hot loop is Go: client-go's rate-limiting
// workqueue and the controller expectations cache
// (pkg/controller/controller.go:122-126, pkg/controller.v2/controller.go
// via k8s.io/kubernetes/pkg/controller).  This file is the compiled
// equivalent for the Python control plane: the same semantics, C++ under a
// C ABI consumed over ctypes (k8s_tpu/native/__init__.py), selected by the
// controllers when built.
//
// Semantics mirrored 1:1 from k8s_tpu/util/workqueue.py and
// k8s_tpu/controller_v2/expectations.py (which mirror client-go):
//  - dirty/processing dedup: one key is never handled by two workers; an add
//    during processing re-queues after done().
//  - per-item exponential backoff (base*2^n, capped) max'd with a global
//    token bucket (qps/burst).
//  - delayed items sit in a min-heap drained by get() — no timer thread.
//  - expectations: TTL'd {adds,dels} counters per key; accumulate while
//    pending (see expectations.py expect_creations docstring).

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "tsan_wait.h"

using Clock = std::chrono::steady_clock;

static double now_s() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch()).count();
}

// ---------------------------------------------------------------- limiters

struct ItemExponentialLimiter {
  double base_delay;
  double max_delay;
  std::unordered_map<std::string, int> failures;

  double when(const std::string& item) {
    int f = failures[item]++;
    if (f > 64) f = 64;
    double d = base_delay * static_cast<double>(1ULL << (f > 62 ? 62 : f));
    if (f > 62 || d > max_delay) d = max_delay;
    return d < max_delay ? d : max_delay;
  }
  void forget(const std::string& item) { failures.erase(item); }
  int num_requeues(const std::string& item) {
    auto it = failures.find(item);
    return it == failures.end() ? 0 : it->second;
  }
};

struct BucketLimiter {
  double qps;
  double burst;
  double tokens;
  double last;

  BucketLimiter(double q, double b) : qps(q), burst(b), tokens(b), last(now_s()) {}

  double when() {
    double now = now_s();
    tokens = std::min(burst, tokens + (now - last) * qps);
    last = now;
    tokens -= 1.0;
    if (tokens >= 0) return 0.0;
    return -tokens / qps;
  }
};

// ---------------------------------------------------------------- workqueue

struct DelayedItem {
  double when;
  long seq;
  std::string item;
  bool operator>(const DelayedItem& o) const {
    return when != o.when ? when > o.when : seq > o.seq;
  }
};

struct RateLimitingQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::string> queue;
  std::unordered_set<std::string> dirty;
  std::unordered_set<std::string> processing;
  std::priority_queue<DelayedItem, std::vector<DelayedItem>, std::greater<DelayedItem>> heap;
  long seq = 0;
  bool shutting_down = false;

  ItemExponentialLimiter item_limiter;
  BucketLimiter bucket;

  RateLimitingQueue(double base_delay, double max_delay, double qps, double burst)
      : item_limiter{base_delay, max_delay}, bucket(qps, burst) {}

  // requires mu held
  void add_locked(const std::string& item) {
    if (shutting_down || dirty.count(item)) return;
    dirty.insert(item);
    if (!processing.count(item)) {
      queue.push_back(item);
      cv.notify_one();
    }
  }

  // requires mu held: move expired heap entries into the queue
  void drain_heap_locked() {
    double now = now_s();
    while (!heap.empty() && heap.top().when <= now) {
      std::string item = heap.top().item;
      heap.pop();
      add_locked(item);
    }
  }

  void add(const std::string& item) {
    std::lock_guard<std::mutex> l(mu);
    add_locked(item);
  }

  void add_after(const std::string& item, double delay) {
    std::lock_guard<std::mutex> l(mu);
    if (shutting_down) return;
    if (delay <= 0) {
      add_locked(item);
      return;
    }
    heap.push({now_s() + delay, seq++, item});
    cv.notify_one();  // a waiter may need to shorten its sleep
  }

  void add_rate_limited(const std::string& item) {
    std::lock_guard<std::mutex> l(mu);
    if (shutting_down) return;
    double d = item_limiter.when(item);
    double b = bucket.when();
    if (b > d) d = b;
    if (d <= 0) {
      add_locked(item);
      return;
    }
    heap.push({now_s() + d, seq++, item});
    cv.notify_one();
  }

  // returns 1=item written to out, 0=timeout, -1=shutdown
  int get(double timeout_s, char* out, int out_len) {
    std::unique_lock<std::mutex> l(mu);
    bool has_deadline = timeout_s >= 0;
    double deadline = has_deadline ? now_s() + timeout_s : 0;
    for (;;) {
      drain_heap_locked();
      if (!queue.empty()) break;
      if (shutting_down) return -1;
      double now = now_s();
      double wait = 3600.0;
      if (!heap.empty()) wait = std::min(wait, heap.top().when - now);
      if (has_deadline) {
        double rem = deadline - now;
        if (rem <= 0) return 0;
        wait = std::min(wait, rem);
      }
      if (wait < 0.0001) wait = 0.0001;
      tsan_safe_wait_for(cv, l, std::chrono::duration<double>(wait));
    }
    std::string item = queue.front();
    queue.pop_front();
    processing.insert(item);
    dirty.erase(item);
    std::strncpy(out, item.c_str(), out_len - 1);
    out[out_len - 1] = '\0';
    return 1;
  }

  void done(const std::string& item) {
    std::lock_guard<std::mutex> l(mu);
    processing.erase(item);
    if (dirty.count(item)) {
      queue.push_back(item);
      cv.notify_one();
    }
  }

  void forget(const std::string& item) {
    std::lock_guard<std::mutex> l(mu);
    item_limiter.forget(item);
  }

  int num_requeues(const std::string& item) {
    std::lock_guard<std::mutex> l(mu);
    return item_limiter.num_requeues(item);
  }

  int size() {
    std::lock_guard<std::mutex> l(mu);
    return static_cast<int>(queue.size());
  }

  void shut_down() {
    std::lock_guard<std::mutex> l(mu);
    shutting_down = true;
    cv.notify_all();
  }

  bool is_shutting_down() {
    std::lock_guard<std::mutex> l(mu);
    return shutting_down;
  }
};

// ------------------------------------------------------------ expectations

struct Expectation {
  long adds = 0;
  long dels = 0;
  double timestamp = 0;
};

struct ControllerExpectations {
  std::mutex mu;
  std::unordered_map<std::string, Expectation> store;
  double ttl;

  explicit ControllerExpectations(double ttl_s) : ttl(ttl_s) {}

  bool expired(const Expectation& e) const { return now_s() - e.timestamp > ttl; }

  void expect(const std::string& key, long adds, long dels) {
    std::lock_guard<std::mutex> l(mu);
    auto it = store.find(key);
    if (it != store.end() && !expired(it->second) &&
        (it->second.adds > 0 || it->second.dels > 0)) {
      it->second.adds += adds;
      it->second.dels += dels;
    } else {
      store[key] = Expectation{adds, dels, now_s()};
    }
  }

  void lower(const std::string& key, long add_delta, long del_delta) {
    std::lock_guard<std::mutex> l(mu);
    auto it = store.find(key);
    if (it != store.end()) {
      it->second.adds += add_delta;
      it->second.dels += del_delta;
    }
  }

  void raise_expectations(const std::string& key, long adds, long dels) {
    std::lock_guard<std::mutex> l(mu);
    auto it = store.find(key);
    if (it != store.end()) {
      it->second.adds += adds;
      it->second.dels += dels;
    }
  }

  bool satisfied(const std::string& key) {
    std::lock_guard<std::mutex> l(mu);
    auto it = store.find(key);
    if (it == store.end()) return true;
    const Expectation& e = it->second;
    return (e.adds <= 0 && e.dels <= 0) || expired(e);
  }

  void erase(const std::string& key) {
    std::lock_guard<std::mutex> l(mu);
    store.erase(key);
  }
};

// ------------------------------------------------------------------ C ABI

extern "C" {

void* rlq_new(double base_delay, double max_delay, double qps, double burst) {
  return new RateLimitingQueue(base_delay, max_delay, qps, burst);
}
void rlq_free(void* h) { delete static_cast<RateLimitingQueue*>(h); }
void rlq_add(void* h, const char* item) {
  static_cast<RateLimitingQueue*>(h)->add(item);
}
void rlq_add_after(void* h, const char* item, double delay) {
  static_cast<RateLimitingQueue*>(h)->add_after(item, delay);
}
void rlq_add_rate_limited(void* h, const char* item) {
  static_cast<RateLimitingQueue*>(h)->add_rate_limited(item);
}
int rlq_get(void* h, double timeout_s, char* out, int out_len) {
  return static_cast<RateLimitingQueue*>(h)->get(timeout_s, out, out_len);
}
void rlq_done(void* h, const char* item) {
  static_cast<RateLimitingQueue*>(h)->done(item);
}
void rlq_forget(void* h, const char* item) {
  static_cast<RateLimitingQueue*>(h)->forget(item);
}
int rlq_num_requeues(void* h, const char* item) {
  return static_cast<RateLimitingQueue*>(h)->num_requeues(item);
}
int rlq_len(void* h) { return static_cast<RateLimitingQueue*>(h)->size(); }
void rlq_shut_down(void* h) { static_cast<RateLimitingQueue*>(h)->shut_down(); }
int rlq_shutting_down(void* h) {
  return static_cast<RateLimitingQueue*>(h)->is_shutting_down() ? 1 : 0;
}

void* exp_new(double ttl_s) { return new ControllerExpectations(ttl_s); }
void exp_free(void* h) { delete static_cast<ControllerExpectations*>(h); }
void exp_expect_creations(void* h, const char* key, long n) {
  static_cast<ControllerExpectations*>(h)->expect(key, n, 0);
}
void exp_expect_deletions(void* h, const char* key, long n) {
  static_cast<ControllerExpectations*>(h)->expect(key, 0, n);
}
void exp_creation_observed(void* h, const char* key) {
  static_cast<ControllerExpectations*>(h)->lower(key, -1, 0);
}
void exp_deletion_observed(void* h, const char* key) {
  static_cast<ControllerExpectations*>(h)->lower(key, 0, -1);
}
void exp_raise(void* h, const char* key, long adds, long dels) {
  static_cast<ControllerExpectations*>(h)->raise_expectations(key, adds, dels);
}
int exp_satisfied(void* h, const char* key) {
  return static_cast<ControllerExpectations*>(h)->satisfied(key) ? 1 : 0;
}
void exp_delete(void* h, const char* key) {
  static_cast<ControllerExpectations*>(h)->erase(key);
}

}  // extern "C"

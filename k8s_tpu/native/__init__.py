"""Native runtime loader.

Builds ``src/runtime.cc`` into a shared library with the system g++ on first
use (no pybind11/pip in this image — plain C ABI over ctypes) and caches it
under ``_build/``.  Everything degrades gracefully: when no toolchain is
present, :func:`available` is False and callers keep the pure-Python
implementations (k8s_tpu/util/workqueue.py, controller_v2/expectations.py).

Opt-in/out: env ``K8S_TPU_NATIVE`` — "1" forces native (raises if unbuildable),
"0" disables, unset/auto uses native when it builds.
"""

from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess
import threading
from k8s_tpu.analysis import checkedlock

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "runtime.cc")
_DL_SRC = os.path.join(_DIR, "src", "dataloader.cc")
_TSAN_WAIT_HDR = os.path.join(_DIR, "src", "tsan_wait.h")
_BUILD_DIR = os.path.join(_DIR, "_build")
_LIB = os.path.join(_BUILD_DIR, "libk8stpu_runtime.so")

_lock = checkedlock.make_lock("native.build")
_lib: ctypes.CDLL | None = None
_tried = False


def build(force: bool = False) -> str | None:
    """Compile the library if stale; returns the .so path or None."""
    sources = [p for p in (_SRC, _DL_SRC, _TSAN_WAIT_HDR) if os.path.exists(p)]
    if len(sources) < 3:
        log.warning("native sources missing; native runtime unavailable")
        return None  # graceful: callers fall back to pure Python
    src_mtime = max(os.path.getmtime(p) for p in sources)
    if not force and os.path.exists(_LIB) and os.path.getmtime(_LIB) >= src_mtime:
        return _LIB
    gxx = shutil.which("g++")
    if gxx is None:
        log.warning("g++ not found; native runtime unavailable")
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = _LIB + ".tmp"
    cmd = [gxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread", _SRC,
           _DL_SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        log.error("native build failed: %s", e.stderr)
        return None
    os.replace(tmp, _LIB)
    log.info("built native runtime: %s", _LIB)
    return _LIB


_STRESS_SRC = os.path.join(_DIR, "src", "stress_main.cc")


def build_stress_binary(tsan: bool = False) -> str | None:
    """Compile the C++ concurrency stress harness (src/stress_main.cc, which
    includes runtime.cc) into a standalone binary; with ``tsan`` it is built
    under -fsanitize=thread — the `go test -race` analogue for the native
    runtime.  Returns the binary path or None when the toolchain (or libtsan)
    is missing."""
    gxx = shutil.which("g++")
    if gxx is None:
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    out = os.path.join(_BUILD_DIR, "stress_tsan" if tsan else "stress")
    sources_mtime = max(os.path.getmtime(_SRC), os.path.getmtime(_STRESS_SRC),
                        os.path.getmtime(_DL_SRC), os.path.getmtime(_TSAN_WAIT_HDR))
    if os.path.exists(out) and os.path.getmtime(out) >= sources_mtime:
        return out
    cmd = [gxx, "-O1", "-g", "-std=c++17", "-pthread",
           "-I", os.path.dirname(_SRC), _STRESS_SRC, "-o", out + ".tmp"]
    if tsan:
        cmd.insert(1, "-fsanitize=thread")
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        log.warning("stress binary build failed (%s): %s",
                    "tsan" if tsan else "plain", e.stderr[-500:])
        return None
    os.replace(out + ".tmp", out)
    return out


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes.c_char_p
    lib.rlq_new.restype = ctypes.c_void_p
    lib.rlq_new.argtypes = [ctypes.c_double] * 4
    lib.rlq_free.argtypes = [ctypes.c_void_p]
    lib.rlq_add.argtypes = [ctypes.c_void_p, c]
    lib.rlq_add_after.argtypes = [ctypes.c_void_p, c, ctypes.c_double]
    lib.rlq_add_rate_limited.argtypes = [ctypes.c_void_p, c]
    lib.rlq_get.restype = ctypes.c_int
    lib.rlq_get.argtypes = [ctypes.c_void_p, ctypes.c_double, ctypes.c_char_p, ctypes.c_int]
    lib.rlq_done.argtypes = [ctypes.c_void_p, c]
    lib.rlq_forget.argtypes = [ctypes.c_void_p, c]
    lib.rlq_num_requeues.restype = ctypes.c_int
    lib.rlq_num_requeues.argtypes = [ctypes.c_void_p, c]
    lib.rlq_len.restype = ctypes.c_int
    lib.rlq_len.argtypes = [ctypes.c_void_p]
    lib.rlq_shut_down.argtypes = [ctypes.c_void_p]
    lib.rlq_shutting_down.restype = ctypes.c_int
    lib.rlq_shutting_down.argtypes = [ctypes.c_void_p]

    lib.exp_new.restype = ctypes.c_void_p
    lib.exp_new.argtypes = [ctypes.c_double]
    lib.exp_free.argtypes = [ctypes.c_void_p]
    lib.exp_expect_creations.argtypes = [ctypes.c_void_p, c, ctypes.c_long]
    lib.exp_expect_deletions.argtypes = [ctypes.c_void_p, c, ctypes.c_long]
    lib.exp_creation_observed.argtypes = [ctypes.c_void_p, c]
    lib.exp_deletion_observed.argtypes = [ctypes.c_void_p, c]
    lib.exp_raise.argtypes = [ctypes.c_void_p, c, ctypes.c_long, ctypes.c_long]
    lib.exp_satisfied.restype = ctypes.c_int
    lib.exp_satisfied.argtypes = [ctypes.c_void_p, c]
    lib.exp_delete.argtypes = [ctypes.c_void_p, c]

    lib.dl_new.restype = ctypes.c_void_p
    lib.dl_new.argtypes = [ctypes.c_int, ctypes.c_uint64, ctypes.c_int]
    lib.dl_free.argtypes = [ctypes.c_void_p]
    lib.dl_register_file.restype = ctypes.c_int
    lib.dl_register_file.argtypes = [ctypes.c_void_p, c]
    lib.dl_submit.restype = ctypes.c_int
    lib.dl_submit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                              ctypes.c_uint64, ctypes.c_uint64]
    lib.dl_next.restype = ctypes.c_int64
    lib.dl_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_uint64, ctypes.c_int]
    lib.dl_error.restype = ctypes.c_int
    lib.dl_error.argtypes = [ctypes.c_void_p]
    lib.dl_inflight.restype = ctypes.c_uint64
    lib.dl_inflight.argtypes = [ctypes.c_void_p]
    return lib


def load() -> ctypes.CDLL | None:
    """Build-if-needed and dlopen the native runtime; None when unavailable."""
    global _lib, _tried
    if os.environ.get("K8S_TPU_NATIVE", "") == "0":
        return None  # checked outside the cache: the env var works at any time
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = build()
        if path is None:
            if os.environ.get("K8S_TPU_NATIVE") == "1":
                raise RuntimeError("K8S_TPU_NATIVE=1 but native runtime failed to build")
            return None
        _lib = _declare(ctypes.CDLL(path))
        return _lib


def available() -> bool:
    return load() is not None


def select(native_factory, fallback_factory):
    """THE selection policy, shared by every factory seam
    (workqueue.new_rate_limiting_queue, expectations.new_controller_expectations).

    - ``K8S_TPU_NATIVE=0``: fallback (handled inside :func:`load`).
    - ``K8S_TPU_NATIVE=1``: native or raise — a forced-native operator must
      never silently run pure Python.
    - unset: native when it builds, else fallback.
    """
    lib = load()  # raises only in forced mode when unbuildable
    if lib is None:
        return fallback_factory()
    try:
        return native_factory()
    except Exception:
        if os.environ.get("K8S_TPU_NATIVE") == "1":
            raise
        log.warning("native factory failed; using Python fallback", exc_info=True)
        return fallback_factory()

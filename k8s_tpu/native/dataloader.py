"""Python wrapper over the native data loader (src/dataloader.cc).

``NativeWindowReader`` streams fixed-size byte windows from registered
files in submission order, with the reads running on C++ threads — no GIL
involvement, unlike the mmap path whose page faults block the whole
interpreter.  k8s_tpu/models/dataset.py uses it as the ``reader="native"``
backend for token-shard windows.
"""

from __future__ import annotations

import ctypes
from typing import Iterable, Iterator, Sequence

from k8s_tpu import native


def available() -> bool:
    return native.load() is not None


class NativeWindowReader:
    """Ordered windows over (path, offset, nbytes) descriptors.

    Usage::

        with NativeWindowReader(paths, window_bytes) as r:
            for data in r.stream(descriptors):  # (path_idx, offset) pairs
                ...
    """

    def __init__(self, paths: Sequence[str], window_bytes: int,
                 n_slots: int = 16, n_threads: int = 2):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._window_bytes = int(window_bytes)
        self._h = lib.dl_new(int(n_slots), self._window_bytes, int(n_threads))
        if not self._h:
            raise RuntimeError("dl_new failed")
        self._file_ids = []
        for p in paths:
            fid = lib.dl_register_file(self._h, p.encode())
            if fid < 0:
                self.close()
                raise FileNotFoundError(f"native loader cannot open {p}")
            self._file_ids.append(fid)
        self._buf = ctypes.create_string_buffer(self._window_bytes)

    def close(self) -> None:
        if self._h:
            self._lib.dl_free(self._h)
            self._h = None

    def __enter__(self) -> "NativeWindowReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stream(self, descriptors: Iterable[tuple[int, int]],
               timeout_s: float = 30.0) -> Iterator[bytes]:
        """Yield each descriptor's bytes in order; descriptors are
        (path_index, byte_offset) pairs, all window_bytes long."""
        it = iter(descriptors)
        exhausted = False
        pending = 0
        while True:
            # keep the ring full before draining one window
            while not exhausted:
                try:
                    path_idx, offset = next(it)
                except StopIteration:
                    exhausted = True
                    break
                rc = self._lib.dl_submit(
                    self._h, self._file_ids[path_idx], int(offset),
                    self._window_bytes)
                if rc == 0:
                    # ring full: put it back conceptually by consuming first
                    yield self._next(timeout_s)
                    pending -= 1
                    rc = self._lib.dl_submit(
                        self._h, self._file_ids[path_idx], int(offset),
                        self._window_bytes)
                if rc != 1:
                    raise IOError("native loader rejected a window "
                                  "(poisoned by an earlier read failure)")
                pending += 1
            if pending == 0:
                return
            yield self._next(timeout_s)
            pending -= 1

    def _next(self, timeout_s: float) -> bytes:
        n = self._lib.dl_next(self._h, self._buf, self._window_bytes,
                              int(timeout_s * 1000))
        if n == 0:
            raise TimeoutError("native loader stalled (no window within "
                               f"{timeout_s}s)")
        if n < 0:
            raise IOError(f"native loader failed (rc={n}) — short read or "
                          "IO error on a shard")
        if n != self._window_bytes:
            raise IOError(f"native loader returned {n} bytes, expected "
                          f"{self._window_bytes}")
        return self._buf.raw[:n]

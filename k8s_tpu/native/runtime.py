"""Python wrappers over the native runtime (ctypes, see __init__.py).

Drop-in interface matches k8s_tpu/util/workqueue.RateLimitingQueue and
k8s_tpu/controller_v2/expectations.ControllerExpectations, so the
controllers can take either implementation through their factory seams.
"""

from __future__ import annotations

from typing import Optional

from k8s_tpu import native
from k8s_tpu.controller_v2.expectations import EXPECTATION_TTL_SECONDS

_KEY_BUF = 4096


def _b(item) -> bytes:
    return item.encode() if isinstance(item, str) else bytes(item)


class NativeRateLimitingQueue:
    """workqueue.RateLimitingQueue backed by libk8stpu_runtime.

    Item keys must be strings (controller keys are "<ns>/<name>", which is
    all the operators ever enqueue).
    """

    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 1000.0,
        qps: float = 10.0,
        burst: int = 100,
    ):
        from k8s_tpu.util.workqueue import WaitTracker

        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.rlq_new(base_delay, max_delay, qps, float(burst))
        # enqueue→dequeue wait accounting via the same WaitTracker the
        # Python WorkQueue uses (one pop_wait contract, one
        # implementation).  The C++ core is opaque about WHEN an item
        # lands in the ready deque, so the stamps are best-effort: add()
        # stamps now, add_after() stamps now+delay (the scheduled
        # delivery), add_rate_limited() doesn't stamp at all (the backoff
        # delay is computed inside the core and is deliberate latency, not
        # queue wait) — those deliveries simply record no wait.
        self._wait_tracker = WaitTracker()

    def add(self, item: str) -> None:
        self._wait_tracker.stamp(item)
        self._lib.rlq_add(self._h, _b(item))

    def add_after(self, item: str, delay: float) -> None:
        import time

        self._wait_tracker.stamp(item, at=time.monotonic() + max(delay, 0.0))
        self._lib.rlq_add_after(self._h, _b(item), delay)

    def add_rate_limited(self, item: str) -> None:
        self._lib.rlq_add_rate_limited(self._h, _b(item))

    def get(self, timeout: Optional[float] = None):
        import ctypes

        buf = ctypes.create_string_buffer(_KEY_BUF)
        rc = self._lib.rlq_get(self._h, -1.0 if timeout is None else timeout, buf, _KEY_BUF)
        if rc == 1:
            item = buf.value.decode()
            wait = self._wait_tracker.claim(item)
            if wait is not None:
                from k8s_tpu.util.workqueue import workqueue_wait_histogram

                workqueue_wait_histogram().observe(wait)
            return item, False
        if rc == 0:
            return None, False
        return None, True

    def pop_wait(self, item: str) -> Optional[float]:
        """Same contract as WorkQueue.pop_wait: the wait measured at the
        last get() of ``item``, consumed on read; None when untracked."""
        return self._wait_tracker.pop(item)

    def done(self, item: str) -> None:
        # evict any unclaimed wait (same lifecycle rule as the Python
        # WorkQueue.done: consumers that never pop_wait must not leak)
        self._wait_tracker.evict(item)
        self._lib.rlq_done(self._h, _b(item))

    def forget(self, item: str) -> None:
        self._lib.rlq_forget(self._h, _b(item))

    def num_requeues(self, item: str) -> int:
        return self._lib.rlq_num_requeues(self._h, _b(item))

    def shut_down(self) -> None:
        self._lib.rlq_shut_down(self._h)

    def shutting_down(self) -> bool:
        return bool(self._lib.rlq_shutting_down(self._h))

    def __len__(self) -> int:
        return self._lib.rlq_len(self._h)

    def depth(self) -> int:
        """Ready backlog for the workqueue_depth gauge (same contract as the
        pure-Python WorkQueue.depth)."""
        return len(self)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None):
            self._lib.rlq_free(h)


class NativeControllerExpectations:
    """expectations.ControllerExpectations backed by libk8stpu_runtime."""

    def __init__(self, ttl_seconds: float = EXPECTATION_TTL_SECONDS):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.exp_new(ttl_seconds)

    def expect_creations(self, key: str, count: int) -> None:
        self._lib.exp_expect_creations(self._h, _b(key), count)

    def expect_deletions(self, key: str, count: int) -> None:
        self._lib.exp_expect_deletions(self._h, _b(key), count)

    def creation_observed(self, key: str) -> None:
        self._lib.exp_creation_observed(self._h, _b(key))

    def deletion_observed(self, key: str) -> None:
        self._lib.exp_deletion_observed(self._h, _b(key))

    def raise_expectations(self, key: str, adds: int, dels: int) -> None:
        self._lib.exp_raise(self._h, _b(key), adds, dels)

    def satisfied(self, key: str) -> bool:
        return bool(self._lib.exp_satisfied(self._h, _b(key)))

    def delete_expectations(self, key: str) -> None:
        self._lib.exp_delete(self._h, _b(key))

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None):
            self._lib.exp_free(h)

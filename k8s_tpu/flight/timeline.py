"""Per-job lifecycle timelines (the journal half of the flight recorder).

A bounded ring journal: one deque per job (oldest entries evicted at
``max_events_per_job``) inside an LRU-bounded job registry (least recently
*written* job evicted at ``max_jobs``) — a 5k-job churn storm can never
grow the journal past a fixed footprint.  Entries are stamped with a
process-wide monotonic sequence number (the ordering key — wall clocks
can step backwards mid-run) plus a wall timestamp for humans.

The v2 controller records condition transitions (``controller_v2/status``),
admission/parking/preemption (the scheduler gate), create/delete waves
(``controller_v2/control``), and recorder events (``client/record``)
through the process-global ``flight.TIMELINE``; ``/debug/timeline`` on the
metrics server and dashboard serves it back (``flight/debug.py``).

The recorder starts *inactive* — ``record()`` is a cheap no-op until a
controller (or test) calls ``activate()``.  This is what gives
``/debug/timeline`` the same 404-with-explicit-body contract as
``/debug/traces`` (tracing off) and ``/debug/scheduler`` (no scheduler
registered).
"""

from __future__ import annotations

import itertools
from k8s_tpu.analysis import checkedlock
import time
from collections import OrderedDict, deque

DEFAULT_MAX_EVENTS_PER_JOB = 256
DEFAULT_MAX_JOBS = 8192


class TimelineRecorder:
    """Bounded, thread-safe per-job lifecycle journal."""

    def __init__(self, max_events_per_job: int = DEFAULT_MAX_EVENTS_PER_JOB,
                 max_jobs: int = DEFAULT_MAX_JOBS):
        if max_events_per_job < 1 or max_jobs < 1:
            raise ValueError("timeline bounds must be >= 1")
        self.max_events_per_job = max_events_per_job
        self.max_jobs = max_jobs
        self._lock = checkedlock.make_lock("flight.timeline")
        self._seq = itertools.count(1)
        # job key -> deque of entry dicts; OrderedDict gives LRU-by-write
        self._jobs: "OrderedDict[str, deque]" = OrderedDict()
        self._active = False
        self._events_total = 0
        self._evicted_jobs = 0
        self._dropped_events = 0  # ring-evicted entries (per-job bound)

    # -- lifecycle -----------------------------------------------------------

    @property
    def active(self) -> bool:
        return self._active

    def activate(self) -> None:
        self._active = True

    def deactivate(self) -> None:
        self._active = False

    # -- writers -------------------------------------------------------------

    def record(self, job: str, kind: str, reason: str = "",
               message: str = "", **attrs) -> None:
        """Append one entry to ``job``'s ring.  No-op while inactive; never
        raises (the callers are reconcile hot paths)."""
        if not self._active or not job:
            return
        entry = {
            "ts_monotonic": time.monotonic(),
            "ts_wall": time.time(),
            "kind": str(kind),
        }
        if reason:
            entry["reason"] = str(reason)
        if message:
            entry["message"] = str(message)
        if attrs:
            entry["attrs"] = {k: v for k, v in attrs.items()}
        with self._lock:
            # seq allocated UNDER the lock: allocating outside would let two
            # writers to the same job append out of seq order, breaking
            # snapshot()'s ordering and the ?since= incremental-poll contract
            entry["seq"] = next(self._seq)
            ring = self._jobs.get(job)
            if ring is None:
                ring = deque(maxlen=self.max_events_per_job)
                self._jobs[job] = ring
                if len(self._jobs) > self.max_jobs:
                    self._jobs.popitem(last=False)
                    self._evicted_jobs += 1
            else:
                self._jobs.move_to_end(job)
            if len(ring) == ring.maxlen:
                self._dropped_events += 1
            ring.append(entry)
            self._events_total += 1

    def clear(self) -> None:
        with self._lock:
            self._jobs.clear()
            self._events_total = 0
            self._evicted_jobs = 0
            self._dropped_events = 0

    # -- readers -------------------------------------------------------------

    def jobs(self) -> list[str]:
        with self._lock:
            return list(self._jobs)

    def snapshot(self, job: str, since: int | None = None,
                 limit: int | None = None) -> list[dict]:
        """``job``'s entries ordered by sequence number.  ``since`` keeps
        only entries with ``seq > since`` (the incremental-poll contract of
        ``?since=``); ``limit`` keeps the most recent N."""
        with self._lock:
            ring = self._jobs.get(job)
            entries = [dict(e) for e in ring] if ring is not None else []
        if since is not None:
            entries = [e for e in entries if e["seq"] > since]
        if limit is not None and limit >= 0:
            entries = entries[-limit:] if limit else []
        return entries

    def stats(self) -> dict:
        """Journal footprint + per-job depth distribution (the churn-bench
        "timeline depth stats" artifact field)."""
        with self._lock:
            depths = sorted(len(ring) for ring in self._jobs.values())
            out = {
                "jobs": len(self._jobs),
                "events_total": self._events_total,
                "evicted_jobs": self._evicted_jobs,
                "dropped_events": self._dropped_events,
                "max_events_per_job": self.max_events_per_job,
                "max_jobs": self.max_jobs,
            }
        if depths:
            out["depth_p50"] = depths[len(depths) // 2]
            out["depth_max"] = depths[-1]
        else:
            out["depth_p50"] = 0
            out["depth_max"] = 0
        return out

"""Control-plane flight recorder (ISSUE 7): apiserver call accounting,
watch-stream health, and per-job lifecycle timelines.

Three process-global instruments, mirroring the ``trace.TRACER`` /
``scheduler.set_active`` pattern so HTTP debug endpoints and metric
adapters need no controller reference:

- :data:`ACCOUNTING` — every apiserver request either transport issues,
  keyed ``(verb, resource, code)``, with durations and an in-process
  rolling rate (``client/rest.py`` records per wire *attempt*;
  ``client/fake.py`` per backend-protocol call).
- :data:`WATCH` — reflector relists (initial/410/error), watch restarts,
  delivered event counts, and live stream ages (``client/informer.py``).
- :data:`TIMELINE` — a bounded per-job ring journal of lifecycle events
  (conditions, admission/parking/preemption, create/delete waves,
  recorder events), served as ``/debug/timeline`` on the metrics server
  and dashboard.  Inactive (no-op, 404 on the endpoint) until the v2
  controller activates it.
- :data:`EVENTS` — EventRecorder send/drop/aggregate counters.

This package is stdlib-only by policy (``harness/py_checks.py`` gates it
like ``trace/`` and ``scheduler/``): it rides the REST client's request
hot path and is read by two HTTP processes.
"""

from __future__ import annotations

import contextlib
import threading
import time

from k8s_tpu.flight.accounting import (  # noqa: F401 (public surface)
    CallAccounting,
    EventStats,
)
from k8s_tpu.flight.debug import debug_timeline_response  # noqa: F401
from k8s_tpu.flight.timeline import (  # noqa: F401
    DEFAULT_MAX_EVENTS_PER_JOB,
    DEFAULT_MAX_JOBS,
    TimelineRecorder,
)
from k8s_tpu.flight.watchhealth import (  # noqa: F401
    RELIST_ERROR,
    RELIST_EXPIRED,
    RELIST_INITIAL,
    RELIST_NO_RV,
    WatchHealth,
)

def _bound_from_env(name: str, default: int) -> int:
    """Positive int from the environment, else the default (garbage and
    non-positive values fall back — a journal bound of 0 is meaningless)."""
    import os

    try:
        n = int(os.environ.get(name, ""))
    except ValueError:
        return default
    return n if n > 0 else default


ACCOUNTING = CallAccounting()
WATCH = WatchHealth()
# Journal sizing knobs: the worst-case footprint is the PRODUCT of the two
# bounds (defaults 256 x 8192 ≈ 2M entries ≈ hundreds of MB if every ring
# of a huge churning fleet actually fills) — operators running very large
# fleets on small control-plane pods can shrink either bound.
TIMELINE = TimelineRecorder(
    max_events_per_job=_bound_from_env("K8S_TPU_TIMELINE_EVENTS_PER_JOB",
                                       DEFAULT_MAX_EVENTS_PER_JOB),
    max_jobs=_bound_from_env("K8S_TPU_TIMELINE_JOBS", DEFAULT_MAX_JOBS),
)
EVENTS = EventStats()

# Reentrancy guard for account(): composite backend calls (the fake's
# patch = get + merge + update, delete_collection = list + N deletes)
# must count as ONE apiserver request, matching what a real apiserver
# would have seen on the wire for the outermost verb.
_accounting_depth = threading.local()


def record_api_call(verb: str, resource: str, code: int,
                    seconds: float) -> None:
    """Account one request attempt directly (the REST client's entry —
    it times attempts itself because one logical call can be several).
    Honors the same thread-local guard as :func:`account`, so
    :func:`suppress_accounting` covers BOTH transports."""
    if getattr(_accounting_depth, "n", 0):
        return
    ACCOUNTING.record(verb, resource, code, seconds)


@contextlib.contextmanager
def account(verb: str, resource: str, success_code: int = 200):
    """Time and count one backend-protocol call.  The status code is
    ``success_code`` on success (POST callers pass 201 for wire parity),
    the ApiError's code on failure, 0 when the failure carries no HTTP
    status.  Nested accounted calls on the same thread are not
    double-counted (see the reentrancy note above)."""
    depth = getattr(_accounting_depth, "n", 0)
    _accounting_depth.n = depth + 1
    if depth:
        try:
            yield
        finally:
            _accounting_depth.n = depth
        return
    t0 = time.monotonic()
    code = success_code
    try:
        yield
    except BaseException as e:
        code = getattr(e, "code", 0)
        if not isinstance(code, int):
            code = 0
        raise
    finally:
        _accounting_depth.n = depth
        ACCOUNTING.record(verb, resource, code, time.monotonic() - t0)


@contextlib.contextmanager
def suppress_accounting():
    """Suppress call accounting for calls made on THIS thread (bench
    fault injection, harness setup traffic).  Thread-local by design:
    concurrent operator threads keep counting — a global off-switch would
    race them and silently swallow real operator traffic."""
    depth = getattr(_accounting_depth, "n", 0)
    _accounting_depth.n = depth + 1
    try:
        yield
    finally:
        _accounting_depth.n = depth


def timeline(job: str, kind: str, reason: str = "", message: str = "",
             **attrs) -> None:
    """Record one lifecycle event on the process-global journal (no-op
    while the recorder is inactive)."""
    TIMELINE.record(job, kind, reason=reason, message=message, **attrs)


def timeline_response(query: str = "") -> tuple[int, str, str]:
    """The /debug/timeline endpoint body for the global recorder."""
    return debug_timeline_response(TIMELINE, query)


def reset_all() -> None:
    """Zero every instrument (benches and tests; the timeline's
    active/inactive state is preserved — only data is cleared)."""
    ACCOUNTING.reset()
    WATCH.reset()
    TIMELINE.clear()
    EVENTS.reset()

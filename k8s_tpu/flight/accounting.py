"""Apiserver call accounting (the client half of the flight recorder).

One process-global :class:`CallAccounting` (see ``flight.ACCOUNTING``)
counts every request either transport issues — ``client/rest.py`` records
one entry per *wire attempt* (a transport-retried GET is two attempts and
two counts), and ``client/fake.py`` records one entry per backend-protocol
call, so benches against the in-process cluster measure the same substrate
a deployed operator exports.  The counters back the
``apiserver_requests_total{verb,resource,code}`` and
``apiserver_request_duration_seconds`` families in ``util/metrics.py``;
``bench_operator --churn`` asserts flatness on ``total()`` deltas over
explicit measurement windows (``rate()`` is an in-process debug
convenience with coarser per-second bucketing).

Verbs are HTTP-shaped with two refinements real operators need for
steady-state proofs: collection GETs count as ``LIST`` and streaming GETs
as ``WATCH`` — "zero per-sync LISTs" is only assertable if LIST is a
label, not a path-parsing exercise.  Transport-level failures (no HTTP
status ever arrived) count under code ``0``.

Stdlib-only by policy (``harness/py_checks.py`` gates this package): the
REST client records through here on its request hot path.
"""

from __future__ import annotations

from k8s_tpu.analysis import checkedlock
import time
from typing import Optional

# Histogram bounds for request durations; chosen to match the
# util.metrics default request-latency buckets so the exported family
# lines up with the rest of the operator's latency metrics.
DURATION_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Rolling-rate window state: per-second call buckets, pruned past this
# horizon.  Coarse on purpose — the in-process rate() reader is a bench /
# debug convenience, not a precision instrument.
RATE_HORIZON_S = 120


class CallAccounting:
    """Thread-safe request counters keyed ``(verb, resource, code)`` plus a
    process-wide duration histogram and a per-second rolling rate."""

    def __init__(self):
        self._lock = checkedlock.make_lock("flight.accounting")
        self._requests: dict[tuple[str, str, int], int] = {}
        self._bucket_counts = [0] * len(DURATION_BUCKETS)
        self._duration_sum = 0.0
        self._duration_count = 0
        # int(monotonic second) -> calls landed in it (rolling rate source)
        self._per_second: dict[int, int] = {}

    def record(self, verb: str, resource: str, code: int,
               seconds: float) -> None:
        """Account one request attempt.  ``code`` is the HTTP status (0 for
        transport failures that never produced one)."""
        key = (str(verb), str(resource), int(code))
        now_s = int(time.monotonic())
        with self._lock:
            self._requests[key] = self._requests.get(key, 0) + 1
            self._duration_sum += seconds
            self._duration_count += 1
            for i, bound in enumerate(DURATION_BUCKETS):
                if seconds <= bound:
                    self._bucket_counts[i] += 1
                    break
            self._per_second[now_s] = self._per_second.get(now_s, 0) + 1
            if len(self._per_second) > RATE_HORIZON_S + 2:
                cutoff = now_s - RATE_HORIZON_S
                for s in [s for s in self._per_second if s < cutoff]:
                    del self._per_second[s]

    # -- readers -------------------------------------------------------------

    def total(self) -> int:
        with self._lock:
            return sum(self._requests.values())

    def snapshot(self) -> dict[tuple[str, str, int], int]:
        """Copy of the ``(verb, resource, code) -> count`` table."""
        with self._lock:
            return dict(self._requests)

    def by_verb_resource(self) -> dict[str, int]:
        """Counts aggregated over status code, keyed ``"VERB resource"`` —
        the churn-bench artifact's call-breakdown shape."""
        out: dict[str, int] = {}
        with self._lock:
            for (verb, resource, _code), n in self._requests.items():
                k = f"{verb} {resource}"
                out[k] = out.get(k, 0) + n
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def count(self, verb: Optional[str] = None,
              resource: Optional[str] = None) -> int:
        """Total requests matching the given verb and/or resource."""
        with self._lock:
            return sum(
                n for (v, r, _c), n in self._requests.items()
                if (verb is None or v == verb)
                and (resource is None or r == resource)
            )

    def rate(self, window_s: float = 5.0) -> float:
        """Calls/second over the trailing ``window_s`` (whole seconds,
        including the current in-progress one — a mid-second read slightly
        understates a steady stream but never hides just-recorded calls)."""
        window = max(1, int(window_s))
        now_s = int(time.monotonic())
        with self._lock:
            calls = sum(n for s, n in self._per_second.items()
                        if now_s - window < s <= now_s)
        return calls / window

    def duration_stats(self) -> dict:
        with self._lock:
            return {
                "count": self._duration_count,
                "sum": self._duration_sum,
                "buckets": {
                    str(b): c
                    for b, c in zip(DURATION_BUCKETS, self._bucket_counts)
                },
            }

    def duration_samples(self) -> tuple[tuple[float, ...], list[int], float, int]:
        """(bucket bounds, per-bucket counts, sum, count) for the
        Prometheus-histogram adapter in util/metrics.py."""
        with self._lock:
            return (DURATION_BUCKETS, list(self._bucket_counts),
                    self._duration_sum, self._duration_count)

    def reset(self) -> None:
        with self._lock:
            self._requests.clear()
            self._bucket_counts = [0] * len(DURATION_BUCKETS)
            self._duration_sum = 0.0
            self._duration_count = 0
            self._per_second.clear()


class EventStats:
    """Recorder-event counters (``events_recorded_total`` /
    ``events_dropped_total`` / ``events_aggregated_total``): recording must
    never block or raise on the reconcile path, so the only observability a
    dropped event gets is this counter."""

    def __init__(self):
        self._lock = checkedlock.make_lock("flight.events")
        self.recorded = 0
        self.dropped = 0
        self.aggregated = 0

    def record_recorded(self, n: int = 1) -> None:
        with self._lock:
            self.recorded += n

    def record_dropped(self, n: int = 1) -> None:
        with self._lock:
            self.dropped += n

    def record_aggregated(self, n: int = 1) -> None:
        with self._lock:
            self.aggregated += n

    def snapshot(self) -> dict:
        with self._lock:
            return {"recorded": self.recorded, "dropped": self.dropped,
                    "aggregated": self.aggregated}

    def reset(self) -> None:
        with self._lock:
            self.recorded = self.dropped = self.aggregated = 0

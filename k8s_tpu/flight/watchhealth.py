"""Watch-stream health (the reflector half of the flight recorder).

``client/informer.py`` records through the process-global instance
(``flight.WATCH``) so a relist storm is *visible* instead of silent:

- ``record_relist(resource, reason)`` — one full LIST+replace cycle, with
  why (``initial`` / ``410`` / ``error``);
- ``record_restart(resource)`` — a watch stream reopened after a previous
  one ended (the steady state restarts on the server's watch timeout;
  a restart *spike* means streams are dying early);
- ``record_event(resource, type)`` — ADDED/MODIFIED/DELETED/ERROR frames
  delivered;
- ``stream_started`` / ``stream_ended`` — bounds for the
  ``watch_stream_age_seconds`` gauge (the series is ABSENT while no
  stream is open: a resource with no age sample has no live watch,
  which is itself the signal).

Backing for the ``watch_*`` metric families in ``util/metrics.py`` and
the relist assertions in ``bench_operator --churn``.
"""

from __future__ import annotations

import itertools
from k8s_tpu.analysis import checkedlock
import time

RELIST_INITIAL = "initial"
RELIST_EXPIRED = "410"
RELIST_ERROR = "error"
# resume-free backend (list responses carry no resourceVersion): every
# clean stream end relists BY DESIGN — a healthy mode, distinguished from
# "error" so it never reads as a permanent failure signal
RELIST_NO_RV = "no_rv"


class WatchHealth:
    """Thread-safe per-resource watch/reflector counters."""

    def __init__(self):
        self._lock = checkedlock.make_lock("flight.watchhealth")
        self._relists: dict[tuple[str, str], int] = {}  # (resource, reason)
        self._restarts: dict[str, int] = {}
        self._events: dict[tuple[str, str], int] = {}  # (resource, type)
        # resource -> {stream token -> start monotonic}.  Token-keyed, not
        # bare resource: two informers watching the same resource in one
        # process (leader-failover candidates, embedded layouts) must not
        # clobber each other's entries — one reflector's teardown popping a
        # live sibling's stream would read as a false no-watch alarm.  The
        # exposed age is the OLDEST open stream's.
        self._streams: dict[str, dict[int, float]] = {}
        self._stream_tokens = itertools.count(1)

    def record_relist(self, resource: str, reason: str) -> None:
        key = (str(resource), str(reason))
        with self._lock:
            self._relists[key] = self._relists.get(key, 0) + 1

    def record_restart(self, resource: str) -> None:
        with self._lock:
            self._restarts[resource] = self._restarts.get(resource, 0) + 1

    def record_event(self, resource: str, event_type: str) -> None:
        key = (str(resource), str(event_type))
        with self._lock:
            self._events[key] = self._events.get(key, 0) + 1

    def stream_started(self, resource: str) -> int:
        """Register one opened stream; returns the token to pass back to
        :meth:`stream_ended` when exactly this stream closes."""
        with self._lock:
            token = next(self._stream_tokens)
            self._streams.setdefault(resource, {})[token] = time.monotonic()
            return token

    def stream_ended(self, resource: str, token: int) -> None:
        with self._lock:
            open_streams = self._streams.get(resource)
            if open_streams is not None:
                open_streams.pop(token, None)
                if not open_streams:
                    del self._streams[resource]

    def _ages_locked(self, now: float) -> dict[str, float]:
        return {res: now - min(t0s.values())
                for res, t0s in self._streams.items() if t0s}

    # -- readers -------------------------------------------------------------

    def relists(self, resource: str | None = None,
                reason: str | None = None) -> int:
        with self._lock:
            return sum(
                n for (res, why), n in self._relists.items()
                if (resource is None or res == resource)
                and (reason is None or why == reason)
            )

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "relists": {f"{res}/{why}": n
                            for (res, why), n in sorted(self._relists.items())},
                "restarts": dict(self._restarts),
                "events": {f"{res}/{etype}": n
                           for (res, etype), n in sorted(self._events.items())},
                "stream_age_s": {res: round(age, 3)
                                 for res, age
                                 in self._ages_locked(now).items()},
            }

    def labeled(self) -> dict:
        """Raw label-keyed tables for the Prometheus adapters."""
        now = time.monotonic()
        with self._lock:
            return {
                "relists": dict(self._relists),
                "restarts": dict(self._restarts),
                "events": dict(self._events),
                "stream_age_s": self._ages_locked(now),
            }

    def reset(self) -> None:
        with self._lock:
            self._relists.clear()
            self._restarts.clear()
            self._events.clear()
            self._streams.clear()

"""/debug/timeline responder (mirror of trace.debug_traces_response and
scheduler.debug_scheduler_response — ONE implementation shared by the
metrics server and the dashboard backend, so both speak the same
contract).

Routes:

- ``/debug/timeline``                     — journal summary (jobs + stats)
- ``/debug/timeline?job=<ns/name>``       — that job's ordered lifecycle
- ``?since=<seq>``                        — only entries newer than seq
  (incremental polling: pass the last seq you saw)
- ``?n=<limit>``                          — most recent N entries

404 with an explicit body while no controller has activated the recorder
(same contract as /debug/traces with tracing off).
"""

from __future__ import annotations

import json
from urllib.parse import parse_qs


def debug_timeline_response(timeline, query: str = "") -> tuple[int, str, str]:
    """(status_code, body, content_type) for GET /debug/timeline."""
    if timeline is None or not timeline.active:
        return (404,
                "timeline recording inactive (the v2 controller activates "
                "the flight recorder on startup)\n",
                "text/plain")
    params = parse_qs(query or "")

    def _int_param(name: str):
        raw = (params.get(name) or [None])[0]
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return None

    job = (params.get("job") or [None])[0]
    if job:
        since = _int_param("since")
        entries = timeline.snapshot(job, since=since,
                                    limit=_int_param("n"))
        body = json.dumps({
            "job": job,
            "events": entries,
            "count": len(entries),
            # an empty incremental poll ECHOES the caller's since — a
            # last_seq of 0 would make the next ?since=0 poll re-download
            # the whole ring as apparent new events
            "last_seq": entries[-1]["seq"] if entries else (since or 0),
        }, indent=2)
        return 200, body + "\n", "application/json"
    body = json.dumps({
        "jobs": timeline.jobs(),
        "stats": timeline.stats(),
    }, indent=2, sort_keys=True)
    return 200, body + "\n", "application/json"

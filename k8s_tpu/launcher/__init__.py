"""In-pod launcher runtime (replaces the reference's TF_CONFIG /
tf.train.Server contract, SURVEY.md §3.3)."""

from k8s_tpu.launcher.bootstrap import (  # noqa: F401
    LauncherConfig,
    initialize_distributed,
    make_training_mesh,
)

"""In-pod bootstrap: operator-injected env → jax.distributed → device mesh.

The reference's in-pod runtime was: parse TF_CONFIG → tf.train.ClusterSpec →
tf.train.Server(grpc) → PS blocks in server.join()
(examples/tf_sample/tf_sample/tf_smoke.py:88-138).  The TPU-native contract
(injected by k8s_tpu.controller_v2.tpu_config.gen_env_vars) is:

    JAX_COORDINATOR_ADDRESS  host:port of process 0
    JAX_NUM_PROCESSES        world size
    JAX_PROCESS_ID           this pod's process id
    TPU_ACCELERATOR_TYPE / TPU_TOPOLOGY        slice topology
    MEGASCALE_NUM_SLICES / MEGASCALE_SLICE_ID  multi-slice (DCN)

``initialize_distributed`` is idempotent and a no-op for single-process
jobs.  ``make_training_mesh`` builds the global mesh after initialization —
chief-exit semantics reduce to "process 0 returns / raises"
(pkg/trainer/training.go:154-189 chief logic → process-0 exit propagation).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

from k8s_tpu.parallel.mesh import MeshConfig, make_mesh

log = logging.getLogger(__name__)

_initialized = False


@dataclass
class LauncherConfig:
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0
    accelerator_type: str = ""
    topology: str = ""
    num_slices: int = 1
    slice_id: int = 0
    checkpoint_dir: str = ""

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "LauncherConfig":
        e = env if env is not None else os.environ
        return cls(
            coordinator_address=e.get("JAX_COORDINATOR_ADDRESS", ""),
            num_processes=int(e.get("JAX_NUM_PROCESSES", "1") or 1),
            process_id=int(e.get("JAX_PROCESS_ID", "0") or 0),
            accelerator_type=e.get("TPU_ACCELERATOR_TYPE", ""),
            topology=e.get("TPU_TOPOLOGY", ""),
            num_slices=int(e.get("MEGASCALE_NUM_SLICES", "1") or 1),
            slice_id=int(e.get("MEGASCALE_SLICE_ID", "0") or 0),
            # Orbax-style checkpoint convention (SURVEY.md §5 Checkpoint/resume):
            # stable across gang restarts because it is spec'd, not generated.
            checkpoint_dir=e.get("CHECKPOINT_DIR", ""),
        )

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_chief(self) -> bool:
        """Chief ≡ process 0 (the v1 chief termination policy maps here)."""
        return self.process_id == 0


def apply_platform_env(env: Optional[dict] = None) -> None:
    """Honor K8S_TPU_PLATFORM (e.g. "cpu") from the pod env.

    This image's sitecustomize pins the axon TPU platform before env vars
    apply, so CPU pods (e2e kubelet subprocesses, CPU-only node pools) need
    the platform re-forced via jax.config after import — the operator can
    inject this var like any other pod env."""
    e = env if env is not None else os.environ
    platform = e.get("K8S_TPU_PLATFORM", "")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def initialize_distributed(config: Optional[LauncherConfig] = None) -> LauncherConfig:
    """Idempotent jax.distributed bring-up from the operator env contract."""
    global _initialized
    apply_platform_env()
    cfg = config or LauncherConfig.from_env()
    if not cfg.is_distributed:
        log.info("single-process job; skipping jax.distributed")
        return cfg
    if _initialized:
        return cfg
    if not cfg.coordinator_address:
        raise RuntimeError(
            "JAX_NUM_PROCESSES > 1 but JAX_COORDINATOR_ADDRESS is not set - "
            "was this pod created by the tpu-job operator?"
        )
    import jax

    # Multi-process CPU worlds (localhost e2e gangs, CPU node pools) need
    # an explicit cross-process collectives backend: without one the CPU
    # client is built with collectives=None and every computation that
    # spans processes dies with "Multiprocess computations aren't
    # implemented on the CPU backend".  The gloo TCP implementation rides
    # the same coordinator jax.distributed just connected.  Must happen
    # BEFORE the first backend touch; idempotent and a no-op for TPU.
    platform = os.environ.get("K8S_TPU_PLATFORM", "") or \
        os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in platform:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception as e:  # noqa: BLE001 - older jaxlib: no gloo build
            log.warning("cpu collectives impl not configurable: %s", e)

    log.info(
        "jax.distributed.initialize(coordinator=%s, num_processes=%d, process_id=%d)",
        cfg.coordinator_address, cfg.num_processes, cfg.process_id,
    )
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    _initialized = True
    return cfg


def make_training_mesh(
    tp: int = 1,
    sp: int = 1,
    fsdp: Optional[int] = None,
    config: Optional[LauncherConfig] = None,
    *,
    pp: int = 1,
):
    """Build the global training mesh over all devices of the job.

    Multi-slice layout (MEGASCALE_NUM_SLICES > 1): data parallelism spans
    slices over DCN via the explicit two-level hybrid mesh
    (parallel.mesh.make_hybrid_mesh — slice boundary guaranteed on the
    outer stride); all other axes stay within a slice on ICI (callers
    choose tp*sp <= devices-per-slice).
    """
    import jax

    cfg = config or LauncherConfig.from_env()
    n = len(jax.devices())
    if cfg.num_slices > 1:
        from k8s_tpu.parallel.mesh import DcnConfig, make_hybrid_mesh

        if n % cfg.num_slices != 0:
            raise ValueError(
                f"{n} devices not divisible by {cfg.num_slices} slices")
        ici = MeshConfig.auto(n // cfg.num_slices, tp=tp, sp=sp, fsdp=fsdp,
                              pp=pp)
        mesh = make_hybrid_mesh(ici, DcnConfig(dp=cfg.num_slices))
    else:
        mesh = make_mesh(MeshConfig.auto(n, tp=tp, sp=sp, fsdp=fsdp, pp=pp))
    log.info("mesh: %s over %d devices (%d slice(s))",
             dict(mesh.shape), n, cfg.num_slices)
    return mesh, cfg


_profiler_server = None
_profiler_port = None
_trace_active = False
_trace_dir = None


def setup_observability(env: Optional[dict] = None) -> dict:
    """Surface JAX profiler / XLA dump hooks from operator-injected env
    (SURVEY.md §5 "Tracing / profiling": the reference had none; the rebuild
    exposes them as launcher env, injected like any other pod env var).

    Recognized:
      JAX_PROFILER_PORT  start jax.profiler.start_server(port) — a pod-local
                         endpoint TensorBoard/xprof can connect to
      JAX_PROFILE_DIR    start a programmatic trace now; stop_trace() at
                         job teardown captures the whole run
      XLA_DUMP_TO        appended to XLA_FLAGS as --xla_dump_to (effective
                         only if jax has not initialized a backend yet)

    Returns {hook: value} for what was enabled.
    """
    global _profiler_server, _profiler_port, _trace_active, _trace_dir
    e = env if env is not None else os.environ
    enabled: dict = {}

    dump_to = e.get("XLA_DUMP_TO", "")
    if dump_to:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_dump_to" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_dump_to={dump_to}").strip()
            enabled["xla_dump_to"] = dump_to
        else:
            # Report where dumps actually go: an existing flag wins (XLA
            # reads it once), so claiming the requested path would send
            # whoever debugs a compile to an empty directory.
            existing = [f for f in flags.split() if "--xla_dump_to" in f]
            actual = existing[0].split("=", 1)[-1] if existing else dump_to
            enabled["xla_dump_to"] = actual
            if actual != dump_to:
                log.warning(
                    "XLA_DUMP_TO=%s ignored: XLA_FLAGS already dumps to %s",
                    dump_to, actual)

    port = e.get("JAX_PROFILER_PORT", "")
    if port:
        import jax

        if _profiler_server is None:
            _profiler_server = jax.profiler.start_server(int(port))
            _profiler_port = int(port)
        elif _profiler_port != int(port):
            log.warning(
                "JAX_PROFILER_PORT=%s ignored: server already on %s",
                port, _profiler_port)
        # report where the server actually listens
        enabled["profiler_port"] = _profiler_port

    profile_dir = e.get("JAX_PROFILE_DIR", "")
    if profile_dir:
        import jax

        if not _trace_active:
            jax.profiler.start_trace(profile_dir)
            _trace_active = True
            _trace_dir = profile_dir
        elif _trace_dir != profile_dir:
            log.warning(
                "JAX_PROFILE_DIR=%s ignored: trace already writing to %s",
                profile_dir, _trace_dir)
        enabled["profile_dir"] = _trace_dir

    return enabled


def stop_observability(env: Optional[dict] = None) -> None:
    """Stop a JAX_PROFILE_DIR trace (call at job teardown, chief included).
    No-op when no trace was actually started — teardown must not mask the
    job's real exit status."""
    global _trace_active, _trace_dir
    del env  # kept for call-site symmetry with setup_observability
    if _trace_active:
        import jax

        jax.profiler.stop_trace()
        _trace_active = False
        _trace_dir = None


def barrier(name: str = "launcher") -> None:
    """Cross-process sync point (used before checkpoint writes / teardown)."""
    import jax

    if jax.process_count() > 1:
        # psum over a tiny array forces a global collective
        import jax.numpy as jnp

        jax.block_until_ready(
            jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
                jnp.ones((jax.local_device_count(),))
            )
        )

"""Distributed smoke workload (the tpu analogue of
examples/tf_sample/tf_sample/tf_smoke.py).

The reference smoke test placed a matmul on every task of the gRPC cluster
and summed the results on the master (tf_smoke.py:52-60).  Here every
process joins jax.distributed, a matmul runs on every device of the mesh,
and a psum verifies the collective path over ICI/DCN.  Exit code 0 on
success — the operator's chief (process 0) exit-code contract.

Run inside a pod:  python -m k8s_tpu.launcher.tpu_smoke
"""

from __future__ import annotations

import logging
import sys

log = logging.getLogger(__name__)


def run_smoke(size: int = 1024, iters: int = 3) -> float:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from k8s_tpu.launcher.bootstrap import initialize_distributed, make_training_mesh

    cfg = initialize_distributed()
    mesh, _ = make_training_mesh()

    @jax.jit
    def step(x):
        y = x @ x.T
        # sum over every mesh axis: exercises the full collective fabric
        total = jnp.sum(y)
        return total

    batch = jax.device_put(
        jnp.ones((size, size), jnp.bfloat16),
        NamedSharding(mesh, P(("dp", "fsdp"), None)),
    )
    checksum = 0.0
    for i in range(iters):
        checksum = float(step(batch))
        log.info("iter %d checksum %.1f", i, checksum)

    expected = float(size) * size * size
    if abs(checksum - expected) / expected > 1e-2:
        raise RuntimeError(f"smoke checksum {checksum} != expected {expected}")
    if cfg.is_chief:
        log.info("smoke OK on %d devices", len(jax.devices()))
    return checksum


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    try:
        run_smoke()
    except Exception:
        log.exception("smoke failed")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Vision Transformer: patch embedding + the repo's Transformer encoder.

Reference parity note: the reference's vision workloads are CNNs
(dist-mnist, and the rebuild's ResNet-50 per BASELINE.json); ViT extends
the model-family coverage with the dominant modern vision architecture
while REUSING the LM stack wholesale — `transformer.Block` runs
bidirectionally (config.causal=False), positions are passed as zeros so
RoPE degenerates to the identity rotation (cos 0 = 1, sin 0 = 0) and the
standard ViT learned position embedding does the positional work.  Fused
RMSNorm, remat, and the FSDP sharding rules therefore apply to ViT
unchanged.  Attention is the plain XLA path by design: the 197-token
sequence (196 patches + cls) cannot align to the flash kernels' block
tiling, and at this length the O(L^2) scores are small enough that XLA's
fused attention is the right tool — flash earns its keep at LM context
lengths, not here.

TPU-first choices: the patch embed is a strided Conv (one big MXU matmul
per image at patch granularity), tokens stay [B, 197, hidden] static, and
bf16 activations with f32 params follow the LM configs.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from k8s_tpu.models.transformer import Block, RMSNorm, TransformerConfig


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden: int = 768
    # the blocks use SwiGLU (three FFN matrices), so the canonical ViT-B
    # parameter budget calls for 2/3 of the classic 4*hidden width —
    # 2048, the same reparameterization Llama applies (total ~86M params,
    # matching ViT-B/16)
    ffn_hidden: int = 2048
    layers: int = 12
    heads: int = 12
    dtype: Any = jnp.bfloat16
    pool: str = "cls"  # "cls" token | "mean" over patch tokens
    use_fused_norm: bool = False
    remat: bool = True

    @property
    def num_patches(self) -> int:
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image_size {self.image_size} not divisible by "
                f"patch_size {self.patch_size}")
        n = self.image_size // self.patch_size
        return n * n

    def block_config(self) -> TransformerConfig:
        """The encoder blocks' TransformerConfig: bidirectional, no
        window, sequence = patches + cls token."""
        return TransformerConfig(
            vocab_size=1,  # unused: ViT embeds patches, not tokens
            hidden=self.hidden, ffn_hidden=self.ffn_hidden,
            layers=self.layers, heads=self.heads, kv_heads=self.heads,
            max_seq_len=self.num_patches + 1, causal=False,
            dtype=self.dtype, remat=self.remat,
            # plain XLA attention: 197 tokens can't align to the flash
            # kernels' tiling and don't need them (module docstring)
            use_flash_attention=False,
            use_fused_norm=self.use_fused_norm,
        )


def vit_b16(**overrides) -> ViTConfig:
    """ViT-B/16 (the standard base config)."""
    return ViTConfig(**overrides)


def vit_tiny_test() -> ViTConfig:
    """CPU-testable config."""
    return ViTConfig(image_size=32, patch_size=8, num_classes=10,
                     hidden=64, ffn_hidden=128, layers=2, heads=4,
                     dtype=jnp.float32, remat=False)


class ViT(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        """[B, H, W, C] images -> [B, num_classes] logits.

        ``train`` is accepted for API symmetry with resnet50 (ViT has no
        batch-stat state; dropout-free following the modern recipe).
        """
        cfg = self.config
        del train
        B = images.shape[0]
        bc = cfg.block_config()

        x = nn.Conv(
            cfg.hidden, kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size), padding="VALID",
            use_bias=True, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="patch_embed",
        )(images.astype(cfg.dtype))
        x = x.reshape(B, cfg.num_patches, cfg.hidden)

        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.hidden), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, cfg.hidden)).astype(cfg.dtype), x],
            axis=1)
        pos = self.param("pos_embedding", nn.initializers.normal(0.02),
                         (1, cfg.num_patches + 1, cfg.hidden), jnp.float32)
        x = x + pos.astype(cfg.dtype)

        # positions=0 everywhere: RoPE at position 0 is the identity, so
        # the learned pos_embedding above is the only positional signal —
        # and the LM Block is reused verbatim
        zeros = jnp.zeros((B, cfg.num_patches + 1), jnp.int32)
        block = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.layers):
            x = block(bc, name=f"layer_{i}")(x, zeros)

        x = RMSNorm(fused=cfg.use_fused_norm, name="final_norm")(x)
        if cfg.pool == "cls":
            feat = x[:, 0]
        elif cfg.pool == "mean":
            feat = jnp.mean(x[:, 1:], axis=1)
        else:
            raise ValueError(f"unknown pool {cfg.pool!r} "
                             "(expected 'cls' or 'mean')")
        return nn.Dense(
            cfg.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
            name="head",
        )(feat.astype(jnp.float32))

"""Tiered KV memory hierarchy (ISSUE 17): the host-RAM spill tier
beneath the engine's device block pool, plus the block-chain
fingerprint scheme shared by the spill tier, the kvxfer dedup
handshake, and the fleet prefix cache index.

The pool (models/kvblocks.py) is device-memory-only: when the free
list dries the radix tree LRU-evicts leaves, and before this module
an evicted prefix meant a full re-prefill on the next hit.  The spill
tier turns evict-means-recompute into demote-means-requantize:

- **Demote** — the engine gathers the victim leaf's block content
  through the same ``gather_blocks`` chain seam migration exports
  ride, quantizes float K/V leaves to int8 through the ONE
  ``paged.quantize_kv`` definition (native-int8 pools and their scale
  leaves store bitwise as-is), and parks the payload here keyed by the
  leaf's cumulative chain fingerprint.
- **Promote** — on a prefix-tree miss whose chain fingerprint IS
  resident, the engine allocates fresh pool blocks and grafts the
  dequantized payload back through the same ``graft_blocks`` scatter
  the kv-transfer plane uses, then re-inserts the tree nodes; the
  attaching request sees an ordinary tree hit.

Identity contract (mirrors the migration wire): int8 pools round-trip
bit-exactly (int8 payloads are stored and grafted untouched); float
pools round-trip through int8 quantization and are documented-lossy
EXACTLY like a kvxfer migrate with ``wire_int8`` — same quantizer,
same dequant expression — so a demote→promote never introduces a
loss mode the wire doesn't already have.

The tier is bounded (``K8S_TPU_SERVE_SPILL_MB``, default 0 = off) with
its own LRU over host bytes; it holds HOST COPIES only — never a pool
block reference — so ``debug_check_blocks`` refcount accounting is
unchanged and a demoted payload can never alias a live device block.

Chain fingerprints: the cumulative fingerprint at block ``k`` equals
``router.ring.fingerprint_tokens(tokens, block_size, k)`` — one hash
scheme across the router's affinity keys, the spill tier's entry keys,
the kvxfer dedup offer frames, and the fleet index advertisements, so
every layer of the hierarchy agrees about which bytes a fingerprint
names.
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict
from typing import Optional

import numpy as np

DEFAULT_SPILL_MB = 0


def env_spill_mb() -> int:
    """Host-RAM spill budget in MiB (``K8S_TPU_SERVE_SPILL_MB``,
    default 0 = spill tier off — seed behaviour: evicted leaves die)."""
    raw = os.environ.get("K8S_TPU_SERVE_SPILL_MB", "").strip()
    if not raw:
        return DEFAULT_SPILL_MB
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"K8S_TPU_SERVE_SPILL_MB must be an integer, got {raw!r}")
    if val < 0:
        raise ValueError(
            f"K8S_TPU_SERVE_SPILL_MB must be >= 0, got {val}")
    return val


def chain_fingerprints(tokens, block_size: int,
                       max_blocks: Optional[int] = None) -> list[str]:
    """Cumulative chain fingerprint at every full-block boundary of
    ``tokens``: entry ``k`` covers blocks ``0..k`` and equals
    ``ring.fingerprint_tokens(tokens, block_size, k + 1)`` — the
    router's affinity fingerprint IS the chain fingerprint at its
    affinity depth.  Computed incrementally (one hasher, snapshotted
    per boundary), so fingerprinting a whole prompt chain costs one
    pass over its tokens."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    n_full = len(tokens) // block_size
    if max_blocks is not None:
        n_full = min(n_full, max(0, max_blocks))
    h = hashlib.sha1()
    h.update(f"{block_size}:".encode())
    out: list[str] = []
    for k in range(n_full):
        for t in tokens[k * block_size:(k + 1) * block_size]:
            h.update(f"{int(t)},".encode())
        out.append(h.hexdigest())
    return out


def _is_kv_leaf(path: str, dtype) -> bool:
    """Float K/V leaves quantize on demote; everything else (native
    int8 K/V, their scale leaves) stores as-is.  Same test the serving
    wire applies in ``server._wire_blocks``."""
    return path.rsplit("/", 1)[-1] in ("k", "v") and (
        np.issubdtype(np.dtype(dtype), np.floating))


def encode_payload(flat: dict, quantize_kv) -> tuple[dict, int]:
    """Pack one gathered block's flat leaves ``{path: array[bs, ...]}``
    into a host spill payload: float K/V leaves become ``(q int8,
    scale f32)`` via the one ``quantize_kv`` (passed in so this module
    stays importable without jax at collection time); other leaves are
    stored native.  Returns ``(payload, nbytes)``."""
    payload: dict[str, tuple] = {}
    nbytes = 0
    for path, arr in flat.items():
        if _is_kv_leaf(path, arr.dtype):
            q, scale = quantize_kv(arr)
            q = np.asarray(q, np.int8)
            scale = np.asarray(scale, np.float32)
            payload[path] = ("q8", q, scale)
            nbytes += q.nbytes + scale.nbytes
        else:
            host = np.asarray(arr)
            payload[path] = ("raw", host)
            nbytes += host.nbytes
    return payload, nbytes


def decode_payload(payload: dict) -> dict:
    """Inverse of :func:`encode_payload`: flat ``{path: array}`` ready
    for the graft scatter.  Dequant is the wire's exact expression
    (``q.astype(f32) * scale[..., None]``); the graft itself casts to
    each pool leaf's dtype, so int8 pools receive their stored int8
    bytes untouched."""
    out: dict[str, np.ndarray] = {}
    for path, packed in payload.items():
        if packed[0] == "q8":
            _, q, scale = packed
            out[path] = q.astype(np.float32) * scale[..., None]
        else:
            out[path] = packed[1]
    return out


class SpillEntry:
    __slots__ = ("fingerprint", "tokens", "payload", "nbytes")

    def __init__(self, fingerprint: str, tokens: tuple, payload: dict,
                 nbytes: int):
        self.fingerprint = fingerprint  # cumulative chain fp at this block
        self.tokens = tokens            # this block's token run (len == bs)
        self.payload = payload          # {path: ("q8", q, scale) | ("raw", a)}
        self.nbytes = nbytes


class SpillTier:
    """Byte-budgeted host LRU over demoted blocks, keyed by cumulative
    chain fingerprint.  Single-threaded by design: every mutation
    happens on the engine thread (the same no-locks contract
    kvblocks.py states); cross-thread readers (the fleet index proxy
    metric, the kvxfer dedup index_fn) only take GIL-atomic snapshots
    through :meth:`fingerprints`."""

    def __init__(self, budget_bytes: int):
        if budget_bytes < 0:
            raise ValueError(
                f"spill budget must be >= 0, got {budget_bytes}")
        self.budget_bytes = budget_bytes
        self._entries: "OrderedDict[str, SpillEntry]" = OrderedDict()
        self._bytes = 0
        # lifetime counters (engine stats() + serving metrics read them)
        self.spilled_blocks = 0
        self.promoted_blocks = 0
        self.spill_evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, fingerprint: str) -> bool:
        return fingerprint in self._entries

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def put(self, fingerprint: str, tokens: tuple, payload: dict,
            nbytes: int) -> bool:
        """Admit one demoted block; evicts the LRU tail until the
        budget holds.  A payload larger than the whole budget is
        refused (False) — the tier never admits-then-immediately-drops.
        Re-admitting a resident fingerprint just refreshes its LRU
        position (block content for a chain fingerprint is immutable,
        so the stored bytes are already right)."""
        if fingerprint in self._entries:
            self._entries.move_to_end(fingerprint)
            return True
        if nbytes > self.budget_bytes:
            return False
        while self._bytes + nbytes > self.budget_bytes and self._entries:
            _, victim = self._entries.popitem(last=False)
            self._bytes -= victim.nbytes
            self.spill_evictions += 1
        self._entries[fingerprint] = SpillEntry(
            fingerprint, tuple(tokens), payload, nbytes)
        self._bytes += nbytes
        self.spilled_blocks += 1
        return True

    def touch(self, fingerprint: str) -> bool:
        """True + LRU refresh when the fingerprint is already resident
        (a re-demote of an immutable chain block needs no re-gather and
        no re-quantize — the stored bytes are already right)."""
        if fingerprint in self._entries:
            self._entries.move_to_end(fingerprint)
            return True
        return False

    def get(self, fingerprint: str) -> Optional[SpillEntry]:
        """LRU-refreshing lookup; the entry STAYS resident — a promote
        copies bytes back to the pool, and keeping the host copy means
        the next demote of the same chain is a pure tree-reference drop
        (no re-gather, no re-quantize)."""
        entry = self._entries.get(fingerprint)
        if entry is not None:
            self._entries.move_to_end(fingerprint)
            self.promoted_blocks += 1
        return entry

    def peek(self, fingerprint: str) -> Optional[SpillEntry]:
        """Lookup without LRU refresh or promote accounting (dedup
        index probes, tests)."""
        return self._entries.get(fingerprint)

    def fingerprints(self) -> list[str]:
        """Resident chain fingerprints, LRU → MRU.  GIL-atomic snapshot
        safe to call off the engine thread (fleet index, dedup
        index_fn)."""
        return list(self._entries)

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0

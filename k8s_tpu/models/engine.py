"""Slot-based continuous-batching inference engine (the serving TFJob's
throughput core).

The resident HTTP server (models/server.py) used to be single-flight: one
lock around all device work, batch size 1, a long generation blocking
every short one behind it.  This module replaces that with
Orca/vLLM-style iteration-level scheduling plus (round 6) a paged KV
cache with shared-prefix reuse and a batched sampling lane:

- a fixed pool of ``B`` decode **slots**; for full-cache configs each
  slot references a per-request **block table** over one shared
  block-granular KV pool (``[num_blocks, block_size, kv_heads,
  head_dim]`` per layer) instead of owning a fixed ``max_seq_len`` row —
  persistent KV memory is ``num_blocks x block_size``, deduplicated
  across requests, no longer ``slots x max_seq_len`` by construction;
- a **radix prefix tree** (models/kvblocks.py) caches block-sized
  token runs: a request walks the tree, attaches to already-prefilled
  blocks **by reference** (refcounted), copy-on-writes the divergence
  block when the match ends mid-block, and prefills only its unshared
  tail — templated traffic prefills the common prefix once per process,
  not once per request;
- incoming tails are **prefilled** through the chunked decode-mode cache
  path with exact per-token positions (no left-pad RoPE corruption) in
  bucket-sized chunks (decode.prefill_buckets_for / split_prefill), then
  land in the request's own pool blocks;
- one **batched decode step** advances every active slot per iteration,
  addressing the block pool DIRECTLY through per-row block tables behind
  the ``paged_attention`` seam (models/paged.py, round 9): new K/V
  scatters straight into pool blocks and attention gathers them in
  table order — no per-row view is materialized or written back per
  fused window, and a Pallas TPU kernel can replace the seam's body
  without touching this engine; requests join and retire *between*
  steps, so a long generation never serializes short ones;
- **sampling rides the batch** (round 6): per-slot RNG keys, temperature
  and top-k are threaded through the batched step and
  ``decode.sample_logits_rows`` draws each row from its own distribution
  with the exact key schedule of the exclusive lane's program — a
  fixed-seed ``temperature>0`` request emits token-identical output on
  either lane (asserted in tests);
- **speculative decoding rides the batch too** (round 9): a spec slot
  verifies its ``draft_k``-token prompt-lookup chunk in the SAME model
  call that advances its 1-token neighbors — per-slot widths with
  write-masked padding lanes (a masked lane rides at position -1 and
  its K/V write is dropped, so a mixed-width batch never scribbles past
  a short row's block capacity), host-side drafting mirroring the
  exclusive lane's ``lookup_draft`` exactly, and the shared
  ``decode.spec_accept_*`` rejection-sampling path with the exclusive
  lane's per-iteration key schedule — fixed-seed batched spec output is
  token-identical to ``make_speculative_generate_fn``.  Spec slots with
  different ``draft_k`` values are grouped per step (round-robin across
  groups) so the chunk width stays uniform and per-row random draws
  keep the exclusive lane's shapes.  Beam requests (and speculative on
  windowed configs, whose dense rows have no write-maskable pool) still
  take the **exclusive lane**;
- compile count stays bounded: one prefill program per USED bucket, one
  batched decode program per (fused width, sampling, spec) tuple
  actually used, and a constant set of pool auxiliaries (copy-on-write,
  block reset, row scatter) — never per prefix length;
- a **bounded admission queue** gives backpressure: when it is full,
  submit() raises :class:`QueueFull` and the HTTP layer answers 503 with
  ``Retry-After`` (readiness is not not-busy — /healthz stays 200 while
  shedding).

Sliding-window configs keep the pre-paging dense slot rows (their ring
cache is position-wrapped per row and does not decompose into shareable
absolute-position blocks); prefix reuse is a full-cache feature.

Knobs: ``K8S_TPU_SERVE_SLOTS`` (decode slots, default 4; the server
treats 0 as "engine off" → legacy single-flight),
``K8S_TPU_SERVE_QUEUE`` (admission queue bound, default 64), and
``K8S_TPU_SERVE_PREFIX_BLOCKS`` (extra pool blocks retained for the
prefix tree beyond the ``1 + slots x blocks_per_row`` floor; 0 disables
prefix reuse, unset auto-sizes to two full-length rows).  The
``K8S_TPU_SERVE_BATCH_SAMPLING`` and ``K8S_TPU_SERVE_BATCH_SPEC``
lane-routing knobs live in the server.

Round 14 (ISSUE 14): the engine's device programs live behind a
**placement-agnostic seam** (models/placement.py).  The slot scheduler,
block-pool bookkeeping, and batch-plan construction in this module are
host-side Python and run on ONE chief process; the jitted compute
bodies (models/placement.PagedCompute) are compiled by a ``Placement``
— ``LocalPlacement`` (plain jit, byte-for-byte the single-host path) or
``MeshPlacement`` (models/mesh_serve.py: params tensor-sharded over a
``tp`` mesh axis, the KV block pool sharded per-host along the head
axis but addressed by the SAME block tables, the per-step batch plan
broadcast to worker processes over a stdlib plan bus and sampled tokens
collected replicated).  ``K8S_TPU_SERVE_MESH`` / ``K8S_TPU_SERVE_TP``
select the mesh placement; unset keeps this file's original behavior.

Round 15 (ISSUE 15): the engine disaggregates.  :meth:`Engine.
prefill_export` runs a request in prefill-only mode — the ordinary
slot path (prefix reuse, tree insert), first token, then the block
chain exported host-side in ONE gather call and the slot released, no
decode seat held — and :meth:`Engine.submit_prefilled` seats a request
directly from an imported chain (one graft scatter into fresh blocks,
tree graft so the migrated prefix is immediately shareable, the
migrated PRNG carry continuing the exact key schedule).  The wire
between them is models/kvxfer.py; models/server.py owns the role
routing (``K8S_TPU_SERVE_ROLE``).  Fixed-seed migrated output is
token-identical to local output on every lane by construction.

Round 12: the engine narrates itself per request.  With
``K8S_TPU_REQUEST_LOG=1`` (models/requestlog.py) every request gets a
bounded timeline — queue wait, prefill chunks with the prefix-reuse
outcome, every decode step its slot rode, spec propose/accept counts,
evictions it caused, retire reason — closed with a dominant-phase
attribution (queue|prefill|decode|spec_reject|compile|evict), plus a
per-iteration engine step ledger; TTFT/TPOT/queue-wait/step-duration
histograms and the prefill-convoy counter flow through the serving
metrics family regardless of the recorder knob.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import threading
import time
from k8s_tpu.analysis import checkedlock
from k8s_tpu.analysis import compileledger
from k8s_tpu.models import requestlog
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from k8s_tpu.models import kvtier
from k8s_tpu.models import placement as placement_lib
from k8s_tpu.models.decode import prefill_buckets_for, split_prefill
from k8s_tpu.models.kvblocks import BlockPool, PrefixTree, chain_tokens

log = logging.getLogger(__name__)

DEFAULT_SLOTS = 4
DEFAULT_QUEUE = 64
# preferred KV block size (tokens); clamped into the bucket set so block
# boundaries line up with prefill chunk boundaries
DEFAULT_BLOCK = 16
# fused decode: up to this many batched iterations run as ONE program
# (lax.scan) when no active row can retire mid-scan (no EOS condition,
# >= k tokens remaining everywhere) — the pool gather, write-back, and
# host round-trip amortize over k tokens.  Joins and exclusive-lane
# work wait at most k-1 extra iterations (~a few ms); the paged step
# compiles one program per used k, bounded by this constant.
MAX_STEP_TOKENS = 4


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        val = int(raw)
    except ValueError:
        if raw:
            log.warning("ignoring non-integer %s=%r", name, raw)
        return default
    if val < 0:
        log.warning("ignoring negative %s=%d", name, val)
        return default
    return val


def env_slots() -> int:
    """K8S_TPU_SERVE_SLOTS (>= 0; 0 = single-flight, engine off)."""
    return _env_int("K8S_TPU_SERVE_SLOTS", DEFAULT_SLOTS)


def env_queue() -> int:
    """K8S_TPU_SERVE_QUEUE admission bound (0 rejects everything)."""
    return _env_int("K8S_TPU_SERVE_QUEUE", DEFAULT_QUEUE)


def env_prefix_blocks() -> Optional[int]:
    """K8S_TPU_SERVE_PREFIX_BLOCKS: pool blocks retained for the prefix
    tree beyond the slot floor (0 = prefix reuse off; unset = auto)."""
    if "K8S_TPU_SERVE_PREFIX_BLOCKS" not in os.environ:
        return None
    return _env_int("K8S_TPU_SERVE_PREFIX_BLOCKS", 0)


def env_batch_sampling() -> bool:
    """K8S_TPU_SERVE_BATCH_SAMPLING: route temperature>0 requests onto
    the batched slot lanes (default on; 0/false restores the exclusive
    single-flight routing — the pre-round-6 behavior and the bench
    baseline).  Consumed by models/server.py's lane routing."""
    raw = os.environ.get("K8S_TPU_SERVE_BATCH_SAMPLING", "").strip().lower()
    if raw in ("0", "false", "no", "off"):
        return False
    return True


def env_batch_spec() -> bool:
    """K8S_TPU_SERVE_BATCH_SPEC: route speculative requests onto the
    batched slot lanes (default on; 0/false restores the exclusive
    single-flight routing — the pre-round-9 behavior and the bench
    baseline).  Consumed by models/server.py's lane routing; windowed
    configs ride the exclusive lane regardless (their dense rows have no
    write-maskable block pool)."""
    raw = os.environ.get("K8S_TPU_SERVE_BATCH_SPEC", "").strip().lower()
    if raw in ("0", "false", "no", "off"):
        return False
    return True


class QueueFull(RuntimeError):
    """Admission queue at capacity; carries the Retry-After hint."""

    def __init__(self, depth: int, limit: int, retry_after_s: float = 1.0):
        super().__init__(
            f"admission queue full ({depth}/{limit} waiting)")
        self.depth = depth
        self.limit = limit
        self.retry_after_s = retry_after_s


class EngineClosed(RuntimeError):
    pass


class PoolExhausted(RuntimeError):
    """The KV block pool cannot take an imported block chain right now
    (disaggregated receive-side backpressure, ISSUE 15): every free and
    tree-evictable block counted, the migrated chain still does not
    fit.  The sender maps this to a 503-class refusal so the router's
    retry walk re-places the request instead of wedging the decode
    pod."""

    def __init__(self, needed: int, available: int,
                 retry_after_s: float = 1.0):
        super().__init__(
            f"KV pool cannot seat {needed} migrated blocks "
            f"({available} free+evictable)")
        self.needed = needed
        self.available = available
        self.retry_after_s = retry_after_s


class DedupStale(RuntimeError):
    """A deduped migration promised prefix blocks this engine no longer
    holds (evicted between the OP_NEED answer and the seat, ISSUE 17).
    The ``kind`` travels back as a typed kvxfer error frame; the sender
    re-sends the full chain once — the dedup index is advisory, the
    seat path is the truth."""

    kind = "dedup_stale"


def _flatten_tree(tree) -> dict:
    """Nested-dict pytree → flat ``{"a/b/k": np.ndarray}`` host dict
    (the kv-transfer wire shape; models/kvxfer.py never sees a pytree)."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        # sync-ok: export boundary — one host fetch per exported block
        # chain, never per decode step
        out[key] = np.asarray(leaf)
    return out


def _unflatten_tree(flat: dict) -> dict:
    root: dict = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = root
        for k in parts[:-1]:
            node = node.setdefault(k, {})
        node[parts[-1]] = arr
    return root


@dataclasses.dataclass
class _Request:
    """One queued unit of work: either a batched generation (``ids``
    set; greedy or sampled) or an exclusive-lane callable (``fn``)."""

    ids: Optional[np.ndarray] = None
    max_new_tokens: int = 0
    eos_id: Optional[int] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    seed: int = 0
    speculative: int = 0  # draft_k (>= 2) for batched spec; 0 = off
    fn: Optional[Callable[[], Any]] = None
    # disaggregated serving (ISSUE 15): a prefill-only request emits
    # first token + block manifest and retires without a decode slot; a
    # manifest-carrying request seats directly from imported blocks
    export: bool = False
    manifest: Optional[dict] = None
    seated_cb: Optional[Callable[[], None]] = None
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    # observability (ISSUE 12): request-recorder timeline id, the
    # remote trace context the ingress extracted from the inbound W3C
    # traceparent, submit stamp and first-token latency (TTFT)
    rid: Optional[int] = None
    trace_ctx: Optional[tuple] = None
    t_submit: float = 0.0
    ttft_s: Optional[float] = None

    def finish(self, result=None, error=None) -> None:
        self.result = result
        self.error = error
        self.done.set()


class _Slot:
    """One decode slot: generation state plus either a block table over
    the shared pool (paged mode) or one batch row of the dense cache
    (windowed fallback).  ``ready`` flips True once prefill landed."""

    __slots__ = ("idx", "req", "pos", "last", "tokens", "ready",
                 "key", "table", "nblocks", "ctx")

    def __init__(self, idx: int, maxb: int):
        self.idx = idx
        self.req: Optional[_Request] = None
        self.pos = 0          # absolute position of the NEXT cache write
        self.last = 0         # last emitted token (fed to the next step)
        self.tokens: list[int] = []
        self.ready = False
        self.key = np.zeros(2, np.uint32)   # per-slot PRNG carry
        self.table = np.zeros(maxb, np.int32)  # pool block ids (0 = null)
        self.nblocks = 0
        # full context (prompt + emitted) for speculative slots only:
        # host-side prompt-lookup drafting reads it every verify step
        self.ctx: Optional[list[int]] = None

    @property
    def free(self) -> bool:
        return self.req is None

    def clear(self) -> None:
        self.req = None
        self.tokens = []
        self.ready = False
        self.table[:] = 0
        self.nblocks = 0
        self.ctx = None


class Engine:
    """Continuous-batching decode engine over one model + params.

    All device work happens on the single engine thread; callers block in
    :meth:`submit` / :meth:`submit_exclusive` on a per-request event.
    """

    def __init__(self, config, params, *, slots: Optional[int] = None,
                 queue_limit: Optional[int] = None,
                 buckets: Optional[tuple] = None, pad_id: int = 0,
                 block_size: Optional[int] = None,
                 prefix_blocks: Optional[int] = None,
                 metrics: Optional[dict] = None,
                 placement=None,
                 spill_mb: Optional[int] = None):
        if slots is None:
            slots = env_slots() or DEFAULT_SLOTS
        if slots < 1:
            raise ValueError(f"engine needs slots >= 1, got {slots}")
        if queue_limit is None:
            queue_limit = env_queue()
        self.config = config
        # the placement seam (ISSUE 14): LocalPlacement is today's plain
        # single-device jit; MeshPlacement shards the same compute
        # bodies over a tp process mesh.  The scheduler below never
        # branches on it — only compilation and host<->device transfer
        # differ.
        self._placement = placement if placement is not None \
            else placement_lib.LocalPlacement()
        if self._placement.is_mesh and config.window_size is not None:
            raise ValueError(
                "mesh serving needs the paged block pool; windowed "
                "configs keep dense per-slot rows and stay single-host")
        self._compute = placement_lib.PagedCompute(
            config, apply_mesh=self._placement.mesh)
        self._model = self._compute.model
        self.params = self._placement.globalize_params(params)
        self.pad_id = pad_id
        self.queue_limit = queue_limit
        self.buckets = tuple(sorted(buckets or prefill_buckets_for(config)))
        if not self.buckets or self.buckets[0] != 1:
            raise ValueError(
                f"buckets must include 1 so every prompt length "
                f"decomposes, got {self.buckets}")
        if config.window_size and \
                self.buckets[-1] > max(1, config.prefill_chunk):
            raise ValueError(
                f"bucket {self.buckets[-1]} exceeds prefill_chunk "
                f"({config.prefill_chunk}): a windowed ring cache only "
                "holds window + prefill_chunk - 1 slots")
        self.metrics = metrics or {}
        self._queue: deque[_Request] = deque()
        self._cond = checkedlock.make_condition("engine.cond")
        self._closed = False
        self._crashed = False

        # paged block cache (full-cache configs only): a windowed ring
        # wraps positions per row and cannot share absolute-position
        # blocks, so it keeps the dense per-slot rows
        self.paged = config.window_size is None
        if block_size is None:
            block_size = max(b for b in self.buckets
                             if b <= DEFAULT_BLOCK)
        if block_size not in self.buckets:
            raise ValueError(
                f"block_size {block_size} must be one of the prefill "
                f"buckets {self.buckets} so block boundaries line up "
                "with chunk boundaries")
        self.block_size = block_size
        self._maxb = math.ceil(config.max_seq_len / block_size)
        if prefix_blocks is None:
            prefix_blocks = env_prefix_blocks()
        if prefix_blocks is None:
            prefix_blocks = 2 * self._maxb  # auto: ~two full-length rows
        self.prefix_blocks = prefix_blocks if self.paged else 0
        # pool floor: null block + worst-case fully-private slots, so
        # decode-time allocation can always succeed by evicting the tree
        self.pool_blocks = (1 + slots * self._maxb + self.prefix_blocks) \
            if self.paged else 0
        self._slots = [_Slot(i, self._maxb) for i in range(slots)]

        # jit program inventory — the compile-bound contract: one prefill
        # program per USED bucket size (lazy, tracked in _prefill_fns),
        # one batched decode step, plus shape-constant auxiliaries
        # (copy-on-write, block reset, row scatter, cache init) that
        # never grow with traffic or with distinct prefix lengths.
        self._prefill_fns: dict[int, Callable] = {}
        # (fused width, has-sampling, is-spec) step programs compiled
        # so far — spec verify steps are distinct programs from the
        # k-fused greedy/sampled scans at the same width
        self._step_ks: set[tuple[int, bool, bool]] = set()
        if self.paged:
            # one jit entry point; the fused iteration count k and the
            # has-sampling flag are static arguments, so the decode
            # program set is (widths used) x (greedy-only | sampling) —
            # an all-greedy batch pays a bare argmax, never the per-row
            # sort/split/categorical machinery.  resident_argnums marks
            # device-resident state (params/pool/tables) a mesh
            # placement keeps on every process; everything else is
            # per-step host plan data the chief broadcasts.
            self._step_fn = self._placement.wrap(
                "paged_step", self._compute.paged_step,
                donate_argnums=(1,), static_argnums=(6, 7),
                resident_argnums=(0, 1, 2))
            # the variable-width speculative step: chunk width W and the
            # sampling flag are static, so spec traffic adds one program
            # per (draft_k, sampling) pair actually used
            self._spec_fn = self._placement.wrap(
                "spec_step", self._compute.spec_step,
                donate_argnums=(1,), static_argnums=(7, 8),
                resident_argnums=(0, 1, 2))
            self._cow_fn = self._placement.wrap(
                "cow", self._compute.cow, donate_argnums=(0,),
                resident_argnums=(0,))
            # disaggregated block export/import (ISSUE 15): two
            # shape-constant programs — gather one block to the host,
            # graft one received block into a fresh local block.  A
            # mesh placement has no single-host pool to export from;
            # disaggregation composes tiers of single-host (or whole-
            # gang) pods, so the seams stay local-only for now.
            if not self._placement.is_mesh:
                self._gather_fn = self._placement.wrap(
                    "kv_gather", self._compute.gather_blocks,
                    resident_argnums=(0,))
                self._graft_fn = self._placement.wrap(
                    "kv_graft", self._compute.graft_blocks,
                    donate_argnums=(0,), resident_argnums=(0,))
            else:
                self._gather_fn = None
                self._graft_fn = None
            self._pool = self._placement.build_pool(
                self._compute.pool_manifest(self.params, self.pool_blocks,
                                            self.block_size))
            # wire-manifest metadata: {leaf path: (per-block tail shape,
            # dtype str)} — what submit_prefilled validates an imported
            # chain against before any device work (shapes/dtypes are
            # host metadata; no transfer happens here)
            self._pool_leaf_meta = {
                path: (tuple(leaf.shape[2:]), str(leaf.dtype))
                for path, leaf in self._iter_pool_leaves()}
            self._row_template = None  # dense-mode only; a dense
            # [1, max_seq_len] row would idle on device forever
            self._pool_alloc = BlockPool(self.pool_blocks)
            self._tree = PrefixTree(block_size) \
                if self.prefix_blocks > 0 else None
            # host-RAM spill tier (ISSUE 17): evicted tree leaves demote
            # to bounded host buffers instead of dying; 0 MB (the
            # default) keeps the pre-hierarchy evict-means-recompute
            # behavior.  Needs the gather/graft chain seams, so a mesh
            # placement (no local pool export) stays single-tier.
            if spill_mb is None:
                spill_mb = kvtier.env_spill_mb()
            self._spill = kvtier.SpillTier(int(spill_mb) * (1 << 20)) \
                if (self._tree is not None and int(spill_mb) > 0
                    and self._gather_fn is not None) else None
            self._cache = None
            # device-side table stack, refreshed only when a slot's
            # table changes (join/retire/growth) — not every step
            self._tables_dev = None
            self._tables_dirty = True
        else:
            self._step_fn = self._placement.wrap(
                "dense_step", self._compute.dense_step,
                donate_argnums=(1,), static_argnums=(7,),
                resident_argnums=(0, 1))
            self._scatter_fn = self._placement.wrap(
                "scatter", self._compute.scatter, donate_argnums=(0,),
                resident_argnums=(0,))
            self._row_template = self._compute.init_cache(self.params, 1)
            self._cache = self._compute.init_cache(self.params, slots)
            self._pool = None
            self._pool_alloc = None
            self._tree = None
            self._gather_fn = None
            self._graft_fn = None
            self._pool_leaf_meta = {}
            self._spill = None

        # runtime compile ledger (ISSUE 11, K8S_TPU_COMPILE_LEDGER=1):
        # every jit entry point becomes a declared SEAM with the compile
        # budget the engine's program inventory promises — one prefill
        # program per bucket, one decode program per (fused width,
        # sampling) pair, one spec program per (draft_k, sampling) pair,
        # a small shape-constant auxiliary set — and a recompile past any
        # budget raises CompileBudgetExceeded with the offending
        # fingerprint + stack.  Zero overhead when the ledger is off:
        # the raw jit functions are used unwrapped.
        self._ledger = compileledger.maybe_active()
        if self._ledger is not None:
            self._declare_seams()

        # request lifecycle recorder (ISSUE 12, K8S_TPU_REQUEST_LOG=1):
        # per-request timelines (queue wait, prefill chunks + prefix
        # outcome, decode-step participation, spec propose/accept,
        # evictions, retire reason, dominant-phase attribution) plus the
        # engine step ledger — served at /debug/requests and
        # /debug/engine.  Zero overhead when off: every call site is
        # guarded on the None binding.
        self._reqlog = requestlog.maybe_active()

        # stats (mutated on the engine thread; read under _cond)
        self._steps = 0
        self._completed = 0
        # disaggregated migration counters (ISSUE 15)
        self._kv_exports = 0
        self._kv_imports = 0
        self._kv_blocks_out = 0
        self._kv_blocks_in = 0
        # tiered-KV counters (ISSUE 17): blocks a deduped migration
        # attached locally instead of receiving, and full-block prefix
        # chains grafted in through the fleet fetch-on-miss path
        self._kv_blocks_deduped = 0
        self._kv_prefix_fetched = 0
        # chain-fingerprint index over the tree's resident chains
        # (spill entries carry their own): mutated ONLY on the engine
        # thread; cross-thread readers (prefix_index, dedup_have) take
        # GIL-atomic dict snapshots and every consumer re-verifies at
        # use time
        self._tree_fps: dict[str, bool] = {}
        self._peak_active = 0
        self._prefix_hits = 0
        self._prefix_tokens_saved = 0
        self._cow_copies = 0
        self._spec_proposed = 0   # draft tokens offered to verify steps
        self._spec_accepted = 0   # draft tokens accepted by verify steps
        self._spec_steps = 0      # verify calls (per participating slot)
        self._spec_rr = 0         # round-robin over draft_k groups
        self._occupancy: deque[tuple[int, int]] = deque(maxlen=4096)

        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lm-engine")
        self._thread.start()

    def _iter_pool_leaves(self):
        import jax

        flat = jax.tree_util.tree_flatten_with_path(self._pool)[0]
        for path, leaf in flat:
            yield "/".join(str(getattr(k, "key", k))
                           for k in path), leaf

    # ------------------------------------------------------------------ API

    def submit(self, ids, max_new_tokens: int, eos_id: Optional[int] = None,
               temperature: float = 0.0, top_k: Optional[int] = None,
               seed: int = 0, speculative: int = 0,
               timeout: Optional[float] = None,
               trace_ctx: Optional[tuple] = None) -> list[int]:
        """Batched generation (greedy at ``temperature == 0``, otherwise
        temperature/top-k sampling with the exclusive lane's exact key
        schedule for ``seed``); ``speculative=draft_k`` (>= 2) verifies
        prompt-lookup draft chunks in the batched variable-width step —
        fixed-seed output token-identical to the exclusive lane's
        ``make_speculative_generate_fn`` program.  Returns emitted
        tokens, stopping at the first EOS inclusive.  Raises QueueFull
        under backpressure."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        # same bounds the unbatched jits enforce at trace time, surfaced
        # BEFORE the request occupies queue space (an over-capacity row
        # would wrap slot = pos % S and corrupt its own cache row)
        self._validate_gen_args(ids, int(max_new_tokens),
                                float(temperature), top_k,
                                int(speculative))
        req = _Request(ids=ids, max_new_tokens=int(max_new_tokens),
                       eos_id=eos_id, temperature=float(temperature),
                       top_k=top_k, seed=int(seed),
                       speculative=int(speculative), trace_ctx=trace_ctx)
        req.t_submit = time.monotonic()
        if self._reqlog is not None:
            req.rid = self._reqlog.begin(
                int(ids.size), int(max_new_tokens),
                temperature=float(temperature), top_k=top_k,
                speculative=int(speculative),
                trace_id=trace_ctx[0] if trace_ctx else None)
        return self._enqueue_and_wait(req, timeout)

    def _check_disagg_ready(self) -> None:
        if not self.paged:
            raise ValueError(
                "disaggregated serving needs the paged block pool; "
                "windowed configs keep dense per-slot rows")
        if self._gather_fn is None or self._graft_fn is None:
            raise ValueError(
                "disaggregated serving tiers are single-host engines; "
                "a mesh placement has no local pool to export/import "
                "(compose disaggregation ACROSS gangs, not inside one)")

    def _validate_gen_args(self, ids, max_new_tokens: int,
                           temperature: float, top_k: Optional[int],
                           speculative: int) -> None:
        """The submit()-shape validation shared by every slot-seating
        entry point (batched, prefill-export, seat-from-import)."""
        from k8s_tpu.models.decode import (
            _check_cache_capacity,
            check_speculative_capacity,
        )

        if ids.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k is not None and top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if speculative:
            if speculative < 2:
                raise ValueError(
                    f"speculative draft_k must be >= 2, got {speculative}")
            if not self.paged:
                raise ValueError(
                    "batched speculative decoding needs the paged block "
                    "cache; windowed configs ride the exclusive lane "
                    "(models/server.py routes them there)")
            if ids.size < 2:
                raise ValueError(
                    "prompt-lookup drafting needs prompt_len >= 2")
            check_speculative_capacity(self.config, int(ids.size),
                                       int(max_new_tokens),
                                       int(speculative))
        _check_cache_capacity(self.config, int(ids.size),
                              int(max_new_tokens))

    def prefill_export(self, ids, max_new_tokens: int,
                       eos_id: Optional[int] = None,
                       temperature: float = 0.0,
                       top_k: Optional[int] = None, seed: int = 0,
                       speculative: int = 0,
                       timeout: Optional[float] = None,
                       trace_ctx: Optional[tuple] = None) -> dict:
        """Prefill-only mode (ISSUE 15): chunk-prefill the prompt
        through the normal slot path (prefix reuse, tree insert — the
        prefill tier's radix trees compose exactly like a serving
        pod's), emit the first token, then EXPORT the request's block
        chain to the host and retire — no decode slot is held past the
        prefill, so a prefill tier never convoys its own admissions
        behind decodes it is not running.

        Returns the migration manifest: ``ids``/``first``/``key`` (the
        PRNG carry — the decode pod continues the exclusive lane's
        exact key schedule)/``blocks`` (flat ``{leaf path: [n_blocks,
        block_size, ...]}`` host arrays)/``n_blocks``/``block_size``,
        plus ``done`` + ``tokens`` when the generation finished at the
        first token (first-token EOS / ``max_new_tokens == 1`` — no
        migration needed), and ``rid`` so the HTTP layer can close the
        request timeline with the transfer span.  Raises QueueFull
        under backpressure like :meth:`submit`."""
        self._check_disagg_ready()
        ids = np.asarray(ids, np.int32).reshape(-1)
        self._validate_gen_args(ids, int(max_new_tokens),
                                float(temperature), top_k,
                                int(speculative))
        req = _Request(ids=ids, max_new_tokens=int(max_new_tokens),
                       eos_id=eos_id, temperature=float(temperature),
                       top_k=top_k, seed=int(seed),
                       speculative=int(speculative), export=True,
                       trace_ctx=trace_ctx)
        req.t_submit = time.monotonic()
        if self._reqlog is not None:
            req.rid = self._reqlog.begin(
                int(ids.size), int(max_new_tokens),
                temperature=float(temperature), top_k=top_k,
                speculative=int(speculative), kind="prefill_export",
                trace_id=trace_ctx[0] if trace_ctx else None)
        return self._enqueue_and_wait(req, timeout)

    def submit_prefilled(self, ids, blocks: dict, *, first_token: int,
                         key, max_new_tokens: int,
                         eos_id: Optional[int] = None,
                         temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         speculative: int = 0,
                         block_size: Optional[int] = None,
                         skip: int = 0,
                         timeout: Optional[float] = None,
                         trace_id: Optional[str] = None,
                         seated: Optional[Callable[[], None]] = None
                         ) -> list[int]:
        """Seat a request DIRECTLY from an imported block chain (the
        decode half of disaggregated serving, ISSUE 15): graft the
        received blocks into the local pool, insert the prompt's
        full-block runs into the local prefix tree (a migrated prefix
        is immediately shareable), and join the batched decode lanes at
        position ``len(ids)`` with ``first_token`` as the last emitted
        token and ``key`` as the PRNG carry — fixed-seed output is
        token-identical to a local prefill by construction (same pool
        bytes, same key schedule, row-independent batched math).

        ``blocks`` is the sender's flat ``{leaf path: [n_blocks,
        block_size, ...]}`` manifest; structural mismatches (paths,
        shapes, an int8 pool fed non-int8 content) refuse with
        ValueError BEFORE any device work, and a pool that cannot fit
        the chain even after evicting every unpinned tree leaf refuses
        with :class:`PoolExhausted` (receive-side backpressure — the
        sender's router re-places the request).  ``seated()`` fires on
        the engine thread the moment the request holds its slot (the
        kv-transfer plane's ack seam; keep it O(set-an-event)).
        Returns the full emitted token list, ``first_token``
        included.

        ``skip`` (ISSUE 17, migration dedup): the sender omitted the
        chain's first ``skip`` FULL blocks after this engine's dedup
        index promised it already holds them (in-tree or in-spill);
        ``blocks`` then carries only the shipped tail and the seat path
        attaches the promised prefix by reference (promoting from the
        spill tier when needed).  A promise the tree can no longer keep
        refuses with :class:`DedupStale` and the sender re-sends the
        full chain."""
        self._check_disagg_ready()
        ids = np.asarray(ids, np.int32).reshape(-1)
        self._validate_gen_args(ids, int(max_new_tokens),
                                float(temperature), top_k,
                                int(speculative))
        bs = self.block_size if block_size is None else int(block_size)
        if bs != self.block_size:
            raise ValueError(
                f"imported block_size {bs} != engine block_size "
                f"{self.block_size}: disaggregated tiers must serve the "
                "same artifact with the same bucket set")
        n = math.ceil(int(ids.size) / self.block_size)
        skip = int(skip)
        if not 0 <= skip < n:
            raise ValueError(
                f"dedup skip {skip} out of range for a {n}-block chain")
        if skip > max(0, (int(ids.size) - 1) // self.block_size):
            raise ValueError(
                f"dedup skip {skip} covers the last prompt token's "
                "block — that block is never tree-shareable and must "
                "always ship")
        shipped = n - skip
        missing = set(self._pool_leaf_meta) - set(blocks)
        extra = set(blocks) - set(self._pool_leaf_meta)
        if missing or extra:
            raise ValueError(
                f"imported chain does not match the pool manifest "
                f"(missing {sorted(missing)[:4]}, extra "
                f"{sorted(extra)[:4]})")
        for path, arr in blocks.items():
            tail, dtype = self._pool_leaf_meta[path]
            want = (shipped, self.block_size) + tail
            if tuple(arr.shape) != want:
                raise ValueError(
                    f"imported leaf {path} has shape {tuple(arr.shape)}"
                    f", expected {want}")
            if dtype == "int8" and str(arr.dtype) != "int8":
                raise ValueError(
                    f"imported leaf {path} is {arr.dtype} but the pool "
                    "stores int8: quantized pools migrate their native "
                    "leaves bit-exact (no wire re-quantization)")
        # receive-side backpressure: refuse BEFORE queuing when the
        # chain cannot fit even after evicting every unpinned tree leaf
        # (best-effort read — pool state moves on the engine thread,
        # and the seat-time allocation path re-checks for real).  A
        # deduped chain only needs fresh blocks for its shipped tail;
        # the skipped prefix attaches by reference.
        with self._cond:
            try:
                available = self._pool_alloc.free_blocks \
                    + self._evictable_blocks()
            # except-ok: the tree mutates on the engine thread without
            # this lock; a torn walk must not refuse a seatable chain —
            # the seat-time allocation path is the real check
            except RuntimeError:  # noqa: BLE001
                available = shipped
        if available < shipped:
            raise PoolExhausted(shipped, available)
        req = _Request(ids=ids, max_new_tokens=int(max_new_tokens),
                       eos_id=eos_id, temperature=float(temperature),
                       top_k=top_k, speculative=int(speculative),
                       manifest={
                           "first": int(first_token),
                           "key": np.asarray(key, np.uint32).reshape(2),
                           "n_blocks": n,
                           "skip": skip,
                           "nested": _unflatten_tree(blocks),
                       },
                       seated_cb=seated)
        req.t_submit = time.monotonic()
        if self._reqlog is not None:
            req.rid = self._reqlog.begin(
                int(ids.size), int(max_new_tokens),
                temperature=float(temperature), top_k=top_k,
                speculative=int(speculative), kind="migrated",
                trace_id=trace_id)
        return self._enqueue_and_wait(req, timeout)

    def prefix_index(self, limit: int = 128) -> list[str]:
        """Chain fingerprints this pod can serve by reference (resident
        tree chains) or re-promote (spill entries), most-recent-ish
        first, capped at ``limit`` — what the fleet prefix cache index
        advertises (ISSUE 17).  Advisory by design: the dedup seat path
        and the fetch-on-miss path both re-verify at use time, so a
        stale entry costs one round trip, never correctness."""
        # unguarded-ok: called from scrape/metrics threads; both reads
        # are single C-level snapshots (list(dict)) of maps mutated only
        # on the engine thread, and every consumer re-verifies
        fps: list[str] = []
        if self.paged and self._tree is not None:
            fps.extend(reversed(list(self._tree_fps)))
        if self._spill is not None:
            fps.extend(reversed(self._spill.fingerprints()))
        seen: set[str] = set()
        out: list[str] = []
        for fp in fps:
            if fp not in seen:
                seen.add(fp)
                out.append(fp)
                if len(out) >= limit:
                    break
        return out

    def dedup_have(self, fps: list) -> int:
        """Longest leading run of offered chain fingerprints held
        in-tree or in-spill — the receiver half of the kvxfer dedup
        handshake (ISSUE 17).  Advisory: the seat path re-verifies and
        refuses with :class:`DedupStale` if eviction broke the promise
        in between."""
        # unguarded-ok: membership probes against maps mutated only on
        # the engine thread; a torn read only mis-sizes the advisory
        # skip, which the seat path re-verifies
        spill = self._spill
        have = 0
        for fp in fps:
            if fp in self._tree_fps or (spill is not None and fp in spill):
                have += 1
            else:
                break
        return have

    def fetch_prefix(self, ids, timeout: Optional[float] = None
                     ) -> Optional[dict]:
        """Holder side of fleet fetch-on-miss (ISSUE 17): gather the
        longest cached FULL-block prefix of ``ids`` — resident tree
        chain first, extended straight from spill payloads (host bytes,
        no pool writes) — as a wire-ready manifest ``{"n_blocks",
        "block_size", "blocks": {leaf path: [n, block_size, ...]}}``,
        or None when nothing is cached.  Runs on the engine thread via
        :meth:`submit_exclusive` (the pool is donated per step; an
        off-thread gather would race invalidated buffers).  The last
        prompt token's block is never served — it is never
        tree-shareable on the importer either."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        if not self.paged or self._gather_fn is None \
                or self._tree is None:
            return None
        cap = (int(ids.size) - 1) // self.block_size
        if cap < 1:
            return None

        def _do() -> Optional[dict]:
            bs = self.block_size
            full, _ = self._tree.match(ids, cap * bs)
            n = len(full)
            flat_dev: dict = {}
            if n:
                idxs = np.ascontiguousarray(
                    [nd.block for nd in full], np.int32)
                flat_dev = {
                    p: np.asarray(a) for p, a in _flatten_tree(
                        self._gather_fn(self._pool, idxs)).items()}
            extra: list[dict] = []
            if self._spill is not None and n < cap:
                fps = kvtier.chain_fingerprints(ids, bs, max_blocks=cap)
                for k in range(n, cap):
                    e = self._spill.peek(fps[k])
                    if e is None:
                        break
                    extra.append(kvtier.decode_payload(e.payload))
            total = n + len(extra)
            if total == 0:
                return None
            out: dict[str, np.ndarray] = {}
            for path, (tail, dtype) in self._pool_leaf_meta.items():
                parts = []
                if n:
                    parts.append(flat_dev[path])
                for dec in extra:
                    parts.append(np.asarray(dec[path])[None])
                arr = np.concatenate(parts, 0) if len(parts) > 1 \
                    else parts[0]
                # spill payloads for fp pools decode to f32; int8 pools
                # stay native — cast so the manifest matches the pool
                out[path] = np.ascontiguousarray(
                    arr.astype(dtype, copy=False))
            return {"n_blocks": total, "block_size": bs, "blocks": out}

        return self.submit_exclusive(_do, timeout=timeout)

    def import_prefix(self, ids, blocks: dict, n_blocks: int,
                      timeout: Optional[float] = None) -> int:
        """Requester side of fleet fetch-on-miss (ISSUE 17): graft a
        fetched chain prefix into fresh pool blocks and insert its runs
        into the tree, so the generation submitted right after attaches
        it like any local prefix hit.  Best-effort by contract — any
        shortfall (local coverage grew, pool pressure, structural
        mismatch) imports less or nothing and the request simply
        re-prefills the tail.  Returns the blocks adopted."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        n_blocks = int(n_blocks)
        if not self.paged or self._graft_fn is None \
                or self._tree is None or n_blocks < 1:
            return 0
        for path, arr in blocks.items():
            meta = self._pool_leaf_meta.get(path)
            if meta is None:
                raise ValueError(f"fetched leaf {path} not in pool")
            tail, dtype = meta
            want = (n_blocks, self.block_size) + tail
            if tuple(arr.shape) != want:
                raise ValueError(
                    f"fetched leaf {path} has shape {tuple(arr.shape)}"
                    f", expected {want}")
        if set(blocks) != set(self._pool_leaf_meta):
            raise ValueError("fetched chain does not match the pool "
                             "manifest")

        def _do() -> int:
            bs = self.block_size
            cap = min(n_blocks, (int(ids.size) - 1) // bs)
            if cap < 1:
                return 0
            full, _ = self._tree.match(ids, cap * bs)
            start = len(full)
            if start >= cap:
                return 0  # already covered locally
            dsts: list[int] = []
            try:
                for _ in range(cap - start):
                    dsts.append(self._alloc_block(None))
            # except-ok: pool pressure during an opportunistic import
            # falls back to re-prefilling, never fails anything
            except RuntimeError:  # noqa: BLE001
                for b in dsts:
                    self._pool_alloc.release(b)
                return 0
            # the allocs may have evicted part of the matched path;
            # re-match so the insert path is attached (the same hazard
            # _prefill_into's re-match comment names)
            full2, _ = self._tree.match(ids, cap * bs)
            if len(full2) != start:
                for b in dsts:
                    self._pool_alloc.release(b)
                return 0
            sliced = {p: np.ascontiguousarray(a[start:cap])
                      for p, a in blocks.items()}
            self._pool = self._graft_fn(
                self._pool, _unflatten_tree(sliced),
                np.ascontiguousarray(dsts, np.int32))
            created = self._tree.insert(
                full2, [int(t) for t in ids[:cap * bs]],
                [0] * start + dsts)
            # a fresh alloc's refcount-1 becomes the tree's reference;
            # release any block the insert did not adopt
            adopted = {node.block for node in created}
            for b in dsts:
                if b not in adopted:
                    self._pool_alloc.release(b)
            self._index_add(created)
            self._update_block_gauge()
            with self._cond:
                self._kv_prefix_fetched += len(created)
            return len(created)

        return self.submit_exclusive(_do, timeout=timeout)

    def _evictable_blocks(self) -> int:
        """Tree blocks eviction could EVENTUALLY free for an import
        (caller holds ``_cond`` or accepts a benign best-effort read).
        ``evict_one`` only removes leaves, but freeing a leaf exposes
        its parent — so a whole unpinned chain is evictable bottom-up,
        and counting only the current leaves would refuse imports a
        warm pod (pool mostly tree-held chains) can in fact seat.  A
        node counts iff nothing else pins it AND its entire subtree is
        unpinned (a pinned descendant never becomes removable, so its
        ancestors never become leaves)."""
        if self._tree is None:
            return 0
        count = 0

        def walk(node) -> bool:
            nonlocal count
            subtree_ok = True
            for child in node.children.values():
                if not walk(child):
                    subtree_ok = False
            if not subtree_ok \
                    or self._pool_alloc.refcount(node.block) != 1:
                return False
            count += 1
            return True

        for child in self._tree.root.children.values():
            walk(child)
        return count

    def submit_exclusive(self, fn: Callable[[], Any],
                         timeout: Optional[float] = None,
                         trace_ctx: Optional[tuple] = None):
        """Run ``fn`` single-flight on the engine thread between batch
        iterations (the speculative / beam lane); FIFO with batched
        admissions through the same bounded queue."""
        req = _Request(fn=fn, trace_ctx=trace_ctx)
        req.t_submit = time.monotonic()
        if self._reqlog is not None:
            req.rid = self._reqlog.begin(
                None, 0, kind="exclusive",
                trace_id=trace_ctx[0] if trace_ctx else None)
        return self._enqueue_and_wait(req, timeout)

    def _enqueue_and_wait(self, req: _Request, timeout: Optional[float]):
        try:
            with self._cond:
                if self._closed:
                    raise EngineClosed("engine is shut down")
                if len(self._queue) >= self.queue_limit:
                    rej = self.metrics.get("rejected")
                    if rej is not None:
                        rej.inc()
                    raise QueueFull(len(self._queue), self.queue_limit)
                self._queue.append(req)
                self._cond.notify_all()
        except QueueFull as e:
            # recorded OUTSIDE the engine lock (the recorder lock stays
            # a leaf); the timeline closes as shed/queue-dominant
            if self._reqlog is not None:
                self._reqlog.shed(req.rid, e.depth, e.limit)
            raise
        except EngineClosed:
            # the just-opened timeline must close too: _live has no
            # ring bound, and a client retry loop against a crashed
            # engine would otherwise leak one entry per POST
            if self._reqlog is not None:
                self._reqlog.retire(req.rid, "closed")
            raise
        if not req.done.wait(timeout):
            # best-effort cancellation: a still-queued request is removed
            # so abandoned retries don't pile phantom work onto a loaded
            # engine; one already admitted to a slot runs to completion
            # (its tokens are simply discarded)
            removed = False
            with self._cond:
                try:
                    self._queue.remove(req)
                    removed = True
                except ValueError:
                    pass
            if removed and self._reqlog is not None:
                self._reqlog.retire(req.rid, "abandoned")
            raise TimeoutError("generation did not complete in time")
        if req.error is not None:
            raise req.error
        return req.result

    @property
    def disagg_capable(self) -> bool:
        """True when this engine can export/import KV block chains
        (paged, single-host placement) — what the server gates the
        kv-transfer plane on."""
        return self.paged and self._gather_fn is not None

    @property
    def healthy(self) -> bool:
        """False once the engine loop has died on an unexpected error —
        the serving /healthz must flip to 503 so the kubelet restarts the
        pod instead of routing to a process that 500s every generate.
        Deliberate shutdown() and queue shedding are NOT unhealthy."""
        # unguarded-ok: /healthz must stay lock-free — a wedged engine loop
        # holding _cond must not hang the probe; a bool read is GIL-atomic
        return not self._crashed

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def active_slots(self) -> int:
        with self._cond:
            return sum(1 for s in self._slots if not s.free)

    def stats(self) -> dict:
        # mesh identity (ISSUE 14): read outside the engine lock — the
        # placement is immutable after construction
        mesh_info = self._placement.info()
        with self._cond:
            return {
                # placement/mesh surface: lets the fleet plane and
                # /debug/engine tell a tensor-sharded multi-process pod
                # from a single-host one
                "placement": mesh_info["placement"],
                "num_processes": mesh_info["num_processes"],
                "mesh_shape": mesh_info["mesh_shape"],
                "tp_degree": mesh_info["tp_degree"],
                "slots": len(self._slots),
                "active": sum(1 for s in self._slots if not s.free),
                "queue_depth": len(self._queue),
                "queue_limit": self.queue_limit,
                "steps": self._steps,
                "completed": self._completed,
                "peak_active": self._peak_active,
                "buckets": list(self.buckets),
                "prefill_programs": sorted(self._prefill_fns),
                # one batched decode program per (fused width, sampling,
                # spec) tuple actually used; bounded by a static set
                # (fused widths {1,2,4} x greedy/sampling, plus one per
                # draft_k group x greedy/sampling), never by traffic
                # shape
                "decode_programs": len(self._step_ks),
                "decode_step_ks": sorted(
                    [list(t) for t in self._step_ks]),
                # speculative lane (round 9): drafting efficiency for
                # /healthz and the fleet plane
                "spec_proposed": self._spec_proposed,
                "spec_accepted": self._spec_accepted,
                "spec_steps": self._spec_steps,
                "spec_mean_accepted": round(
                    self._spec_accepted / self._spec_steps, 3)
                if self._spec_steps else 0.0,
                "occupancy_timeline": list(self._occupancy),
                # paged-cache / prefix-reuse surface
                "paged": self.paged,
                "block_size": self.block_size if self.paged else 0,
                "pool_blocks": self.pool_blocks,
                "blocks_in_use": self._pool_alloc.used_blocks
                if self.paged else 0,
                "tree_nodes": self._tree.nodes if self._tree else 0,
                "prefix_hits": self._prefix_hits,
                "prefix_tokens_saved": self._prefix_tokens_saved,
                "cow_copies": self._cow_copies,
                "tree_evictions": self._tree.evictions
                if self._tree else 0,
                # disaggregated migration surface (ISSUE 15): chains
                # exported to decode pods / imported block chains seated
                "kv_exports": self._kv_exports,
                "kv_imports": self._kv_imports,
                "kv_blocks_out": self._kv_blocks_out,
                "kv_blocks_in": self._kv_blocks_in,
                # tiered KV memory hierarchy (ISSUE 17): host spill tier
                # occupancy + demote/promote lifetimes, dedup attaches,
                # and fleet fetch-on-miss imports
                "spill_enabled": self._spill is not None,
                "spill_blocks": len(self._spill) if self._spill else 0,
                "spill_bytes": self._spill.bytes_used
                if self._spill else 0,
                "spill_demotions": self._spill.spilled_blocks
                if self._spill else 0,
                "spill_promotions": self._spill.promoted_blocks
                if self._spill else 0,
                "spill_evictions": self._spill.spill_evictions
                if self._spill else 0,
                "kv_blocks_deduped": self._kv_blocks_deduped,
                "kv_prefix_fetched": self._kv_prefix_fetched,
                # request recorder binding (ISSUE 12): whether this
                # engine records per-request timelines
                "request_log": self._reqlog is not None,
            }

    def shutdown(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        # after the engine thread is done broadcasting: releases worker
        # processes cleanly on a mesh placement (no-op locally)
        self._placement.close()

    def debug_check_blocks(self) -> None:
        """Test hook: assert pool refcounts exactly equal the references
        actually held (slot tables + tree nodes) and that free blocks
        hold no references.  Call when the engine is quiescent."""
        if not self.paged:
            return
        expect = [0] * self.pool_blocks
        with self._cond:
            for s in self._slots:
                if s.req is not None:
                    for b in s.table[:s.nblocks]:
                        expect[int(b)] += 1
        if self._tree is not None:
            def walk(node):
                for child in node.children.values():
                    expect[child.block] += 1
                    walk(child)
            walk(self._tree.root)
        actual = [self._pool_alloc.refcount(i)
                  for i in range(self.pool_blocks)]
        if actual != expect:
            diffs = [(i, e, a) for i, (e, a)
                     in enumerate(zip(expect, actual)) if e != a]
            raise AssertionError(f"block refcount drift: {diffs[:8]}")

    # -------------------------------------------------------- jit programs

    def _declare_seams(self) -> None:
        """Declare this engine's compile-budget seams on the active
        ledger and wrap the step-loop jits so every XLA compile lands
        attributed.  Budgets ARE the compile-bound contract stats()
        documents: traffic shape must never grow any of them."""
        try:
            from jax import monitoring as _monitoring
        except Exception:  # noqa: BLE001 - older jax: wrap fallback covers it
            _monitoring = None
        compileledger.ensure_listener(_monitoring)
        ledger = self._ledger
        fused = []
        k = 1
        while k <= MAX_STEP_TOKENS:
            fused.append(k)
            k *= 2
        self._seam_prefill = ledger.declare(
            "engine.prefill", len(self.buckets),
            note="one chunked-prefill program per USED bucket size")
        step_budget = (len(fused) * 2) if self.paged else 2
        self._seam_step = ledger.declare(
            "engine.decode_step", step_budget,
            note="one batched decode program per (fused width, sampling)"
            " pair (dense mode: sampling only)")
        self._seam_aux = ledger.declare(
            "engine.aux", 4,
            note="shape-constant auxiliaries (copy-on-write, row "
            "scatter) that never grow with traffic")
        if self.paged:
            self._seam_spec = ledger.declare(
                "engine.spec_step", compileledger.DEFAULT_SPEC_BUDGET,
                note="one variable-width verify program per (draft_k, "
                "sampling) pair actually used")
            self._step_fn = ledger.wrap(
                self._step_fn, self._seam_step, name="paged_step",
                static_argnums=(6, 7))
            self._spec_fn = ledger.wrap(
                self._spec_fn, self._seam_spec, name="spec_step",
                static_argnums=(7, 8))
            self._cow_fn = ledger.wrap(self._cow_fn, self._seam_aux,
                                       name="cow")
            if self._gather_fn is not None:
                self._seam_kvxfer = ledger.declare(
                    "engine.kvxfer", 2 * self._maxb,
                    note="block-chain export/import programs (gather + "
                    "graft, one per chain length <= max blocks/row) — "
                    "bounded by the table geometry, never by traffic")
                self._gather_fn = ledger.wrap(
                    self._gather_fn, self._seam_kvxfer, name="kv_gather")
                self._graft_fn = ledger.wrap(
                    self._graft_fn, self._seam_kvxfer, name="kv_graft")
            else:
                self._seam_kvxfer = None
        else:
            self._seam_kvxfer = None
            self._seam_spec = None
            self._step_fn = ledger.wrap(
                self._step_fn, self._seam_step, name="dense_step",
                static_argnums=(7,))
            self._scatter_fn = ledger.wrap(
                self._scatter_fn, self._seam_aux, name="scatter")

    def compile_seams(self) -> list:
        """This engine's declared seam handles (empty when the ledger is
        off) — the server folds its whole-gen seam in for one audit."""
        if self._ledger is None:
            return []
        return [s for s in (self._seam_prefill, self._seam_step,
                            self._seam_spec, self._seam_aux,
                            self._seam_kvxfer)
                if s is not None]

    def compile_audit(self) -> Optional[dict]:
        """This engine's per-seam ledger view (snapshots + over-budget
        names), or None when the ledger is off — what the bench phases
        assert on and /debug/compiles aggregates."""
        if self._ledger is None:
            return None
        return self._ledger.seam_audit(self.compile_seams())

    def _prefill_fn(self, chunk_len: int) -> Callable:
        """Per-bucket prefill program.  Paged mode: one chunked
        decode-mode call writing straight into the request's pool blocks
        through its table (the paged_attention seam).  Dense mode: the
        batch-1 row-cache call (scattered into the slot later)."""
        fn = self._prefill_fns.get(chunk_len)
        if fn is None:
            if self.paged:
                fn = self._placement.wrap(
                    "prefill", self._compute.prefill_paged,
                    donate_argnums=(1,), resident_argnums=(0, 1))
            else:
                fn = self._placement.wrap(
                    "prefill_dense", self._compute.prefill_dense,
                    resident_argnums=(0,))
            if self._ledger is not None:
                fn = self._ledger.wrap(
                    fn, self._seam_prefill, name="prefill",
                    context={"bucket": chunk_len})
            # copy-on-write rebind: stats() iterates this dict from probe
            # threads without the engine lock, so never mutate in place
            self._prefill_fns = {**self._prefill_fns, chunk_len: fn}
        return fn

    # ---------------------------------------------------- block machinery

    def _alloc_block(self, slot: Optional[_Slot] = None) -> int:
        """Pop a free pool block, evicting least-recently-hit prefix-tree
        leaves as needed; with the pool floor of 1 + slots x blocks_per_
        row this cannot fail while slot tables are within capacity.
        Recycled blocks need no scrubbing: stale content sits above the
        new owner's written length and is masked by the synthesized
        validity.  ``slot`` names the request the allocation serves so
        evictions land on ITS timeline (the ``evict`` phase).  With the
        spill tier on (ISSUE 17) each victim's content demotes to host
        buffers BEFORE its pool reference drops — eviction becomes
        demotion, and the block's bytes survive for re-promotion."""
        idx = self._pool_alloc.alloc()
        if idx is not None:
            return idx
        t0 = time.monotonic()
        evicted = 0
        spilled = 0
        while idx is None:
            # only leaves whose block nothing else pins: evicting a
            # slot-referenced block frees nothing and throws away a hot
            # cache entry for no progress
            victim = self._tree.evict_leaf(
                pinned=lambda b: self._pool_alloc.refcount(b) > 1) \
                if self._tree else None
            if victim is None:
                raise RuntimeError(
                    "KV block pool exhausted (no evictable prefix "
                    "blocks) — pool sizing invariant violated")
            spilled += self._demote_leaf(victim)
            released = self._pool_alloc.release(victim.block)
            assert released, "unpinned tree leaf must free its block"
            evicted += 1
            idx = self._pool_alloc.alloc()
        if self._reqlog is not None and slot is not None \
                and slot.req is not None:
            dur = time.monotonic() - t0
            self._reqlog.evicted(slot.req.rid, evicted, dur)
            if spilled:
                # the demote cost rides inside the evict window; the
                # spill event carries the same wall span so dominant-
                # phase attribution can name the tier, not just the walk
                self._reqlog.spilled(slot.req.rid, spilled, dur)
        return idx

    def _demote_leaf(self, node) -> int:
        """Demote one evicted tree leaf to the host spill tier: gather
        its block's content through the chain seam (a COPY — the
        payload can never alias a live device block), int8-quantize
        float K/V leaves through the one ``paged.quantize_kv``, and
        park it keyed by the leaf's cumulative chain fingerprint.
        Returns 1 when a payload is resident afterwards.  Must run
        BEFORE the tree's pool reference is released — the gather reads
        the victim block."""
        fp = self._node_fp_of(node)
        self._tree_fps.pop(fp, None)
        spill = self._spill
        if spill is None:
            return 0
        if spill.touch(fp):
            # chain content is immutable once inserted: the resident
            # host copy is already exact, the evict is a pure
            # tree-reference drop
            return 1
        from k8s_tpu.models import paged
        flat = _flatten_tree(self._gather_fn(
            self._pool, np.ascontiguousarray([node.block], np.int32)))
        flat = {p: a[0] for p, a in flat.items()}
        payload, nbytes = kvtier.encode_payload(flat, paged.quantize_kv)
        ok = spill.put(fp, node.tokens, payload, nbytes)
        self._update_spill_gauges()
        return 1 if ok else 0

    def _promote_spill(self, slot: Optional["_Slot"], ids,
                       max_tokens: int) -> int:
        """Re-promote consecutive spilled chain blocks extending the
        tree's coverage of ``ids`` (capped at ``max_tokens``) back into
        the pool: fresh blocks, ONE chain-graft scatter (the same
        ``kv_graft`` program migration seats ride), tree re-insert —
        the caller's subsequent tree walk sees an ordinary prefix hit.
        Returns the blocks promoted; 0 whenever the tier is off, cold,
        or pool pressure says re-prefilling is the better deal."""
        spill = self._spill
        if spill is None or len(spill) == 0 or self._tree is None:
            return 0
        bs = self.block_size
        cap = max(0, int(max_tokens)) // bs
        if cap < 1:
            return 0
        fps = kvtier.chain_fingerprints(ids, bs, max_blocks=cap)
        full, _ = self._tree.match(ids, cap * bs)
        entries: list[tuple[str, kvtier.SpillEntry]] = []
        for k in range(len(full), len(fps)):
            e = spill.peek(fps[k])
            if e is None:
                break
            entries.append((fps[k], e))
        if not entries:
            return 0
        t0 = time.monotonic()
        dsts: list[int] = []
        try:
            for _ in entries:
                # may demote OTHER leaves to make room — the entry
                # references held above stay valid even if the spill
                # LRU rotates them out underneath
                dsts.append(self._alloc_block(slot))
        # except-ok: allocation pressure during a promote (nothing left
        # to evict) falls back to re-prefilling the tail, never fails
        # the request
        except RuntimeError:  # noqa: BLE001
            for b in dsts:
                self._pool_alloc.release(b)
            return 0
        # the allocs may have evicted part of the matched path; re-match
        # so the insert path is attached (the _prefill_into hazard)
        full2, _ = self._tree.match(ids, cap * bs)
        if len(full2) != len(full):
            for b in dsts:
                self._pool_alloc.release(b)
            return 0
        flat: dict[str, list] = {}
        for fp, e in entries:
            dec = kvtier.decode_payload(e.payload)
            spill.get(fp)  # LRU refresh + promote accounting
            for p, a in dec.items():
                flat.setdefault(p, []).append(a)
        stacked = {p: np.ascontiguousarray(np.stack(parts))
                   for p, parts in flat.items()}
        self._pool = self._graft_fn(
            self._pool, _unflatten_tree(stacked),
            np.ascontiguousarray(dsts, np.int32))
        n_tok = (len(full2) + len(entries)) * bs
        created = self._tree.insert(
            full2, [int(t) for t in ids[:n_tok]],
            [0] * len(full2) + dsts)
        # a fresh alloc's refcount-1 becomes the tree's reference;
        # release any block the insert did not adopt
        adopted = {node.block for node in created}
        for b in dsts:
            if b not in adopted:
                self._pool_alloc.release(b)
        self._index_add(created)
        self._update_block_gauge()
        self._update_spill_gauges()
        promos = self.metrics.get("kv_promotions")
        if promos is not None:
            promos.inc(len(created))
        if self._reqlog is not None and slot is not None \
                and slot.req is not None:
            self._reqlog.promoted(slot.req.rid, len(created),
                                  time.monotonic() - t0)
        return len(created)

    def _node_fp_of(self, node) -> str:
        """A tree node's cumulative chain fingerprint (its whole
        root-to-node token chain, hashed with the router's scheme)."""
        return kvtier.chain_fingerprints(
            chain_tokens(node), self.block_size)[-1]

    def _index_add(self, created) -> None:
        """Register freshly-inserted tree nodes in the chain-fingerprint
        index (engine thread only)."""
        for node in created:
            self._tree_fps[self._node_fp_of(node)] = True

    def _update_spill_gauges(self) -> None:
        if self._spill is None:
            return
        g = self.metrics.get("kv_spilled_blocks")
        if g is not None:
            g.set(len(self._spill))
        g = self.metrics.get("kv_spill_bytes")
        if g is not None:
            g.set(self._spill.bytes_used)

    def _release_table(self, slot: _Slot) -> None:
        for b in slot.table[:slot.nblocks]:
            self._pool_alloc.release(int(b))
        slot.table[:] = 0
        slot.nblocks = 0
        self._tables_dirty = True
        self._update_block_gauge()

    def _update_block_gauge(self) -> None:
        gauge = self.metrics.get("blocks_in_use")
        if gauge is not None and self._pool_alloc is not None:
            gauge.set(self._pool_alloc.used_blocks)

    # -------------------------------------------------------- engine loop

    def _loop(self) -> None:
        try:
            while True:
                with self._cond:
                    while (not self._closed and not self._queue
                           and not any(s.ready for s in self._slots)):
                        self._cond.wait()
                    if self._closed:
                        self._drain_locked()
                        return
                    actions = self._admit_locked()
                for req, slot in actions:
                    if req.fn is not None:
                        self._run_exclusive(req)
                    elif req.manifest is not None:
                        # migrated seat (ISSUE 15): graft-only, no model
                        # forward — orders of magnitude cheaper than the
                        # prefill it replaces, so it does not convoy
                        self._seat_prefilled(slot, req)
                    else:
                        # prefill convoy (ISSUE 12): decode-ready slots
                        # stalled behind this admission's prefill — the
                        # stall bills to each VICTIM's prefill phase and
                        # bumps serve_prefill_convoy_total
                        waiting = [s.req.rid for s in self._slots
                                   if s.ready and s.req is not None]
                        t0 = time.monotonic()
                        self._prefill_into(slot, req)
                        if waiting:
                            conv = self.metrics.get("prefill_convoy")
                            if conv is not None:
                                conv.inc()
                            if self._reqlog is not None:
                                dur = time.monotonic() - t0
                                for rid in waiting:
                                    self._reqlog.convoy(rid, dur)
                if any(s.ready for s in self._slots):
                    self._decode_step_all()
        except BaseException:  # noqa: BLE001 - engine thread must not die silently
            log.exception("engine loop crashed; failing all requests")
            with self._cond:
                self._closed = True
                self._crashed = True
                self._drain_locked()

    def _drain_locked(self) -> None:
        err = EngineClosed("engine shut down with requests in flight")
        while self._queue:
            req = self._queue.popleft()
            if self._reqlog is not None:
                self._reqlog.retire(req.rid, "shutdown")
            req.finish(error=err)
        for s in self._slots:
            if s.req is not None:
                if self._reqlog is not None:
                    self._reqlog.retire(s.req.rid, "shutdown")
                s.req.finish(error=err)
                s.clear()

    def _admit_locked(self) -> list[tuple[_Request, Optional[_Slot]]]:
        """FIFO admission: exclusive requests always pop (they run inline
        between steps); batched requests pop while a free slot exists."""
        out: list[tuple[_Request, Optional[_Slot]]] = []
        while self._queue:
            head = self._queue[0]
            if head.fn is not None:
                out.append((self._queue.popleft(), None))
                continue
            slot = next((s for s in self._slots if s.free), None)
            if slot is None:
                break
            slot.req = self._queue.popleft()
            slot.ready = False
            out.append((slot.req, slot))
        return out

    def _run_exclusive(self, req: _Request) -> None:
        from k8s_tpu import trace

        rlog = self._reqlog
        t0 = time.monotonic()
        if req.t_submit:
            qw_h = self.metrics.get("queue_wait")
            if qw_h is not None:
                qw_h.observe(t0 - req.t_submit)
        if rlog is not None:
            rlog.admitted(req.rid, -1, t0 - req.t_submit
                          if req.t_submit else 0.0)
        try:
            # parented under the ingress's inbound traceparent (ISSUE
            # 12): the exclusive lane runs on the engine thread, so the
            # contextvar chain from the handler thread does not reach
            # here — the explicit remote context does
            with trace.span_under(req.trace_ctx, "exclusive_generate"):
                result = req.fn()
        except BaseException as e:  # noqa: BLE001 - surfaced to the caller
            req.finish(error=e)
            if rlog is not None:
                rlog.step(req.rid, 0, 1, 0, time.monotonic() - t0)
                rlog.retire(req.rid, "error")
            return
        req.finish(result=result)
        if rlog is not None:
            # the whole-generation program is opaque from out here: one
            # step record carrying its full wall time (decode phase)
            rlog.step(req.rid, 0, 1, 0, time.monotonic() - t0)
            rlog.retire(req.rid, "ok")
        with self._cond:
            self._completed += 1

    def _first_token(self, req: _Request, last_logits) -> tuple:
        """Sample/argmax the first token from the prefill's last-position
        logits with the exclusive lane's exact key schedule: split the
        seed key once, draw with the sub key, carry the parent."""
        import jax

        from k8s_tpu.models.decode import sample_logits

        key = jax.random.PRNGKey(req.seed)
        ks = jax.random.split(key)
        # The logits are fetched BEFORE the sampling math so the draw
        # runs on a host-local array: a multi-process mesh's replicated
        # prefill output is fetchable everywhere but not fully
        # addressable, so eager device ops on it would be illegal — and
        # the local placement pays the same single sync either way.
        # sync-ok: once per request at the prefill boundary, not per step
        last_logits = np.asarray(last_logits)
        # sync-ok: host-local sampling of the already-fetched logits
        first = int(np.asarray(sample_logits(
            last_logits, ks[1], req.temperature, req.top_k))[0])
        # sync-ok: the carried key joins the host-side per-slot key
        # array fed back each step; once per request
        return first, np.asarray(ks[0])

    def _attach_prefix(self, slot: _Slot, ids) -> tuple:
        """Walk the prefix tree and attach shared blocks by reference;
        copy-on-write the divergence block when the match ends mid-run.
        Returns ``(shared, blocks, cow)``: the number of prompt tokens
        whose prefill is skipped (always <= len(ids) - 1: the last
        prompt token is recomputed for its logits), the blocks attached,
        and whether the divergence block was copy-on-written."""
        if self._tree is None:
            return 0, 0, False
        # spilled chains re-promote BEFORE the walk (ISSUE 17): a
        # demoted prefix grafts back into fresh blocks and the match
        # below sees an ordinary tree hit
        self._promote_spill(slot, ids, len(ids) - 1)
        full, partial = self._tree.match(ids, len(ids) - 1)
        shared = 0
        for node in full:
            self._pool_alloc.retain(node.block)
            slot.table[slot.nblocks] = node.block
            slot.nblocks += 1
            shared += self.block_size
        if partial is not None:
            node, j = partial
            dst = self._alloc_block(slot)
            self._pool = self._cow_fn(
                self._pool, np.int32(node.block), np.int32(dst))
            slot.table[slot.nblocks] = dst
            slot.nblocks += 1
            shared += j
            self._cow_copies += 1
        if shared > 0:
            self._prefix_hits += 1
            self._prefix_tokens_saved += shared
            hits = self.metrics.get("prefix_hits")
            if hits is not None:
                hits.inc()
            saved = self.metrics.get("prefill_saved")
            if saved is not None:
                saved.inc(shared)
        return shared, len(full) + (1 if partial is not None else 0), \
            partial is not None

    def _prefill_into(self, slot: _Slot, req: _Request) -> None:
        """Prefill one prompt into the slot (tail-only when a prefix was
        attached), then emit the first token.  A first-token EOS or
        max_new_tokens == 1 retires the request without ever occupying a
        step."""
        from k8s_tpu import trace

        ids = req.ids
        rlog = self._reqlog
        t_adm = time.monotonic()
        qw = t_adm - req.t_submit if req.t_submit else 0.0
        qw_h = self.metrics.get("queue_wait")
        if qw_h is not None:
            qw_h.observe(qw)
        if rlog is not None:
            rlog.admitted(req.rid, slot.idx, qw)
        try:
            if self.paged:
                shared, pblocks, cow = self._attach_prefix(slot, ids)
                if rlog is not None:
                    rlog.prefix_outcome(
                        req.rid,
                        "cow" if cow else ("hit" if shared else "miss"),
                        pblocks, shared)
                # blocks covering the unshared prompt tail (the CoW
                # block, if any, already covers its own span)
                needed = math.ceil(len(ids) / self.block_size)
                while slot.nblocks < needed:
                    slot.table[slot.nblocks] = self._alloc_block(slot)
                    slot.nblocks += 1
                self._tables_dirty = True
                self._update_block_gauge()
                chunks = split_prefill(len(ids) - shared, self.buckets)
                with trace.span_under(req.trace_ctx, "prefill",
                                      prompt_len=len(ids),
                                      chunks=len(chunks), shared=shared):
                    # host plan data stays numpy: the placement owns the
                    # transfer (plain jit uploads it; a mesh placement
                    # broadcasts it to every process first)
                    table = np.ascontiguousarray(slot.table)
                    off = shared
                    last = None
                    for c in chunks:
                        compiled = c not in self._prefill_fns
                        tc0 = time.monotonic()
                        chunk = ids[off:off + c][None, :]
                        positions = (off + np.arange(
                            c, dtype=np.int32))[None, :]
                        self._pool, last = self._prefill_fn(c)(
                            self.params, self._pool, table, chunk,
                            positions)
                        if rlog is not None:
                            rlog.prefill_chunk(
                                req.rid, c, time.monotonic() - tc0,
                                compiled)
                        off += c
                    first, slot.key = self._first_token(req, last)
                if self._tree is not None:
                    # re-match NOW: block allocations above may have
                    # evicted part of the originally-matched path, and
                    # inserting under a detached node would leak
                    # unreachable (unevictable) references
                    created = self._tree.insert(
                        self._tree.match(ids, len(ids) - 1)[0], ids,
                        [int(b) for b in slot.table[:slot.nblocks]])
                    for node in created:
                        self._pool_alloc.retain(node.block)
                    self._index_add(created)
            else:
                chunks = split_prefill(len(ids), self.buckets)
                with trace.span_under(req.trace_ctx, "prefill",
                                      prompt_len=len(ids),
                                      chunks=len(chunks)):
                    cache = self._row_template
                    off = 0
                    last = None
                    for c in chunks:
                        compiled = c not in self._prefill_fns
                        tc0 = time.monotonic()
                        chunk = ids[off:off + c][None, :]
                        positions = (off + np.arange(
                            c, dtype=np.int32))[None, :]
                        cache, last = self._prefill_fn(c)(
                            self.params, cache, chunk, positions)
                        if rlog is not None:
                            rlog.prefill_chunk(
                                req.rid, c, time.monotonic() - tc0,
                                compiled)
                        off += c
                    first, slot.key = self._first_token(req, last)
        except BaseException as e:  # noqa: BLE001 - bad request must not kill the loop
            req.finish(error=e)
            if rlog is not None:
                rlog.retire(req.rid, "error")
            with self._cond:
                if self.paged:
                    self._release_table(slot)
                slot.clear()
            return
        # TTFT: submit to first emitted token, the _first_token sync
        # above having forced the whole prefill chain
        now = time.monotonic()
        req.ttft_s = now - req.t_submit if req.t_submit else None
        if req.ttft_s is not None:
            tt_h = self.metrics.get("ttft")
            if tt_h is not None:
                tt_h.observe(req.ttft_s)
        if rlog is not None:
            rlog.prefill_done(req.rid, now - t_adm,
                              req.ttft_s if req.ttft_s is not None
                              else now - t_adm)
        if req.export:
            self._finish_export(slot, req, first)
            return
        tokens = [first]
        if (req.eos_id is not None and first == req.eos_id) \
                or req.max_new_tokens <= 1:
            self._retire(slot, req, tokens,
                         "eos" if req.eos_id is not None
                         and first == req.eos_id else "max_tokens")
            return
        if not self.paged:
            self._cache = self._scatter_fn(self._cache, cache,
                                           np.int32(slot.idx))
        slot.tokens = tokens
        slot.last = first
        slot.pos = len(ids)
        if req.speculative:
            # host-side prompt-lookup drafting reads the full context
            slot.ctx = [int(t) for t in ids] + tokens
        slot.ready = True
        with self._cond:
            self._peak_active = max(
                self._peak_active,
                sum(1 for s in self._slots if not s.free))

    def _retire(self, slot: _Slot, req: _Request, tokens: list[int],
                reason: str = "max_tokens") -> None:
        tok_counter = self.metrics.get("tokens")
        if tok_counter is not None:
            tok_counter.inc(len(tokens))
        if req.temperature > 0:
            sampled = self.metrics.get("sampled_batched")
            if sampled is not None:
                sampled.inc()
        # TPOT: decode-side per-token latency, (e2e - TTFT) / (n - 1) —
        # the Gemma-on-TPU serving comparison's definition, so the
        # fleet-plane p99 means the same thing the paper reports
        if req.ttft_s is not None and len(tokens) > 1 and req.t_submit:
            tp_h = self.metrics.get("tpot")
            if tp_h is not None:
                tp_h.observe(
                    (time.monotonic() - req.t_submit - req.ttft_s)
                    / (len(tokens) - 1))
        if self._reqlog is not None:
            self._reqlog.retire(req.rid, reason, tokens=len(tokens),
                                ttft_s=req.ttft_s)
        req.finish(result=tokens)
        with self._cond:
            self._completed += 1
            if self.paged:
                self._release_table(slot)
            slot.clear()

    def _finish_export(self, slot: _Slot, req: _Request,
                       first: int) -> None:
        """Close a prefill-export request: gather the block chain to the
        host, release the slot (NO decode seat is held), and hand the
        migration manifest back to the HTTP layer.  A generation that
        finished at the first token skips the gather entirely — nothing
        will be migrated."""
        hit_eos = req.eos_id is not None and first == req.eos_id
        done = hit_eos or req.max_new_tokens <= 1
        export = {
            "ids": req.ids,
            "first": int(first),
            # sync-ok: slot.key is host-side numpy (the per-slot PRNG
            # carry lives on the host between steps); no device read
            "key": np.asarray(slot.key),
            "block_size": self.block_size,
            "done": done,
            "tokens": [int(first)],
            "rid": req.rid,
            "blocks": {},
            "n_blocks": 0,
        }
        if not done:
            export["blocks"] = self._export_blocks(slot)
            export["n_blocks"] = int(slot.nblocks)
        with self._cond:
            self._completed += 1
            self._kv_exports += 1
            self._kv_blocks_out += export["n_blocks"]
            self._release_table(slot)
            slot.clear()
        if done:
            tok_counter = self.metrics.get("tokens")
            if tok_counter is not None:
                tok_counter.inc(1)
            # nothing migrates: the timeline closes here like any local
            # retirement; otherwise it stays LIVE so the HTTP layer can
            # bill the transfer to the migrate phase before closing it
            if self._reqlog is not None:
                self._reqlog.retire(req.rid,
                                    "eos" if hit_eos else "max_tokens",
                                    tokens=1, ttft_s=req.ttft_s)
        req.finish(result=export)

    def _export_blocks(self, slot: _Slot) -> dict:
        """The slot's block chain as flat host arrays ``{leaf path:
        [n_blocks, block_size, ...]}`` in table order — ONE gather
        program call per export (per chain length), fetched to the
        host at the export boundary."""
        idxs = np.ascontiguousarray(slot.table[:slot.nblocks])
        return _flatten_tree(self._gather_fn(self._pool, idxs))

    def _seat_prefilled(self, slot: _Slot, req: _Request) -> None:
        """Seat a migrated request: graft each received block into a
        freshly-allocated local block (refcount 1 — a graft can never
        touch a donor another slot or the tree shares), insert the
        prompt's runs into the local tree, and join the decode lanes at
        the migrated position with the migrated PRNG carry."""
        m = req.manifest
        rlog = self._reqlog
        t_adm = time.monotonic()
        qw = t_adm - req.t_submit if req.t_submit else 0.0
        qw_h = self.metrics.get("queue_wait")
        if qw_h is not None:
            qw_h.observe(qw)
        if rlog is not None:
            rlog.admitted(req.rid, slot.idx, qw)
        ids = req.ids
        n = int(m["n_blocks"])
        # sync-ok: the manifest is a plain host dict off the wire frame
        skip = int(m.get("skip") or 0)
        nested = m["nested"]
        try:
            if skip:
                # deduped migration (ISSUE 17): the sender omitted the
                # first ``skip`` full blocks after our OP_NEED promised
                # we hold them; attach by reference now, promoting from
                # the spill tier when that is where they live.  A
                # promise eviction broke refuses with the typed
                # ``dedup_stale`` — the sender re-sends the full chain.
                if self._tree is None:
                    raise DedupStale(
                        "deduped migration on an engine without a "
                        "prefix tree")
                self._promote_spill(slot, ids, skip * self.block_size)
                full, _ = self._tree.match(ids, skip * self.block_size)
                if len(full) < skip:
                    raise DedupStale(
                        f"receiver holds {len(full)}/{skip} promised "
                        "prefix blocks (evicted since the offer)")
                for node in full:
                    self._pool_alloc.retain(node.block)
                    slot.table[slot.nblocks] = node.block
                    slot.nblocks += 1
            dsts = np.empty(n - skip, np.int32)
            for i in range(n - skip):
                dsts[i] = self._alloc_block(slot)
                slot.table[slot.nblocks] = dsts[i]
                slot.nblocks += 1
            # one scatter for the whole chain: the decode loop pays a
            # single dispatch per migration, not one per block
            self._pool = self._graft_fn(self._pool, nested, dsts)
            self._tables_dirty = True
            self._update_block_gauge()
            if self._tree is not None:
                # migrated prefixes are immediately shareable: local
                # requests with the same template attach by reference
                created = self._tree.graft(
                    ids, [int(b) for b in slot.table[:slot.nblocks]])
                for node in created:
                    self._pool_alloc.retain(node.block)
                self._index_add(created)
        except BaseException as e:  # noqa: BLE001 - bad import must not kill the loop
            req.finish(error=e)
            if rlog is not None:
                rlog.retire(req.rid, "error")
            with self._cond:
                self._release_table(slot)
                slot.clear()
            return
        graft_s = time.monotonic() - t_adm
        mig_c = self.metrics.get("kv_migrated")
        if mig_c is not None:
            mig_c.inc(n - skip)
        with self._cond:
            self._kv_imports += 1
            self._kv_blocks_in += n - skip
            self._kv_blocks_deduped += skip
        if rlog is not None:
            rlog.migrated(req.rid, n - skip, graft_s)
        if req.seated_cb is not None:
            try:
                req.seated_cb()
            # except-ok: the seated ack is an observability seam (a dead
            # kvxfer socket); the seated request must still decode
            except Exception:  # noqa: BLE001
                log.exception("kvxfer seated callback failed")
        first = int(m["first"])
        slot.tokens = [int(first)]
        slot.last = first
        slot.pos = int(ids.size)
        # sync-ok: the migrated PRNG carry arrived as host numpy off
        # the wire; no device read happens here
        slot.key = np.asarray(m["key"], np.uint32)
        # first token happened on the prefill pod: stamp the seat time
        # so TPOT (decode-side per-token latency) still computes, but do
        # NOT observe the TTFT histogram — this pod never prefilled
        req.ttft_s = time.monotonic() - req.t_submit \
            if req.t_submit else None
        if (req.eos_id is not None and first == req.eos_id) \
                or req.max_new_tokens <= 1:
            # defensive: the sender short-circuits finished generations
            # without migrating, but a direct API caller must not seat
            # a request the decode loop would over-emit for
            self._retire(slot, req, slot.tokens,
                         "eos" if req.eos_id is not None
                         and first == req.eos_id else "max_tokens")
            return
        if req.speculative:
            slot.ctx = [int(t) for t in ids] + [first]
        slot.ready = True
        with self._cond:
            self._peak_active = max(
                self._peak_active,
                sum(1 for s in self._slots if not s.free))

    def _decode_step_all(self) -> None:
        """One batched step over every ready slot.  Inactive rows ride
        along at position -1: (paged) their writes are dropped before
        reaching the pool, or (dense) the model's write slot wraps to
        S-1 in a row the next prefill scatter fully replaces.  Row
        independence of the batched math keeps active rows exact.

        Speculative slots divert the whole step into the variable-width
        path: all plain slots advance one token while every spec slot of
        the chosen ``draft_k`` group verifies its draft chunk in the
        same model call.  Groups with other ``draft_k`` values sit the
        step out (their state untouched — the per-request key schedule
        only advances on actual verifies) and a round-robin pointer
        rotates the pick, so no group starves and the per-row random
        draw shapes always match the exclusive lane's."""
        from k8s_tpu import trace

        B = len(self._slots)
        active = [s for s in self._slots if s.ready]
        spec_ks = sorted({s.req.speculative for s in active
                          if s.req.speculative})
        if spec_ks:
            pick = spec_ks[self._spec_rr % len(spec_ks)]
            self._spec_rr += 1
            self._spec_step(
                [s for s in active if s.req.speculative in (0, pick)],
                pick)
            return
        k = 1
        if self.paged and active:
            # fuse up to MAX_STEP_TOKENS iterations into one program
            # call when no active row can retire mid-scan: no EOS
            # condition anywhere, and k capped at the smallest remaining
            # count.  k is quantized to powers of two so the fused-width
            # program set stays tiny and predictable ({1, 2, 4}; a
            # single solo request's tail walks through all of them, so
            # they warm early instead of compiling lazily mid-traffic
            # and stalling the whole batch).  A join or exclusive
            # request arriving mid-scan waits at most k-1 iterations —
            # a few ms.
            if all(s.req.eos_id is None for s in active):
                k = min(MAX_STEP_TOKENS,
                        min(s.req.max_new_tokens - len(s.tokens)
                            for s in active))
                while k & (k - 1):  # round down to a power of two
                    k &= k - 1
            # grow tables so every write of the fused window lands in an
            # owned block
            grew = False
            for s in active:
                need_bi = (s.pos + k - 1) // self.block_size
                while s.nblocks <= need_bi:
                    s.table[s.nblocks] = self._alloc_block(s)
                    s.nblocks += 1
                    grew = True
            if grew:
                self._tables_dirty = True
                self._update_block_gauge()
        ints = np.zeros((3, B), np.int32)  # [toks, poss, topks]
        ints[0] = self.pad_id
        ints[1] = -1
        keys = np.zeros((B, 2), np.uint32)
        temps = np.zeros((B,), np.float32)
        for s in active:
            ints[0, s.idx] = s.last
            ints[1, s.idx] = s.pos
            ints[2, s.idx] = s.req.top_k or 0
            keys[s.idx] = s.key
            temps[s.idx] = s.req.temperature
        # jit-static: a batch with no sampled row compiles/uses the
        # argmax-only program (no per-row sort/split/categorical tax on
        # pure-greedy traffic)
        sampling = any(s.req.temperature > 0 for s in active)
        step_key = (k if self.paged else 1, sampling, False)
        step_compiled = step_key not in self._step_ks
        t_step = time.monotonic()
        with trace.span("decode_step", active=len(active), fused=k):
            if self.paged:
                if self._tables_dirty:
                    self._tables_dev = self._placement.put_tables(
                        np.stack([s.table for s in self._slots]))
                    self._tables_dirty = False
                self._pool, toks_all, new_keys = self._step_fn(
                    self.params, self._pool, self._tables_dev,
                    ints, keys, temps, k, sampling)
                # sync-ok: THE one host read per fused step — tokens
                # must reach the host for EOS/retire decisions
                toks_host = np.asarray(toks_all)  # [k, B]
            else:
                self._cache, nxt, new_keys = self._step_fn(
                    self.params, self._cache, ints[0], ints[1], keys,
                    temps, ints[2], sampling)
                # sync-ok: the one host read per dense step (EOS/retire)
                toks_host = np.asarray(nxt)[None, :]  # [1, B]
            # sync-ok: per-slot keys live host-side (slots join/retire
            # between steps; a device key stack would re-gather each time)
            keys_host = np.asarray(new_keys)
        step_dur = time.monotonic() - t_step
        sd_h = self.metrics.get("step_duration")
        if sd_h is not None:
            sd_h.observe(step_dur)
        # copy-on-write rebind like _prefill_fns: stats() reads this set
        # from probe threads without the engine lock
        self._step_ks = self._step_ks | {step_key}
        occ = self.metrics.get("occupancy")
        if occ is not None:
            occ.set(len(active))
        with self._cond:
            for i in range(k):
                self._steps += 1
                self._occupancy.append((self._steps, len(active)))
            seq = self._steps
        rlog = self._reqlog
        if rlog is not None:
            # ledger + per-request participation BEFORE the retire loop
            # clears slots (the fused-step gate guarantees every active
            # row emitted exactly k tokens); a step that compiled a
            # fresh (width, sampling) program bills to the compile phase
            rlog.engine_step(seq, len(active), k, 0,
                             k * len(active), step_dur)
            for s in active:
                rlog.step(s.req.rid, seq, k, k, step_dur,
                          compiled=step_compiled)
        for s in active:
            req = s.req
            for i in range(k):
                tok = int(toks_host[i, s.idx])
                s.tokens.append(tok)
                s.pos += 1
                s.last = tok
                hit_eos = req.eos_id is not None and tok == req.eos_id
                if hit_eos or len(s.tokens) >= req.max_new_tokens:
                    assert i == k - 1, "mid-scan retirement is excluded" \
                        " by the fused-step gate"
                    self._retire(s, req, s.tokens,
                                 "eos" if hit_eos else "max_tokens")
                    break
            else:
                s.key = keys_host[s.idx]
                continue
            # retired: key update irrelevant (slot cleared)

    def _spec_step(self, active: list, draft_k: int) -> None:
        """One write-masked variable-width batched step (chunk width
        W = ``draft_k``): every spec slot of the chosen group feeds its
        last token + W-1 host-proposed prompt-lookup drafts; every plain
        slot feeds just its last token with its padding lanes
        write-masked at position -1.  Emissions are truncated host-side
        at the first EOS / max_new_tokens exactly as the exclusive
        lane's program truncates, so fixed-seed output matches it
        token-for-token; rejected drafts need no rollback — their pool
        writes sit above the row's written length, masked until the
        next chunk overwrites them (the write-then-mask contract)."""
        from k8s_tpu import trace
        from k8s_tpu.models.decode import lookup_draft_host

        B = len(self._slots)
        W = draft_k
        # grow tables so every (masked or not) spec write of this chunk
        # lands in an owned block; plain slots only need their next slot
        grew = False
        for s in active:
            w = W if s.req.speculative else 1
            need_bi = (s.pos + w - 1) // self.block_size
            while s.nblocks <= need_bi:
                s.table[s.nblocks] = self._alloc_block(s)
                s.nblocks += 1
                grew = True
        if grew:
            self._tables_dirty = True
            self._update_block_gauge()
        chunk = np.full((B, W), self.pad_id, np.int32)
        ints = np.zeros((3, B), np.int32)  # [poss, widths, topks]
        ints[0] = -1
        keys = np.zeros((B, 2), np.uint32)
        temps = np.zeros((B,), np.float32)
        for s in active:
            chunk[s.idx, 0] = s.last
            if s.req.speculative:
                chunk[s.idx, 1:W] = lookup_draft_host(s.ctx, W)
                ints[1, s.idx] = W
            else:
                ints[1, s.idx] = 1
            ints[0, s.idx] = s.pos
            ints[2, s.idx] = s.req.top_k or 0
            keys[s.idx] = s.key
            temps[s.idx] = s.req.temperature
        sampling = any(s.req.temperature > 0 for s in active)
        n_spec = sum(1 for s in active if s.req.speculative)
        step_key = (W, sampling, True)
        step_compiled = step_key not in self._step_ks
        t_step = time.monotonic()
        with trace.span("decode_step", active=len(active), fused=W,
                        spec=n_spec):
            if self._tables_dirty:
                self._tables_dev = self._placement.put_tables(
                    np.stack([s.table for s in self._slots]))
                self._tables_dirty = False
            self._pool, emit, n_emit, new_keys = self._spec_fn(
                self.params, self._pool, self._tables_dev,
                chunk, ints, keys, temps, W, sampling)
            # sync-ok: the one host read per verify step — emissions
            # and acceptance counts drive host-side truncation/retire
            emit_host = np.asarray(emit)      # [B, W]
            # sync-ok: acceptance counts, same single post-step read
            n_host = np.asarray(n_emit)       # [B]
            # sync-ok: per-slot keys carried host-side between steps
            keys_host = np.asarray(new_keys)
        step_dur = time.monotonic() - t_step
        sd_h = self.metrics.get("step_duration")
        if sd_h is not None:
            sd_h.observe(step_dur)
        self._step_ks = self._step_ks | {step_key}
        occ = self.metrics.get("occupancy")
        if occ is not None:
            occ.set(len(active))
        with self._cond:
            self._steps += 1
            self._occupancy.append((self._steps, len(active)))
            seq = self._steps
        rlog = self._reqlog
        if rlog is not None:
            # n_host is ALREADY on the host (the one post-step read
            # above); summing it costs no device round-trip
            emitted = 0
            for s in active:  # sync-ok: host-side numpy sum, no device read
                emitted += int(n_host[s.idx])
            rlog.engine_step(seq, len(active), W, W, emitted, step_dur)
        prop_c = self.metrics.get("spec_proposed")
        acc_c = self.metrics.get("spec_accepted")
        for s in active:
            req = s.req
            n = int(n_host[s.idx])
            toks = [int(t) for t in emit_host[s.idx, :n]]
            s.key = keys_host[s.idx]
            if req.speculative:
                self._spec_steps += 1
                self._spec_proposed += W - 1
                self._spec_accepted += n - 1
                if prop_c is not None:
                    prop_c.inc(W - 1)
                if acc_c is not None:
                    acc_c.inc(n - 1)
            if rlog is not None:
                # a spec slot's verify chunk splits its wall time into
                # accepted (decode) and rejected (spec_reject) shares;
                # a plain rider records a width-1 decode participation
                rlog.step(req.rid, seq,
                          W if req.speculative else 1, n, step_dur,
                          compiled=step_compiled,
                          spec=bool(req.speculative),
                          proposed=W - 1 if req.speculative else 0,
                          accepted=n - 1 if req.speculative else 0)
            out: list[int] = []
            done = False
            # truncate exactly as the exclusive lane's program: at the
            # first emitted EOS inclusive, capped at max_new_tokens
            for t in toks[:req.max_new_tokens - len(s.tokens)]:
                out.append(t)
                if req.eos_id is not None and t == req.eos_id:
                    done = True
                    break
            s.tokens.extend(out)
            s.pos += len(out)
            s.last = out[-1]
            if s.ctx is not None:
                s.ctx.extend(out)
            if done or len(s.tokens) >= req.max_new_tokens:
                self._retire(s, req, s.tokens,
                             "eos" if done else "max_tokens")

"""Slot-based continuous-batching inference engine (the serving TFJob's
throughput core).

The resident HTTP server (models/server.py) used to be single-flight: one
lock around all device work, batch size 1, a long generation blocking
every short one behind it.  This module replaces that with
Orca/vLLM-style iteration-level scheduling:

- a fixed pool of ``B`` decode **slots**, each owning one batch row of a
  shared fixed-shape KV cache (``[B, S, kv_heads, head_dim]`` per layer)
  plus a per-slot absolute-position counter;
- incoming requests are **prefilled** into a free slot through the
  chunked decode-mode cache path (transformer.Attention._decode_step)
  with exact per-token positions — no left-padding, so RoPE and the
  validity mask stay correct — then scattered into the slot's cache row;
- one **batched decode step** advances every active slot per iteration;
  requests join and retire *between* steps, so a long generation never
  serializes short ones behind it;
- prompt chunk sizes are drawn from a small fixed **bucket** set
  (decode.prefill_buckets_for / split_prefill), so the engine compiles at
  most ``len(buckets)`` prefill programs + 1 batched decode program,
  instead of one program per distinct prompt length;
- a **bounded admission queue** gives backpressure: when it is full,
  submit() raises :class:`QueueFull` and the HTTP layer answers 503 with
  ``Retry-After`` (readiness is not not-busy — /healthz stays 200 while
  shedding).

Greedy determinism is preserved: prefill logits flow through the same
chunked cache calls the single-request chunked-prefill path uses, and the
batched step takes each row's argmax independently, so batched output is
token-identical to the unbatched path (asserted in tests/test_engine.py,
including requests that join mid-decode).  Sampling (temperature > 0) and
speculative requests run on the **exclusive lane**: FIFO through the same
queue, executed single-flight between batch iterations with the legacy
per-shape programs — the pre-engine behavior, kept for the request
classes a shared greedy batch step cannot express.

Knobs: ``K8S_TPU_SERVE_SLOTS`` (decode slots, default 4; the server
treats 0 as "engine off" → legacy single-flight) and
``K8S_TPU_SERVE_QUEUE`` (admission queue bound, default 64).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from collections import deque
from collections.abc import Mapping
from typing import Any, Callable, Optional

import numpy as np

from k8s_tpu.models.decode import prefill_buckets_for, split_prefill

log = logging.getLogger(__name__)

DEFAULT_SLOTS = 4
DEFAULT_QUEUE = 64


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        val = int(raw)
    except ValueError:
        if raw:
            log.warning("ignoring non-integer %s=%r", name, raw)
        return default
    if val < 0:
        log.warning("ignoring negative %s=%d", name, val)
        return default
    return val


def env_slots() -> int:
    """K8S_TPU_SERVE_SLOTS (>= 0; 0 = single-flight, engine off)."""
    return _env_int("K8S_TPU_SERVE_SLOTS", DEFAULT_SLOTS)


def env_queue() -> int:
    """K8S_TPU_SERVE_QUEUE admission bound (0 rejects everything)."""
    return _env_int("K8S_TPU_SERVE_QUEUE", DEFAULT_QUEUE)


class QueueFull(RuntimeError):
    """Admission queue at capacity; carries the Retry-After hint."""

    def __init__(self, depth: int, limit: int, retry_after_s: float = 1.0):
        super().__init__(
            f"admission queue full ({depth}/{limit} waiting)")
        self.depth = depth
        self.limit = limit
        self.retry_after_s = retry_after_s


class EngineClosed(RuntimeError):
    pass


@dataclasses.dataclass
class _Request:
    """One queued unit of work: either a batched greedy generation
    (``ids`` set) or an exclusive-lane callable (``fn`` set)."""

    ids: Optional[np.ndarray] = None
    max_new_tokens: int = 0
    eos_id: Optional[int] = None
    fn: Optional[Callable[[], Any]] = None
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None

    def finish(self, result=None, error=None) -> None:
        self.result = result
        self.error = error
        self.done.set()


class _Slot:
    """One decode slot: a batch row of the shared cache plus host-side
    generation state.  ``ready`` flips True once prefill has scattered
    the row in; only ready slots participate in the batched step."""

    __slots__ = ("idx", "req", "pos", "last", "tokens", "ready")

    def __init__(self, idx: int):
        self.idx = idx
        self.req: Optional[_Request] = None
        self.pos = 0          # absolute position of the NEXT cache write
        self.last = 0         # last emitted token (fed to the next step)
        self.tokens: list[int] = []
        self.ready = False

    @property
    def free(self) -> bool:
        return self.req is None

    def clear(self) -> None:
        self.req = None
        self.tokens = []
        self.ready = False


def _reset_positions(tree):
    """Fresh-cache normalization: every ``pos`` leaf to -1 (no slot
    valid), leaving K/V storage untouched — the mask keys validity off
    ``pos``, so stale vectors are unreachable."""
    import jax.numpy as jnp

    def rec(node):
        if isinstance(node, Mapping):
            return {k: (jnp.full_like(v, -1) if k == "pos" else rec(v))
                    for k, v in node.items()}
        return node

    return rec(tree)


class Engine:
    """Continuous-batching decode engine over one model + params.

    All device work happens on the single engine thread; callers block in
    :meth:`submit` / :meth:`submit_exclusive` on a per-request event.
    """

    def __init__(self, config, params, *, slots: Optional[int] = None,
                 queue_limit: Optional[int] = None,
                 buckets: Optional[tuple] = None, pad_id: int = 0,
                 metrics: Optional[dict] = None):
        import jax

        from k8s_tpu.models.transformer import Transformer

        if slots is None:
            slots = env_slots() or DEFAULT_SLOTS
        if slots < 1:
            raise ValueError(f"engine needs slots >= 1, got {slots}")
        if queue_limit is None:
            queue_limit = env_queue()
        self.config = config
        self.params = params
        self.pad_id = pad_id
        self.queue_limit = queue_limit
        self.buckets = tuple(sorted(buckets or prefill_buckets_for(config)))
        if not self.buckets or self.buckets[0] != 1:
            raise ValueError(
                f"buckets must include 1 so every prompt length "
                f"decomposes, got {self.buckets}")
        if config.window_size and \
                self.buckets[-1] > max(1, config.prefill_chunk):
            raise ValueError(
                f"bucket {self.buckets[-1]} exceeds prefill_chunk "
                f"({config.prefill_chunk}): a windowed ring cache only "
                "holds window + prefill_chunk - 1 slots")
        self.metrics = metrics or {}
        self._model = Transformer(config)
        self._slots = [_Slot(i) for i in range(slots)]
        self._queue: deque[_Request] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._crashed = False

        # jit program inventory — the compile-bound contract: one prefill
        # program per USED bucket size (lazy, tracked in _prefill_fns),
        # one batched decode step, plus two shape-constant auxiliaries
        # (row scatter, cache init) that never grow with traffic.
        self._prefill_fns: dict[int, Callable] = {}
        self._step_fn = jax.jit(self._step_impl, donate_argnums=(1,))
        self._scatter_fn = jax.jit(self._scatter_impl, donate_argnums=(0,))
        self._decode_compiled = False
        self._cache = self._init_cache(slots)
        self._row_template = self._init_cache(1)

        # stats (mutated on the engine thread; read under _cond)
        self._steps = 0
        self._completed = 0
        self._peak_active = 0
        self._occupancy: deque[tuple[int, int]] = deque(maxlen=4096)

        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lm-engine")
        self._thread.start()

    # ------------------------------------------------------------------ API

    def submit(self, ids, max_new_tokens: int, eos_id: Optional[int] = None,
               timeout: Optional[float] = None) -> list[int]:
        """Batched greedy generation; returns emitted tokens (stopping at
        the first EOS, inclusive).  Raises QueueFull under backpressure."""
        from k8s_tpu.models.decode import _check_cache_capacity

        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # same bound the unbatched jit enforces at trace time, surfaced
        # BEFORE the request occupies queue space (an over-capacity row
        # would wrap slot = pos % S and corrupt its own cache row)
        _check_cache_capacity(self.config, int(ids.size),
                              int(max_new_tokens))
        req = _Request(ids=ids, max_new_tokens=int(max_new_tokens),
                       eos_id=eos_id)
        return self._enqueue_and_wait(req, timeout)

    def submit_exclusive(self, fn: Callable[[], Any],
                         timeout: Optional[float] = None):
        """Run ``fn`` single-flight on the engine thread between batch
        iterations (the sampling / speculative lane); FIFO with batched
        admissions through the same bounded queue."""
        req = _Request(fn=fn)
        return self._enqueue_and_wait(req, timeout)

    def _enqueue_and_wait(self, req: _Request, timeout: Optional[float]):
        with self._cond:
            if self._closed:
                raise EngineClosed("engine is shut down")
            if len(self._queue) >= self.queue_limit:
                rej = self.metrics.get("rejected")
                if rej is not None:
                    rej.inc()
                raise QueueFull(len(self._queue), self.queue_limit)
            self._queue.append(req)
            self._cond.notify_all()
        if not req.done.wait(timeout):
            # best-effort cancellation: a still-queued request is removed
            # so abandoned retries don't pile phantom work onto a loaded
            # engine; one already admitted to a slot runs to completion
            # (its tokens are simply discarded)
            with self._cond:
                try:
                    self._queue.remove(req)
                except ValueError:
                    pass
            raise TimeoutError("generation did not complete in time")
        if req.error is not None:
            raise req.error
        return req.result

    @property
    def healthy(self) -> bool:
        """False once the engine loop has died on an unexpected error —
        the serving /healthz must flip to 503 so the kubelet restarts the
        pod instead of routing to a process that 500s every generate.
        Deliberate shutdown() and queue shedding are NOT unhealthy."""
        return not self._crashed

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def active_slots(self) -> int:
        with self._cond:
            return sum(1 for s in self._slots if not s.free)

    def stats(self) -> dict:
        with self._cond:
            return {
                "slots": len(self._slots),
                "active": sum(1 for s in self._slots if not s.free),
                "queue_depth": len(self._queue),
                "queue_limit": self.queue_limit,
                "steps": self._steps,
                "completed": self._completed,
                "peak_active": self._peak_active,
                "buckets": list(self.buckets),
                "prefill_programs": sorted(self._prefill_fns),
                "decode_programs": int(self._decode_compiled),
                "occupancy_timeline": list(self._occupancy),
            }

    def shutdown(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)

    # -------------------------------------------------------- jit programs

    def _init_cache(self, batch: int):
        """Batched cache pytree for ``batch`` rows, every slot invalid.
        Built by one eager decode-mode apply (flax initializes the cache
        collection), then pos-reset — runs op-by-op, compiles nothing."""
        import jax.numpy as jnp

        toks = jnp.zeros((batch, 1), jnp.int32)
        pos = jnp.zeros((batch, 1), jnp.int32)
        _, varz = self._model.apply(
            {"params": self.params}, toks, positions=pos, mode="decode",
            mutable=["cache"])
        return _reset_positions(varz["cache"])

    def _step_impl(self, params, cache, toks, poss):
        """One batched decode step: feed each row's last token at its own
        position, greedy argmax per row (matching sample_logits'
        temperature-0 path exactly — raw-dtype argmax, no cast)."""
        import jax.numpy as jnp

        logits, varz = self._model.apply(
            {"params": params, "cache": cache}, toks[:, None],
            positions=poss[:, None], mode="decode", mutable=["cache"])
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return varz["cache"], nxt

    def _scatter_impl(self, cache, row, idx):
        """Replace batch row ``idx`` of every cache leaf with the freshly
        prefilled batch-1 row (slot join)."""
        import jax

        return jax.tree_util.tree_map(
            lambda full, r: full.at[idx].set(r[0]), cache, row)

    def _prefill_fn(self, chunk_len: int) -> Callable:
        fn = self._prefill_fns.get(chunk_len)
        if fn is None:
            import jax

            def run(params, cache, chunk, positions):
                logits, varz = self._model.apply(
                    {"params": params, "cache": cache}, chunk,
                    positions=positions, mode="decode", mutable=["cache"])
                return varz["cache"], logits[:, -1]

            fn = jax.jit(run)
            # copy-on-write rebind: stats() iterates this dict from probe
            # threads without the engine lock, so never mutate in place
            self._prefill_fns = {**self._prefill_fns, chunk_len: fn}
        return fn

    # -------------------------------------------------------- engine loop

    def _loop(self) -> None:
        try:
            while True:
                with self._cond:
                    while (not self._closed and not self._queue
                           and not any(s.ready for s in self._slots)):
                        self._cond.wait()
                    if self._closed:
                        self._drain_locked()
                        return
                    actions = self._admit_locked()
                for req, slot in actions:
                    if req.fn is not None:
                        self._run_exclusive(req)
                    else:
                        self._prefill_into(slot, req)
                if any(s.ready for s in self._slots):
                    self._decode_step_all()
        except BaseException:  # noqa: BLE001 - engine thread must not die silently
            log.exception("engine loop crashed; failing all requests")
            with self._cond:
                self._closed = True
                self._crashed = True
                self._drain_locked()

    def _drain_locked(self) -> None:
        err = EngineClosed("engine shut down with requests in flight")
        while self._queue:
            self._queue.popleft().finish(error=err)
        for s in self._slots:
            if s.req is not None:
                s.req.finish(error=err)
                s.clear()

    def _admit_locked(self) -> list[tuple[_Request, Optional[_Slot]]]:
        """FIFO admission: exclusive requests always pop (they run inline
        between steps); batched requests pop while a free slot exists."""
        out: list[tuple[_Request, Optional[_Slot]]] = []
        while self._queue:
            head = self._queue[0]
            if head.fn is not None:
                out.append((self._queue.popleft(), None))
                continue
            slot = next((s for s in self._slots if s.free), None)
            if slot is None:
                break
            slot.req = self._queue.popleft()
            slot.ready = False
            out.append((slot.req, slot))
        return out

    def _run_exclusive(self, req: _Request) -> None:
        from k8s_tpu import trace

        try:
            with trace.span("exclusive_generate"):
                result = req.fn()
        except BaseException as e:  # noqa: BLE001 - surfaced to the caller
            req.finish(error=e)
            return
        req.finish(result=result)
        with self._cond:
            self._completed += 1

    def _prefill_into(self, slot: _Slot, req: _Request) -> None:
        """Chunked prefill of one prompt (batch-1, bucket-sized chunks at
        exact positions), then scatter the row into the slot and emit the
        first token.  A first-token EOS or max_new_tokens == 1 retires the
        request without ever occupying a step."""
        import jax.numpy as jnp

        from k8s_tpu import trace

        try:
            ids = req.ids
            chunks = split_prefill(len(ids), self.buckets)
            with trace.span("prefill", prompt_len=len(ids),
                            chunks=len(chunks)):
                cache = self._row_template
                off = 0
                last = None
                for c in chunks:
                    chunk = jnp.asarray(ids[off:off + c], jnp.int32)[None, :]
                    positions = (off + jnp.arange(c, dtype=jnp.int32))[None, :]
                    cache, last = self._prefill_fn(c)(
                        self.params, cache, chunk, positions)
                    off += c
                first = int(np.asarray(
                    jnp.argmax(last, axis=-1).astype(jnp.int32))[0])
        except BaseException as e:  # noqa: BLE001 - bad request must not kill the loop
            req.finish(error=e)
            with self._cond:
                slot.clear()
            return
        tokens = [first]
        if (req.eos_id is not None and first == req.eos_id) \
                or req.max_new_tokens <= 1:
            self._retire(slot, req, tokens)
            return
        self._cache = self._scatter_fn(self._cache, cache,
                                       jnp.asarray(slot.idx, jnp.int32))
        slot.tokens = tokens
        slot.last = first
        slot.pos = len(ids)
        slot.ready = True
        with self._cond:
            self._peak_active = max(
                self._peak_active,
                sum(1 for s in self._slots if not s.free))

    def _retire(self, slot: _Slot, req: _Request, tokens: list[int]) -> None:
        tok_counter = self.metrics.get("tokens")
        if tok_counter is not None:
            tok_counter.inc(len(tokens))
        req.finish(result=tokens)
        with self._cond:
            self._completed += 1
            slot.clear()

    def _decode_step_all(self) -> None:
        """One batched step over every ready slot.  Free rows ride along
        with (token 0, position 0); their stray cache writes land in rows
        the next prefill scatter fully replaces, and row independence of
        the batched math keeps active rows exact."""
        import jax.numpy as jnp

        from k8s_tpu import trace

        B = len(self._slots)
        toks = np.full((B,), self.pad_id, np.int32)
        poss = np.zeros((B,), np.int32)
        active = [s for s in self._slots if s.ready]
        for s in active:
            toks[s.idx] = s.last
            poss[s.idx] = s.pos
        with trace.span("decode_step", active=len(active)):
            self._cache, nxt = self._step_fn(
                self.params, self._cache, jnp.asarray(toks),
                jnp.asarray(poss))
            nxt_host = np.asarray(nxt)
        self._decode_compiled = True
        occ = self.metrics.get("occupancy")
        if occ is not None:
            occ.set(len(active))
        with self._cond:
            self._steps += 1
            self._occupancy.append((self._steps, len(active)))
        for s in active:
            tok = int(nxt_host[s.idx])
            s.tokens.append(tok)
            s.pos += 1
            s.last = tok
            req = s.req
            hit_eos = req.eos_id is not None and tok == req.eos_id
            if hit_eos or len(s.tokens) >= req.max_new_tokens:
                self._retire(s, req, s.tokens)

"""Cross-pod KV block transfer plane for disaggregated serving
(ISSUE 15): the wire between a prefill-tier pod and a decode-tier pod.

A disaggregated serving TFJob splits the compute-bound prefill phase
from the latency-bound decode phase into heterogeneous replica roles
(``K8S_TPU_SERVE_ROLE``).  A prefill pod chunk-prefills a long prompt,
emits the first token, and retires WITHOUT holding a decode slot; the
finished KV blocks — position-independent and table-addressed by
construction (models/kvblocks.py) — are streamed here to the chosen
decode pod, which grafts them into its own block pool, seats the
request directly from the imported blocks (``Engine.submit_prefilled``),
and answers the remaining tokens back over the same connection.

Wire format (length-prefixed framing like models/mp_plan.py, stdlib
``socket`` + ``struct`` + ``json``, numpy for array payloads)::

    [4-byte big-endian header length][header json][raw array bytes...]

where the header is ``{"op": str, "statics": {...}, "arrays":
[[name, dtype, shape], ...]}`` and the array payloads follow in header
order, C-contiguous.  One migration is a three-frame conversation on
one TCP connection (TCP_NODELAY — a migration is latency, not
bandwidth, bound at serving block sizes):

- ``migrate`` (sender → receiver): generation parameters + trace id in
  ``statics``, prompt ids + the PRNG key carry + one ``blk/<path>``
  array per pool cache leaf (``[n_blocks, block_size, ...]``, the
  request's block chain in table order).  ``wire_int8`` marks
  fp-pool content quantized for transit via ``models/paged.quantize_kv``
  (``blk/…`` int8 + ``blkscale/…`` f32 — 4x less wire, lossy; int8
  pools ship their native leaves bit-exact and ignore the knob);
- ``seated`` (receiver → sender): the blocks are grafted and the
  request holds a decode slot — what ``serve_kv_migrate_seconds``
  measures on the sender (transfer + graft, NOT the decode that
  follows);
- ``tokens`` (receiver → sender): the full emitted token list (first
  token included), or ``error`` with a ``kind`` the sender maps back
  to HTTP semantics (``pool_exhausted`` / ``queue_full`` → 503-shed,
  anything else → 500).

Tiered-KV extensions (ISSUE 17), both wire-compatible with peers that
predate them (an old receiver answers the unknown op with the closed
protocol's ``kind=protocol`` error and closes; the sender memoizes the
peer as legacy and falls back to the classic conversation):

- **dedup handshake** — ``offer`` (sender → receiver: the chain's
  cumulative block fingerprints, models/kvtier.chain_fingerprints) /
  ``need`` (receiver → sender: how many leading blocks it already
  holds in-tree or in-spill) prepended to a migrate; the migrate frame
  then carries ``statics["skip"]`` and only the ``blk/``/``blkscale/``
  rows past the receiver's coverage.  The promise is advisory: a
  receiver that evicted it refuses with ``kind=dedup_stale`` and the
  sender re-sends the full chain once on the same stream;
- **prefix fetch** — ``fetch`` (requester → holder: prompt ids) /
  ``blocks`` (holder → requester: the longest cached full-block chain
  prefix as a migrate-shaped array payload; ``n_blocks`` 0 = miss) —
  the fleet prefix-cache index's cross-pod fetch-on-miss path, cheaper
  than re-prefilling a long shared template.

Failure semantics: a truncated frame or dead peer raises
:class:`KvPeerGone` on the reader; the receiver tears down THAT
connection (and discards the in-flight request's tokens if it was
already seated — the engine ran it to completion, nobody is waiting)
while the accept loop keeps serving; the sender surfaces
:class:`KvTransferError` so the HTTP layer can answer the router, whose
retry walk re-lands the request on another prefill candidate.

This module never imports jax: the engine owns pytree↔flat-dict
conversion and device work; everything here is sockets and numpy.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Callable, Optional

import numpy as np

from k8s_tpu.analysis import checkedlock

log = logging.getLogger(__name__)

# Ops of the closed three-frame protocol.
OP_MIGRATE = "migrate"
OP_SEATED = "seated"
OP_TOKENS = "tokens"
OP_ERROR = "error"
# Tiered-KV extension ops (ISSUE 17).  ``offer``/``need`` prepend a
# fingerprint handshake to the migrate conversation (the sender then
# ships only blocks the receiver lacks); ``fetch``/``blocks`` are the
# fleet prefix-cache fetch-on-miss exchange.  A peer predating them
# answers any with the closed protocol's ``unexpected op`` error frame
# (kind ``protocol``) and closes the connection — the sender treats
# that as "legacy peer", caches the verdict, and falls back to the
# classic full migrate, so mixed-version fleets interoperate.
OP_OFFER = "offer"
OP_NEED = "need"
OP_FETCH = "fetch"
OP_BLOCKS = "blocks"

PROTOCOL_VERSION = 1

_HDR = struct.Struct(">I")
MAX_HEADER = 1 << 20
# one pool leaf's block chain for one request; a serving block chain is
# MBs at most — anything past this is a garbage/misaligned stream, not
# a big prompt (the mp_plan guard, sized up for KV payloads)
MAX_ARRAY_BYTES = 1 << 30

DEFAULT_PORT = 8472

ENV_ROLE = "K8S_TPU_SERVE_ROLE"
ENV_PORT = "K8S_TPU_KVXFER_PORT"
ENV_INT8 = "K8S_TPU_KVXFER_INT8"
ENV_DEDUP = "K8S_TPU_KVXFER_DEDUP"

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


def env_role() -> str:
    """K8S_TPU_SERVE_ROLE: ``prefill`` / ``decode`` tier membership for
    a disaggregated serving TFJob; unset/anything else = the collapsed
    single-role pod (serves both phases — the compatibility default)."""
    raw = os.environ.get(ENV_ROLE, "").strip().lower()
    return raw if raw in (ROLE_PREFILL, ROLE_DECODE) else ""


def env_kvxfer_port() -> Optional[int]:
    """K8S_TPU_KVXFER_PORT: the decode pod's block-transfer listener
    port (0 = ephemeral, for tests/benches; unset = None — the server
    then only starts a receiver when its role is ``decode``)."""
    raw = os.environ.get(ENV_PORT, "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        log.warning("ignoring non-integer %s=%r", ENV_PORT, raw)
        return None
    if not 0 <= port < 65536:
        log.warning("ignoring out-of-range %s=%d", ENV_PORT, port)
        return None
    return port


def env_kvxfer_int8() -> bool:
    """K8S_TPU_KVXFER_INT8: quantize fp-pool block content to int8 for
    transit (models/paged.quantize_kv — 4x less wire, LOSSY on fp
    pools; int8 pools always ship their native leaves bit-exact and
    ignore this).  Default off: exactness beats bandwidth until a
    deployment opts in."""
    return os.environ.get(ENV_INT8, "").strip().lower() in (
        "1", "true", "on", "yes")


def env_kvxfer_dedup() -> bool:
    """K8S_TPU_KVXFER_DEDUP: the block-fingerprint dedup handshake on
    migrations (ISSUE 17).  Default ON — the handshake is one tiny
    frame round trip, falls back transparently on legacy peers, and
    the receiver re-verifies every skip — set 0/false/off to ship
    every block unconditionally."""
    return os.environ.get(ENV_DEDUP, "").strip().lower() not in (
        "0", "false", "off", "no")


class KvTransferError(RuntimeError):
    """A migration failed; ``kind`` maps the failure back to HTTP
    semantics on the sender (``pool_exhausted``/``queue_full`` are
    receiver backpressure → shed; everything else is an error)."""

    def __init__(self, msg: str, kind: str = "error"):
        super().__init__(msg)
        self.kind = kind


class KvPeerGone(KvTransferError):
    """The TCP stream ended mid-conversation (dead peer / truncated
    frame)."""

    def __init__(self, msg: str):
        super().__init__(msg, kind="peer_gone")


# ------------------------------------------------------------- framing

def encode_frame(op: str, statics: Optional[dict] = None,
                 arrays: Optional[dict] = None) -> bytes:
    metas = []
    payloads = []
    for name, arr in (arrays or {}).items():
        arr = np.ascontiguousarray(arr)
        if arr.nbytes > MAX_ARRAY_BYTES:
            raise ValueError(f"kvxfer array {name} too large: {arr.nbytes}")
        metas.append([name, str(arr.dtype), list(arr.shape)])
        payloads.append(arr.tobytes())
    header = json.dumps({"op": op, "statics": statics or {},
                         "arrays": metas}).encode()
    if len(header) > MAX_HEADER:
        raise ValueError(f"kvxfer header too large: {len(header)}")
    return _HDR.pack(len(header)) + header + b"".join(payloads)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            # timeouts propagate distinctly: the SENDER must tell a
            # reply timeout (frame likely delivered — never re-send)
            # from a dead stream (safe to retry a stale keep-alive)
            raise
        except OSError as e:
            raise KvPeerGone(f"kvxfer stream error: {e}") from None
        if not chunk:
            raise KvPeerGone(
                "kvxfer stream ended mid-frame (peer gone)")
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock: socket.socket) -> tuple[str, dict, dict]:
    """One framed message off the stream: ``(op, statics, arrays)``.
    Raises :class:`KvPeerGone` on EOF/truncation and on malformed
    headers (a garbage stream must never be interpreted as a multi-GB
    allocation)."""
    (hlen,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if hlen > MAX_HEADER:
        raise KvPeerGone(f"bad kvxfer header length {hlen}")
    try:
        header = json.loads(_recv_exact(sock, hlen))
        metas = header["arrays"]
        op = header["op"]
    except (ValueError, KeyError, TypeError) as e:
        raise KvPeerGone(f"malformed kvxfer header: {e}") from None
    arrays: dict[str, np.ndarray] = {}
    for name, dtype, shape in metas:
        n = int(np.dtype(dtype).itemsize * int(np.prod(shape or [1])))
        if n > MAX_ARRAY_BYTES:
            raise KvPeerGone(f"bad kvxfer array size {n}")
        raw = _recv_exact(sock, n) if n else b""
        arrays[name] = np.frombuffer(raw, dtype=dtype).reshape(shape)
    return op, header.get("statics") or {}, arrays


def parse_dest(dest: str) -> tuple[str, int]:
    """``host:port`` → (host, port); raises ValueError on garbage (the
    request-level validation path — a bad ``kv_dest`` is a 400, not a
    connect timeout)."""
    host, sep, port = str(dest).rpartition(":")
    if not sep or not host:
        raise ValueError(f"kv_dest must be host:port, got {dest!r}")
    try:
        p = int(port)
    except ValueError:
        raise ValueError(f"kv_dest port not an int: {dest!r}") from None
    if not 0 < p < 65536:
        raise ValueError(f"kv_dest port out of range: {dest!r}")
    return host, p


# ------------------------------------------------------------- receiver

class KvReceiver:
    """Decode-pod side: accept migrations, seat them on the engine, and
    stream the finished tokens back.

    ``seat_fn(statics, arrays, on_seated)`` is the server's seam onto
    ``Engine.submit_prefilled``: it must call ``on_seated()`` the moment
    the blocks are grafted and the request holds a slot (the engine does
    this between graft and the first decode step), then return the full
    emitted token list.  Backpressure raises from ``seat_fn`` travel to
    the sender as typed ``error`` frames.

    One handler thread per connection (senders pool connections, so the
    thread count tracks peer pods, not requests); connections are
    keep-alive — a sender runs many migrations down one socket.
    """

    def __init__(self, seat_fn: Callable, host: str = "127.0.0.1",
                 port: int = 0, reply_timeout_s: float = 600.0,
                 index_fn: Optional[Callable] = None,
                 fetch_fn: Optional[Callable] = None):
        self._seat_fn = seat_fn
        # ISSUE 17 seams, both optional (None = the pre-hierarchy
        # protocol: offers and fetches answer the closed protocol's
        # ``unexpected op`` error, which senders read as "legacy"):
        # ``index_fn(fps) -> int`` answers a dedup offer with the
        # longest leading run of chain fingerprints this pod holds;
        # ``fetch_fn(statics, arrays) -> (statics, arrays) | None``
        # serves a prefix-cache fetch (None = nothing cached).
        self._index_fn = index_fn
        self._fetch_fn = fetch_fn
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        self._lock = checkedlock.make_lock("kvxfer.receiver")
        self._closed = False
        self._conns: list[socket.socket] = []
        self._reply_timeout_s = reply_timeout_s
        # counters (under the receiver lock; stats() renders them)
        self._migrations = 0
        self._blocks_in = 0
        self._errors = 0
        self._peer_gone = 0
        self._dedup_offers = 0
        self._dedup_blocks_promised = 0
        self._fetches = 0
        self._fetch_blocks_out = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="kvxfer-accept")
        self._accept_thread.start()

    def stats(self) -> dict:
        with self._lock:
            return {"port": self.port, "migrations": self._migrations,
                    "blocks_in": self._blocks_in, "errors": self._errors,
                    "peer_gone": self._peer_gone,
                    "connections": len(self._conns),
                    "dedup_offers": self._dedup_offers,
                    "dedup_blocks_promised": self._dedup_blocks_promised,
                    "fetches": self._fetches,
                    "fetch_blocks_out": self._fetch_blocks_out}

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, addr = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn, addr),
                             daemon=True, name="kvxfer-conn").start()

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        try:
            while True:
                try:
                    op, statics, arrays = read_frame(conn)
                except KvPeerGone:
                    # dead peer / truncated frame: tear down THIS
                    # connection; the accept loop keeps serving
                    with self._lock:
                        self._peer_gone += 1
                    return
                if op == OP_OFFER and self._index_fn is not None:
                    # dedup handshake (ISSUE 17): answer how many of
                    # the offered chain fingerprints we hold, then stay
                    # on the conversation — the (possibly sliced)
                    # migrate frame follows on this connection
                    fps = [str(f) for f in statics.get("fps") or []]
                    try:
                        have = int(self._index_fn(fps))
                    # except-ok: the index is advisory; a failed probe
                    # just means "ship everything", never a dead conn
                    except Exception:  # noqa: BLE001
                        log.exception("kvxfer dedup index probe failed")
                        have = 0
                    have = max(0, min(have, len(fps)))
                    with self._lock:
                        self._dedup_offers += 1
                        self._dedup_blocks_promised += have
                    if not self._reply(conn, encode_frame(
                            OP_NEED, {"have": have})):
                        return
                    continue
                if op == OP_FETCH and self._fetch_fn is not None:
                    self._handle_fetch(conn, statics, arrays)
                    continue
                if op != OP_MIGRATE:
                    # unknown op (or an ISSUE 17 op this pod has no
                    # seam for): the closed protocol's error frame —
                    # senders read kind=protocol as "legacy peer" and
                    # fall back to the classic full migrate
                    self._reply(conn, encode_frame(
                        OP_ERROR, {"error": f"unexpected op {op!r}",
                                   "kind": "protocol"}))
                    return
                self._handle_migrate(conn, statics, arrays)
        finally:
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, conn: socket.socket, data: bytes) -> bool:
        try:
            conn.sendall(data)
            return True
        except OSError:
            with self._lock:
                self._peer_gone += 1
            return False

    def _handle_fetch(self, conn: socket.socket, statics: dict,
                      arrays: dict) -> None:
        """One prefix-cache fetch (ISSUE 17): serve the longest cached
        chain prefix of the requested ids.  Runs inline on the
        connection thread — ``fetch_fn`` bounds its own engine-thread
        hop — and answers ``blocks`` (``n_blocks`` 0 = cache miss; the
        requester re-prefills, a miss is never an error)."""
        try:
            reply = self._fetch_fn(statics, arrays)
        except BaseException as e:  # noqa: BLE001 - typed onto the wire
            with self._lock:
                self._errors += 1
            kind = getattr(e, "kind", None) or "error"
            self._reply(conn, encode_frame(
                OP_ERROR, {"error": f"{type(e).__name__}: {e}",
                           "kind": kind}))
            return
        if reply is None:
            self._reply(conn, encode_frame(OP_BLOCKS, {"n_blocks": 0}))
            return
        out_statics, out_arrays = reply
        n = int(out_statics.get("n_blocks") or 0)
        with self._lock:
            self._fetches += 1
            self._fetch_blocks_out += n
        self._reply(conn, encode_frame(OP_BLOCKS, out_statics,
                                       out_arrays))

    def _handle_migrate(self, conn: socket.socket, statics: dict,
                        arrays: dict) -> None:
        """One migration: seat in a worker thread so the ``seated`` ack
        leaves the moment the graft lands (the engine thread must never
        block on this socket), then stream the tokens."""
        seated = threading.Event()
        done = threading.Event()
        box: dict = {}

        def run() -> None:
            try:
                box["tokens"] = self._seat_fn(statics, arrays,
                                              seated.set)
            except BaseException as e:  # noqa: BLE001 - typed onto the wire below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name="kvxfer-seat")
        t.start()
        deadline = time.monotonic() + self._reply_timeout_s
        # ack as soon as seated; a seat failure (refusal) skips the ack
        timed_out = False
        while not seated.is_set() and not done.is_set():
            if time.monotonic() > deadline:
                box.setdefault("error", KvTransferError(
                    "seat timed out on the receive side", "timeout"))
                timed_out = True
                break
            seated.wait(0.01)
        if seated.is_set() and "error" not in box:
            n_blocks = next(
                (int(a.shape[0]) for name, a in arrays.items()
                 if name.startswith("blk/")), 0)
            if not self._reply(conn, encode_frame(
                    OP_SEATED, {"blocks": n_blocks})):
                # sender died between migrate and ack: the engine still
                # runs the seated request to completion; its tokens are
                # discarded below (nobody is waiting)
                done.wait(self._reply_timeout_s)
                return
        if not timed_out:
            done.wait(self._reply_timeout_s)
        # a timed-out seat replies its typed error IMMEDIATELY (waiting
        # on `done` again would delay the frame past the sender's own
        # reply timeout and tie this handler up for a second budget)
        err = box.get("error")
        if err is not None:
            kind = getattr(err, "kind", None) or {
                "PoolExhausted": "pool_exhausted",
                "QueueFull": "queue_full",
                "ValueError": "bad_request",
            }.get(type(err).__name__, "error")
            with self._lock:
                self._errors += 1
            self._reply(conn, encode_frame(
                OP_ERROR, {"error": f"{type(err).__name__}: {err}",
                           "kind": kind}))
            return
        tokens = [int(tk) for tk in box.get("tokens") or []]
        with self._lock:
            self._migrations += 1
            self._blocks_in += next(
                (int(a.shape[0]) for name, a in arrays.items()
                 if name.startswith("blk/")), 0)
        self._reply(conn, encode_frame(OP_TOKENS, {"tokens": tokens}))

    def stop(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns, self._conns = self._conns, []
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._accept_thread.join(timeout=5)


# --------------------------------------------------------------- sender

class KvSender:
    """Prefill-pod side: pooled keep-alive connections per decode peer
    (a fresh TCP connect per migration would pay a handshake on the
    serving hot path), one three-frame conversation per migration."""

    def __init__(self, connect_timeout_s: float = 5.0,
                 reply_timeout_s: float = 600.0, pool_cap: int = 8):
        self._lock = checkedlock.make_lock("kvxfer.sender")
        self._pool: dict[str, list[socket.socket]] = {}
        self._pool_cap = pool_cap
        self._connect_timeout_s = connect_timeout_s
        self._reply_timeout_s = reply_timeout_s
        self._migrations = 0
        self._blocks_out = 0
        # dedup accounting + the legacy-peer memo (ISSUE 17): a dest
        # that answered an offer with the closed protocol's error never
        # gets offered again — one wasted round trip per peer lifetime
        self._dedup_blocks_skipped = 0
        self._dedup_bytes_saved = 0
        self._dedup_stale = 0
        self._legacy_peers: set[str] = set()

    def stats(self) -> dict:
        with self._lock:
            return {"migrations": self._migrations,
                    "blocks_out": self._blocks_out,
                    "pooled_connections": sum(
                        len(v) for v in self._pool.values()),
                    "dedup_blocks_skipped": self._dedup_blocks_skipped,
                    "dedup_bytes_saved": self._dedup_bytes_saved,
                    "dedup_stale": self._dedup_stale,
                    "legacy_peers": len(self._legacy_peers)}

    def _checkout(self, dest: str) -> tuple[socket.socket, bool]:
        with self._lock:
            idle = self._pool.get(dest)
            if idle:
                return idle.pop(), True
        host, port = parse_dest(dest)
        sock = socket.create_connection((host, port),
                                        timeout=self._connect_timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock, False

    def _checkin(self, dest: str, sock: socket.socket) -> None:
        with self._lock:
            idle = self._pool.setdefault(dest, [])
            if len(idle) < self._pool_cap:
                idle.append(sock)
                return
        sock.close()

    def migrate(self, dest: str, statics: dict, arrays: dict,
                fingerprints: Optional[list] = None,
                info: Optional[dict] = None) -> tuple[list[int], float]:
        """Run one migration conversation; returns ``(tokens,
        seated_s)`` where ``seated_s`` is send-to-seated-ack — the
        migration cost proper, decode excluded.  Raises
        :class:`KvTransferError` (typed) on refusal or a dead peer.
        A stale pooled connection gets ONE fresh retry (a receiver
        closing an idle keep-alive is not a peer failure).

        ``fingerprints`` (ISSUE 17, dedup): cumulative chain
        fingerprints of the chain's leading dedup-eligible FULL blocks
        (never the last prompt token's).  When given and the peer
        speaks the handshake, an ``offer``/``need`` prologue runs
        first and the migrate frame ships only ``blk/``/``blkscale/``
        rows past the receiver's promised coverage; a peer answering
        with the closed protocol's error is memoized as legacy and
        gets the classic full migrate, and a ``dedup_stale`` refusal
        (the receiver evicted the promise) re-sends the full chain
        once on the same stream.  ``info`` (optional out-param dict)
        receives this call's ``skipped_blocks``/``skipped_bytes`` —
        per-call and race-free, unlike the aggregate stats()."""
        full_frame = encode_frame(OP_MIGRATE, statics, arrays)
        if info is not None:
            info["skipped_blocks"] = 0
            info["skipped_bytes"] = 0
        last: Optional[KvTransferError] = None
        for only_fresh in (False, True):
            try:
                if only_fresh:
                    host, port = parse_dest(dest)
                    sock = socket.create_connection(
                        (host, port), timeout=self._connect_timeout_s)
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    reused = False
                else:
                    sock, reused = self._checkout(dest)
            except OSError as e:
                # a dead/unreachable decode peer is a transport failure
                # the HTTP layer maps to 502 (and the router walks past)
                raise KvPeerGone(
                    f"kvxfer connect to {dest}: {e}") from None
            try:
                sock.settimeout(self._reply_timeout_s)
                with self._lock:
                    offer = bool(fingerprints) \
                        and dest not in self._legacy_peers
                skip = 0
                t0 = time.monotonic()
                if offer:
                    sock.sendall(encode_frame(OP_OFFER, {
                        "v": PROTOCOL_VERSION,
                        "fps": [str(f) for f in fingerprints]}))
                    op, st, _arr = read_frame(sock)
                    if op == OP_ERROR \
                            and str(st.get("kind")) == "protocol":
                        # legacy peer predating the handshake: it
                        # closed the connection behind the error frame
                        # — memoize, reconnect, run the classic
                        # conversation
                        with self._lock:
                            self._legacy_peers.add(dest)
                        try:
                            sock.close()
                        except OSError:
                            pass
                        host, port = parse_dest(dest)
                        sock = socket.create_connection(
                            (host, port),
                            timeout=self._connect_timeout_s)
                        sock.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        sock.settimeout(self._reply_timeout_s)
                        reused = False
                        t0 = time.monotonic()
                    elif op == OP_ERROR:
                        raise KvTransferError(
                            str(st.get("error")),
                            kind=str(st.get("kind") or "error"))
                    elif op == OP_NEED:
                        skip = max(0, min(int(st.get("have") or 0),
                                          len(fingerprints)))
                    else:
                        raise KvPeerGone(
                            f"unexpected offer reply {op!r}")
                frame = full_frame if not skip else encode_frame(
                    OP_MIGRATE, {**statics, "skip": skip},
                    {name: (a[skip:]
                            if name.startswith(("blk/", "blkscale/"))
                            else a)
                     for name, a in arrays.items()})
                sock.sendall(frame)
                op, st, _arr = read_frame(sock)
                seated_s = time.monotonic() - t0
                if op == OP_ERROR and skip \
                        and str(st.get("kind")) == "dedup_stale":
                    # the receiver lost the promised prefix between
                    # the offer and the seat (eviction race): one full
                    # re-send on the same live stream — we still hold
                    # every array, the index is advisory by contract
                    with self._lock:
                        self._dedup_stale += 1
                    skip = 0
                    t0 = time.monotonic()
                    sock.sendall(full_frame)
                    op, st, _arr = read_frame(sock)
                    seated_s = time.monotonic() - t0
                if op == OP_ERROR:
                    raise KvTransferError(
                        str(st.get("error")),
                        kind=str(st.get("kind") or "error"))
                if op == OP_SEATED:
                    op, st, _arr = read_frame(sock)
                if op == OP_ERROR:
                    raise KvTransferError(
                        str(st.get("error")),
                        kind=str(st.get("kind") or "error"))
                if op != OP_TOKENS:
                    raise KvPeerGone(f"unexpected reply op {op!r}")
                tokens = [int(tk) for tk in st.get("tokens") or []]
                n_blocks = next(
                    (int(a.shape[0]) for name, a in arrays.items()
                     if name.startswith("blk/")), 0)
                saved = sum(
                    (a.nbytes // max(1, int(a.shape[0]))) * skip
                    for name, a in arrays.items()
                    if name.startswith(("blk/", "blkscale/"))) \
                    if skip else 0
                with self._lock:
                    self._migrations += 1
                    self._blocks_out += n_blocks - skip
                    self._dedup_blocks_skipped += skip
                    self._dedup_bytes_saved += saved
                if info is not None and skip:
                    info["skipped_blocks"] = skip
                    info["skipped_bytes"] = saved
                self._checkin(dest, sock)
                return tokens, seated_s
            except socket.timeout:
                # a REPLY timeout is not a stale socket: the migrate
                # frame likely reached the receiver and the request may
                # already be seated — re-sending would graft and decode
                # the whole request a SECOND time on an already-slow
                # decode pod.  Fail the attempt; the router's retry
                # walk re-places it deliberately instead.
                try:
                    sock.close()
                except OSError:
                    pass
                raise KvPeerGone(
                    f"kvxfer reply from {dest} timed out after "
                    f"{self._reply_timeout_s}s") from None
            except (OSError, KvPeerGone) as e:
                try:
                    sock.close()
                except OSError:
                    pass
                last = e if isinstance(e, KvTransferError) \
                    else KvPeerGone(f"kvxfer transport: {e}")
                if reused:
                    continue  # stale keep-alive: one fresh retry
                raise last from None
            except KvTransferError:
                # typed refusal on a live stream: the conversation is
                # complete and the socket is reusable
                self._checkin(dest, sock)
                raise
        raise last  # pragma: no cover - loop always returns or raises

    def fetch(self, dest: str, statics: dict, arrays: dict
              ) -> tuple[dict, dict]:
        """One prefix-cache fetch conversation (ISSUE 17): ask ``dest``
        for its cached chain prefix of the prompt in ``arrays``;
        returns the ``blocks`` reply's ``(statics, arrays)`` —
        ``n_blocks`` 0 is a cache miss, not an error.  Transport
        semantics match :meth:`migrate`: typed errors (a legacy peer
        answers kind ``protocol``), one fresh retry for a stale pooled
        connection."""
        frame = encode_frame(OP_FETCH, statics, arrays)
        last: Optional[KvTransferError] = None
        for only_fresh in (False, True):
            try:
                if only_fresh:
                    host, port = parse_dest(dest)
                    sock = socket.create_connection(
                        (host, port), timeout=self._connect_timeout_s)
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    reused = False
                else:
                    sock, reused = self._checkout(dest)
            except OSError as e:
                raise KvPeerGone(
                    f"kvxfer connect to {dest}: {e}") from None
            try:
                sock.settimeout(self._reply_timeout_s)
                sock.sendall(frame)
                op, st, arr = read_frame(sock)
                if op == OP_ERROR:
                    raise KvTransferError(
                        str(st.get("error")),
                        kind=str(st.get("kind") or "error"))
                if op != OP_BLOCKS:
                    raise KvPeerGone(f"unexpected fetch reply {op!r}")
                self._checkin(dest, sock)
                return st, arr
            except socket.timeout:
                try:
                    sock.close()
                except OSError:
                    pass
                raise KvPeerGone(
                    f"kvxfer fetch reply from {dest} timed out after "
                    f"{self._reply_timeout_s}s") from None
            except (OSError, KvPeerGone) as e:
                try:
                    sock.close()
                except OSError:
                    pass
                last = e if isinstance(e, KvTransferError) \
                    else KvPeerGone(f"kvxfer transport: {e}")
                if reused:
                    continue  # stale keep-alive: one fresh retry
                raise last from None
            except KvTransferError as e:
                # typed refusal on a live stream: the conversation is
                # complete and the socket is reusable — EXCEPT a legacy
                # peer's ``protocol`` refusal, which closed the stream
                # behind the error frame
                if getattr(e, "kind", None) == "protocol":
                    try:
                        sock.close()
                    except OSError:
                        pass
                else:
                    self._checkin(dest, sock)
                raise
        raise last  # pragma: no cover - loop always returns or raises

    def close(self) -> None:
        with self._lock:
            pools, self._pool = self._pool, {}
        for idle in pools.values():
            for sock in idle:
                try:
                    sock.close()
                except OSError:
                    pass

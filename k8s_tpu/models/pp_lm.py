"""Pipeline-parallel causal LM: the flagship Transformer decomposed into
(pre = embedding, S homogeneous block stages, post = final norm + tied LM
head + loss) for parallel.pipeline's heterogeneous schedules.

The reference has no pipeline parallelism anywhere (SURVEY.md §2.4 — its
only axes were PS-vs-worker data parallelism); in the TPU-native design the
``pp`` mesh axis is a first-class choice for models whose layer stack
doesn't fit one chip's HBM.  The decomposition here reuses the exact
modules of models.transformer — a pipelined step is grad-exact against the
unpipelined ``Transformer.apply`` on the same parameters (asserted in
tests/test_pp_lm.py), because it IS the same computation, re-scheduled.

Embedding tying: the token embedding is used by stage 0 (lookup) and the
last stage (vocab projection).  The split layout stores it ONCE; the train
step passes it to both ends and sums the two gradient contributions — the
standard first/last-stage all-reduce of tied-embedding training, here a
``psum`` over pp inside the 1F1B body plus an add outside.
"""

from __future__ import annotations

import re
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_tpu.models.transformer import Block, RMSNorm, TransformerConfig
from k8s_tpu.parallel.pipeline import (
    interleave_chunks,
    pipeline_apply,
    pipeline_train_step_1f1b,
    pipeline_train_step_interleaved,
    stack_stage_params,
)

_LAYER_RE = re.compile(r"^layer_(\d+)$")


def _unwrap(params):
    return params["params"] if "params" in params else params


def split_lm_params(params, num_stages: int, num_virtual: int = 1) -> dict:
    """Re-layout a Transformer param tree for the pp schedules.

    Returns ``{"embedding", "final_norm", "stages"}`` where ``stages``
    stacks ``layers/(num_stages*num_virtual)`` blocks per chunk on a
    leading chunk axis (renamed ``block_{j}`` locally so every chunk has an
    identical pytree structure, as stack_stage_params requires).

    With ``num_virtual > 1`` (interleaved 1F1B) the chunk axis is stored in
    device-major round-robin order — chunk c on pp rank c mod S — so the
    step's P("pp") slicing needs no per-step weight gather.
    """
    p = _unwrap(params)
    idxs = sorted(
        int(m.group(1)) for k in p if (m := _LAYER_RE.match(k)))
    n_layers = len(idxs)
    if idxs != list(range(n_layers)):
        raise ValueError(f"non-contiguous layer keys: {idxs}")
    n_chunks = num_stages * num_virtual
    if n_layers % n_chunks:
        raise ValueError(
            f"{n_layers} layers not divisible into {n_chunks} pp chunks "
            f"({num_stages} stages x {num_virtual} virtual)")
    per = n_layers // n_chunks
    chunk_trees = [
        {f"block_{j}": p[f"layer_{ci * per + j}"] for j in range(per)}
        for ci in range(n_chunks)
    ]
    stages = stack_stage_params(chunk_trees)
    if num_virtual > 1:
        stages = interleave_chunks(stages, num_stages, num_virtual)
    return {
        "embedding": p["embedding"],
        "final_norm": p["final_norm"],
        "stages": stages,
    }


def merge_lm_params(pp_params: dict, num_stages: int,
                    num_virtual: int = 1) -> dict:
    """Inverse of split_lm_params — back to the plain ``Transformer`` tree
    (``{"params": {...}}``), e.g. for checkpoint export or eval without pp."""
    stages = pp_params["stages"]
    if num_virtual > 1:
        stages = interleave_chunks(
            stages, num_stages, num_virtual, inverse=True)
    n_chunks = num_stages * num_virtual
    per = None
    flat = {}
    for ci in range(n_chunks):
        stage = jax.tree.map(lambda x: x[ci], stages)
        if per is None:
            per = len(stage)
        for j in range(per):
            flat[f"layer_{ci * per + j}"] = stage[f"block_{j}"]
    flat["embedding"] = pp_params["embedding"]
    flat["final_norm"] = pp_params["final_norm"]
    return {"params": flat}


def make_stage_fn(cfg: TransformerConfig, blocks_per_stage: int) -> Callable:
    """One homogeneous pp stage: ``blocks_per_stage`` transformer blocks.

    Ring attention is a cross-device collective over ``sp`` and cannot run
    inside the pp shard_map body; pp + long-context composes via the flash
    kernel (device-local Pallas) instead.
    """
    if cfg.use_ring_attention:
        raise ValueError(
            "use_ring_attention composes with pp via flash attention, not "
            "the sp ring (collectives can't nest inside the pp shard_map)")
    block = Block(cfg)

    def apply_block(block_params, x, positions):
        return block.apply({"params": block_params}, x, positions)

    if cfg.remat:
        apply_block = jax.checkpoint(apply_block)

    def stage_fn(stage_params, x):
        B, L, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(L), (B, L))
        for j in range(blocks_per_stage):
            x = apply_block(stage_params[f"block_{j}"], x, positions)
        return x

    return stage_fn


def make_pre_fn(cfg: TransformerConfig) -> Callable:
    """Stage-0 ingest: token ids -> embedded activations (transformer.py's
    ``emb[tokens]`` line, run on the first pp rank only)."""

    def pre_fn(pre_params, tokens):
        return pre_params["embedding"][tokens].astype(cfg.dtype)

    return pre_fn


def _head_logits(cfg: TransformerConfig, post_params, x):
    norm = RMSNorm(fused=cfg.use_fused_norm)
    x = norm.apply({"params": post_params["final_norm"]}, x)
    # tied embeddings, bf16 operands + f32 accumulation — same kernel
    # shape as Transformer.__call__'s head einsum
    return jnp.einsum(
        "bld,vd->blv", x.astype(cfg.dtype),
        post_params["embedding"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )


def make_post_logits_fn(cfg: TransformerConfig) -> Callable:
    """Last-stage output map for pipeline_apply: activations -> logits."""
    return lambda post_params, x: _head_logits(cfg, post_params, x)


def make_post_loss_fn(cfg: TransformerConfig) -> Callable:
    """Last-stage loss head for 1F1B: activations + target tokens ->
    per-microbatch next-token loss (train.lm_loss on the microbatch).

    Equal-sized microbatches make the mean-over-microbatches of this equal
    to the global lm_loss — the decomposition 1F1B requires.
    """
    from k8s_tpu.models.train import lm_loss

    def post_fn(post_params, x, target_tokens):
        return lm_loss(_head_logits(cfg, post_params, x), target_tokens)

    return post_fn


def pp_apply(mesh: Mesh, cfg: TransformerConfig, pp_params: dict, tokens,
             *, num_stages: int, num_microbatches: int,
             batch_axes=("dp", "fsdp"), axis: str = "pp"):
    """Pipelined forward: tokens -> logits, numerically equal to
    ``Transformer(cfg).apply(merge_lm_params(...), tokens)``."""
    stage_fn = make_stage_fn(cfg, cfg.layers // num_stages)
    return pipeline_apply(
        mesh, stage_fn, pp_params["stages"], tokens,
        num_microbatches=num_microbatches, axis=axis, batch_axes=batch_axes,
        pre_fn=make_pre_fn(cfg),
        pre_params={"embedding": pp_params["embedding"]},
        post_fn=make_post_logits_fn(cfg),
        post_params={"final_norm": pp_params["final_norm"],
                     "embedding": pp_params["embedding"]},
    )


def pp_loss_and_grads(mesh: Mesh, cfg: TransformerConfig, pp_params: dict,
                      tokens, targets, *, num_stages: int,
                      num_microbatches: int, num_virtual: int = 1,
                      batch_axes=("dp", "fsdp"), axis: str = "pp"):
    """1F1B loss + gradients in the split layout (tied-embedding grads
    summed across the two end stages).  num_virtual > 1 runs the
    interleaved schedule on the device-major chunk layout split_lm_params
    produced."""
    ends = dict(
        pre_fn=make_pre_fn(cfg),
        pre_params={"embedding": pp_params["embedding"]},
        post_fn=make_post_loss_fn(cfg),
        post_params={"final_norm": pp_params["final_norm"],
                     "embedding": pp_params["embedding"]},
    )
    stage_fn = make_stage_fn(
        cfg, cfg.layers // (num_stages * num_virtual))
    if num_virtual > 1:
        loss, (g_stage, g_pre, g_post) = pipeline_train_step_interleaved(
            mesh, stage_fn, pp_params["stages"], tokens, targets,
            num_microbatches=num_microbatches, num_virtual=num_virtual,
            axis=axis, batch_axes=batch_axes, device_major=True, **ends)
    else:
        loss, (g_stage, g_pre, g_post) = pipeline_train_step_1f1b(
            mesh, stage_fn, pp_params["stages"], tokens, targets,
            num_microbatches=num_microbatches, axis=axis,
            batch_axes=batch_axes, **ends)
    grads = {
        "stages": g_stage,
        # tied embedding: lookup grad (stage 0) + head grad (last stage)
        "embedding": g_pre["embedding"] + g_post["embedding"],
        "final_norm": g_post["final_norm"],
    }
    return loss, grads


def pp_state_shardings(state: dict, mesh: Mesh, axis: str = "pp",
                       num_virtual: int = 1) -> Any:
    """Shardings for a train state over split-layout params: each stage's
    blocks live on their pp rank (leading chunk axis sharded over ``axis``;
    with interleaving each rank holds its num_virtual device-major chunks);
    the tied embedding and final norm are replicated (both end ranks read
    them).  Optimizer moments mirror their parameter leaves; scalars
    replicate."""

    def param_sh(params):
        stage_sh = jax.tree.map(
            lambda _: NamedSharding(mesh, P(axis)), params["stages"])
        rep = NamedSharding(mesh, P())
        return {
            "stages": stage_sh,
            "embedding": rep,
            "final_norm": jax.tree.map(lambda _: rep, params["final_norm"]),
        }

    p_sh = param_sh(state["params"])

    n_chunks = mesh.shape[axis] * num_virtual

    def opt_leaf_sh(x):
        # moment tensors in the split layout mirror params positionally is
        # not guaranteed across optax versions; shard by shape instead: a
        # leaf with the chunk-stacked leading axis gets the stage sharding
        if hasattr(x, "shape") and x.ndim >= 1 and (
                x.shape[:1] == (n_chunks,)) and mesh.shape[axis] > 1:
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())

    opt_sh = jax.tree.map(opt_leaf_sh, state["opt_state"])
    return {"params": p_sh, "opt_state": opt_sh,
            "step": NamedSharding(mesh, P())}


def make_pp_train_step(cfg: TransformerConfig, optimizer, mesh: Mesh, *,
                       num_stages: int, num_microbatches: int,
                       num_virtual: int = 1,
                       batch_axes=("dp", "fsdp"), axis: str = "pp",
                       state_shardings=None) -> Callable:
    """jitted 1F1B train step over split-layout state, with donated state —
    the pp analogue of train.make_sharded_train_step."""

    def step(state, batch):
        tokens, targets = batch
        loss, grads = pp_loss_and_grads(
            mesh, cfg, state["params"], tokens, targets,
            num_stages=num_stages, num_microbatches=num_microbatches,
            num_virtual=num_virtual, batch_axes=batch_axes, axis=axis)
        updates, new_opt = optimizer.update(
            grads, state["opt_state"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return (
            {"params": new_params, "opt_state": new_opt,
             "step": state["step"] + 1},
            loss,
        )

    if state_shardings is None:
        return jax.jit(step, donate_argnums=(0,))
    batch_sh = NamedSharding(mesh, P(batch_axes))
    return jax.jit(
        step,
        in_shardings=(state_shardings, (batch_sh, batch_sh)),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )

"""Input pipeline: host-side batching + async device prefetch.

The reference feeds its workloads with TF-side input pipelines inside the
user container (dist_mnist reads MNIST via tf input_data,
test/e2e/dist-mnist/dist_mnist.py:120-138); the operator itself ships no
loader.  A TPU-native framework needs one: on TPU the train step should
never wait on PCIe — batches must already be in HBM (sharded across the
mesh) when the step is dispatched.

``PrefetchIterator`` wraps any host iterator and stages up to
``buffer_size`` batches ahead through ``jax.device_put`` on a background
thread.  ``device_put`` dispatches asynchronously, so the host→HBM DMA of
batch N+1/N+2 overlaps the device compute of batch N; the queue hand-off
just bounds how far ahead the host runs.  With a ``sharding``
(NamedSharding over the dp/fsdp axes), staging also scatters each batch
shard to its device, which is exactly what make_sharded_train_step's
``in_shardings`` expect — the jit call then finds its inputs already
placed and inserts no transfer.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axes: Sequence[str] = ("dp", "fsdp")) -> NamedSharding:
    """Sharding for a [global_batch, ...] array: leading dim split over the
    data axes, trailing dims replicated (the make_sharded_train_step batch
    contract, k8s_tpu.models.train)."""
    present = tuple(a for a in axes if a in mesh.shape)
    return NamedSharding(mesh, P(present if present else None))


def array_batches(
    arrays: Sequence[np.ndarray],
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epochs: Optional[int] = None,
    drop_remainder: bool = True,
) -> Iterator[tuple]:
    """Host-side epoch/shuffle/batch over aligned numpy arrays.

    Yields tuples of per-array batches (the (inputs, targets) shape fit()
    consumes).  ``epochs=None`` repeats forever — the step budget lives in
    fit(steps=...), not the data pipeline.
    """
    n = len(arrays[0])
    for a in arrays:
        if len(a) != n:
            raise ValueError(f"misaligned arrays: {len(a)} != {n}")
    if batch_size > n:
        raise ValueError(f"batch_size {batch_size} > dataset size {n}")
    rng = np.random.default_rng(seed)
    epoch = 0
    while epochs is None or epoch < epochs:
        idx = rng.permutation(n) if shuffle else np.arange(n)
        end = n - (n % batch_size) if drop_remainder else n
        for start in range(0, end, batch_size):
            take = idx[start:start + batch_size]
            yield tuple(a[take] for a in arrays)
        epoch += 1


class PrefetchIterator:
    """Async device staging of a host batch iterator.

    Runs the wrapped iterator on a daemon thread, ``jax.device_put``-ing
    each batch (optionally with a per-leaf or single ``sharding``) into a
    bounded queue.  Iteration yields device-resident batches; the host
    thread producing batch N+k runs concurrently with device compute on
    batch N.

    Exceptions in the producer propagate to the consumer at the next
    ``__next__``.  Call ``close()`` (use try/finally around the consuming
    loop) to stop the producer: the live thread keeps the iterator
    reachable, so garbage collection alone will NOT stop it — an abandoned
    un-closed iterator over an infinite source polls its full queue until
    process exit (daemon thread, so exit itself is never blocked).
    """

    _DONE = object()

    def __init__(
        self,
        it: Iterable,
        *,
        buffer_size: int = 2,
        sharding: Any = None,
        transform: Optional[Callable] = None,
    ):
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1")
        self._sharding = sharding
        self._transform = transform
        self._source = it
        self._q: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._stop = threading.Event()
        # lazy start: the producer begins on first consumption, so a
        # pre-consumption skip() (checkpoint-resume fast-forward) can still
        # reach the source's index-jump path
        self._thread: Optional[threading.Thread] = None

    def skip(self, n: int) -> None:
        """Forward a pre-consumption skip to the source (the
        checkpoint-resume contract of BatchStream.skip); sources without
        an index jump are drained lazily by the producer."""
        if self._thread is not None:
            raise RuntimeError("skip() must be called before consumption")
        source_skip = getattr(self._source, "skip", None)
        if callable(source_skip):
            source_skip(n)
        else:
            it = iter(self._source)
            for _ in range(n):
                next(it)
            self._source = it

    def _ensure_started(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._produce, args=(iter(self._source),), daemon=True,
                name="prefetch-producer",
            )
            self._thread.start()

    def _stage(self, batch):
        if self._transform is not None:
            batch = self._transform(batch)
        if self._sharding is None:
            return jax.device_put(batch)
        if jax.tree_util.treedef_is_leaf(
            jax.tree_util.tree_structure(self._sharding)
        ):
            return jax.tree.map(lambda x: jax.device_put(x, self._sharding), batch)
        return jax.device_put(batch, self._sharding)

    def _produce(self, it) -> None:
        try:
            for batch in it:
                if self._stop.is_set():
                    return
                self._put_blocking(self._stage(batch))
            self._put_blocking(self._DONE)
        except BaseException as e:  # noqa: BLE001 - forwarded to consumer
            self._put_blocking(e)

    def _put_blocking(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self):
        self._ensure_started()
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                # re-check _stop: close() from another thread may have
                # stopped the producer before it enqueued the sentinel,
                # mirroring the producer's _put_blocking pattern
                continue
        if item is self._DONE:
            self._stop.set()
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()
            raise item
        return item

    def close(self) -> None:
        self._stop.set()
        # drain so a blocked producer can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self._stop.set()
        # except-ok: destructors must never raise (interpreter teardown
        # may have nulled the attribute)
        except Exception:
            pass


def prefetch_to_mesh(
    it: Iterable,
    mesh: Mesh,
    *,
    axes: Sequence[str] = ("dp", "fsdp"),
    buffer_size: int = 2,
    transform: Optional[Callable] = None,
) -> PrefetchIterator:
    """The one-call path for fit(): shard every leaf's leading dim over the
    mesh's data axes and prefetch ``buffer_size`` batches ahead."""
    return PrefetchIterator(
        it,
        buffer_size=buffer_size,
        sharding=batch_sharding(mesh, axes),
        transform=transform,
    )

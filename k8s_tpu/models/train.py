"""Training-step builders: loss, optimizer, sharded jit step.

Replaces the reference's training plumbing (SyncReplicasOptimizer, PS
variable placement, session loops — dist_mnist.py:48-80) with the SPMD
recipe: one jitted step over a mesh, parameters FSDP-sharded, batch sharded
over the data axes, XLA inserting the gradient all-reduces.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_tpu.parallel.sharding import fsdp_sharding


def cross_entropy_loss(logits, labels) -> jnp.ndarray:
    """Mean softmax cross entropy; logits f32 [B, C] (or [B, L, C]).

    logsumexp-minus-gather form: identical math to one_hot·log_softmax but
    never materializes a [..., C] one-hot or log-prob tensor — at LM vocab
    sizes those are the largest activations in the whole step.  Out-of-range
    labels (the ``label = -1`` padding idiom) contribute zero loss and zero
    gradient, exactly as a one-hot of an out-of-range index (all zeros) did,
    while still counting in the mean's denominator.
    """
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    valid = (labels >= 0) & (labels < num_classes)
    safe = jnp.clip(labels, 0, num_classes - 1)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    return jnp.mean(jnp.where(valid, lse - picked, 0.0))


def lm_loss(logits, tokens) -> jnp.ndarray:
    """Next-token prediction loss over [B, L, V] logits and [B, L] tokens."""
    return cross_entropy_loss(logits[:, :-1], tokens[:, 1:])


def lr_schedule(lr: float, *, schedule: str = "constant",
                warmup_steps: int = 0, decay_steps: int = 0,
                final_fraction: float = 0.1):
    """Learning-rate schedule factory: linear warmup to ``lr`` over
    ``warmup_steps``, then "constant" | "cosine" | "linear" decay over
    ``decay_steps`` down to ``final_fraction * lr``.  Pure optax
    schedules — everything stays jit-traceable."""
    if schedule not in ("constant", "cosine", "linear"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if schedule != "constant" and decay_steps <= 0:
        raise ValueError(f"schedule {schedule!r} needs decay_steps > 0")
    end = lr * final_fraction
    if schedule == "cosine":
        main = optax.cosine_decay_schedule(lr, decay_steps,
                                           alpha=final_fraction)
    elif schedule == "linear":
        main = optax.linear_schedule(lr, end, decay_steps)
    else:
        main = optax.constant_schedule(lr)
    if warmup_steps > 0:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, lr, warmup_steps), main],
            boundaries=[warmup_steps])
    return main


def default_optimizer(lr: float = 1e-3, weight_decay: float = 0.0,
                      *, clip_norm: float = 0.0, schedule: str = "constant",
                      warmup_steps: int = 0, decay_steps: int = 0):
    """Adam/AdamW with optional global-norm clipping and LR schedule.

    The bare two-arg form is unchanged (constant LR, no clipping); the
    keyword knobs compose as an optax chain: clip_by_global_norm →
    adam(w)(schedule)."""
    sched = lr if (schedule == "constant" and not warmup_steps) else \
        lr_schedule(lr, schedule=schedule, warmup_steps=warmup_steps,
                    decay_steps=decay_steps)
    opt = (optax.adamw(sched, weight_decay=weight_decay) if weight_decay
           else optax.adam(sched))
    if clip_norm and clip_norm > 0:
        return optax.chain(optax.clip_by_global_norm(clip_norm), opt)
    return opt


def init_state(params: Any, optimizer) -> dict:
    """Train state as a plain pytree: {params, opt_state, step}."""
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jnp.zeros((), jnp.int32),
    }


def _combined_loss(apply_fn: Callable, loss_fn: Callable, params, batch):
    """The one definition of 'the loss' shared by training and held-out
    eval: apply_fn may return (logits, aux_scalar) — e.g. the MoE
    load-balance term from make_moe_apply_fn — which is added to the task
    loss."""
    inputs, targets = batch
    out = apply_fn(params, inputs)
    if isinstance(out, tuple):
        logits, aux = out
    else:
        logits, aux = out, 0.0
    return loss_fn(logits, targets) + aux


def make_train_step(
    apply_fn: Callable,
    loss_fn: Callable,
    optimizer,
    grad_accum: int = 1,
) -> Callable:
    """One SPMD train step: grad → optimizer update.  Under jit over a mesh
    with sharded inputs, XLA inserts the psum/reduce-scatter collectives.

    ``grad_accum > 1`` splits the global batch into that many microbatches
    and accumulates their gradients under ``lax.scan`` before the single
    optimizer update: activation memory drops to one microbatch's worth
    while the update sees the FULL batch.  For batch-DECOMPOSABLE losses
    (mean-reduced over examples, e.g. lm_loss / cross-entropy) the
    mean-of-microbatch-grads equals the full-batch grad exactly when the
    batch divides evenly (enforced).  Losses with batch-coupled terms —
    notably the MoE load-balance aux, a product of batch statistics —
    are averaged per microbatch instead, a standard and well-behaved but
    not bit-identical approximation."""

    def step(state, batch):
        def compute_loss(params, b):
            return _combined_loss(apply_fn, loss_fn, params, b)

        if grad_accum > 1:
            inputs, targets = batch
            if inputs.shape[0] % grad_accum:
                raise ValueError(
                    f"global batch {inputs.shape[0]} not divisible into "
                    f"{grad_accum} microbatches")

            def split(x):
                return x.reshape((grad_accum, x.shape[0] // grad_accum)
                                 + x.shape[1:])

            micro = (split(inputs), split(targets))

            def accum(carry, mb):
                loss_sum, grad_sum = carry
                loss, grads = jax.value_and_grad(compute_loss)(
                    state["params"], mb)
                return (loss_sum + loss,
                        jax.tree_util.tree_map(jnp.add, grad_sum, grads)), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state["params"])
            (loss_sum, grad_sum), _ = jax.lax.scan(
                accum, (jnp.zeros((), jnp.float32), zeros), micro)
            loss = loss_sum / grad_accum
            # cast back to the PARAM leaf dtype — what value_and_grad
            # would have produced directly — so the optimizer state never
            # silently promotes to the f32 accumulator dtype
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / grad_accum).astype(p.dtype),
                grad_sum, state["params"])
        else:
            loss, grads = jax.value_and_grad(compute_loss)(
                state["params"], batch)
        updates, new_opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        new_params = optax.apply_updates(state["params"], updates)
        return (
            {
                "params": new_params,
                "opt_state": new_opt_state,
                "step": state["step"] + 1,
            },
            loss,
        )

    return step


def shard_train_state(state: dict, mesh: Mesh) -> tuple[dict, Any]:
    """FSDP-shard params and (matching leaves of) optimizer state over the
    mesh; step stays replicated.  Returns (sharded_state, state_shardings)."""
    param_sh = fsdp_sharding(state["params"], mesh)
    # Optimizer moments mirror param shapes, so the same FSDP rule applies
    # leaf-wise; scalar leaves (step counts) replicate.
    opt_sh = jax.tree.map(
        lambda x: fsdp_sharding(x, mesh)
        if hasattr(x, "shape")
        else NamedSharding(mesh, P()),
        state["opt_state"],
    )
    shardings = {
        "params": param_sh,
        "opt_state": opt_sh,
        "step": NamedSharding(mesh, P()),
    }
    sharded = jax.device_put(state, shardings)
    return sharded, shardings


def make_sharded_train_step(
    apply_fn: Callable,
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    state_shardings: Any,
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
    grad_accum: int = 1,
) -> Callable:
    """jit the train step with explicit in/out shardings and donated state —
    the full pjit path the dryrun validates multi-chip."""
    step = make_train_step(apply_fn, loss_fn, optimizer,
                           grad_accum=grad_accum)
    batch_sharding = NamedSharding(mesh, P(batch_axes))
    return jax.jit(
        step,
        in_shardings=(state_shardings, (batch_sharding, batch_sharding)),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


class MetricsWriter:
    """Append-only JSONL training scalars (the TF-summaries role in the
    reference's world — user code there wrote TF event files to a
    mounted volume; here the trainer itself streams one JSON object per
    record so curves survive preemption and are greppable/plottable with
    nothing but the standard library).

    Each record: {"step": N, "wall_time": unix_s, ...scalars}.  Writes
    are line-buffered appends — a gang restart reopens the same file and
    the resumed run's steps continue after the checkpoint's (earlier
    in-flight duplicates are harmless: last-write-wins per step when
    plotting).
    """

    def __init__(self, path: str):
        import os as _os

        self.path = path
        d = _os.path.dirname(path)
        if d:
            _os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def write(self, step: int, **scalars) -> None:
        import json as _json
        import time as _time

        rec = {"step": int(step),
               "wall_time": round(_time.time(), 3)}
        for k, v in scalars.items():
            rec[k] = float(v)
        self._f.write(_json.dumps(rec) + "\n")

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def make_eval_fn(apply_fn: Callable, loss_fn: Callable,
                 eval_iter_factory: Callable, *, batches: int = 8):
    """Held-out evaluation for fit(): mean loss over ``batches`` batches.

    ``eval_iter_factory()`` must return a FRESH iterator positioned at the
    eval split's start on every call (e.g. ``lambda:
    ds.batches(B, L, split="eval", eval_fraction=f, shuffle=False)``), so
    every evaluation scores the same windows and the numbers are
    comparable across steps.  The eval step is jit'd WITHOUT donation —
    the training state buffers must survive the call.
    """

    @jax.jit
    def eval_step(params, batch):
        return _combined_loss(apply_fn, loss_fn, params, batch)

    def eval_fn(state) -> float:
        import itertools

        it = eval_iter_factory()
        try:
            total, n = 0.0, 0
            # islice, not zip(it, range(...)): zip would pull (and discard)
            # one extra batch from the stream after the last yielded pair
            for batch in itertools.islice(it, batches):
                total += float(eval_step(state["params"], batch))
                n += 1
        finally:
            close = getattr(it, "close", None)
            if callable(close):
                close()
        if n == 0:
            raise ValueError("eval stream yielded no batches")
        return total / n

    return eval_fn


import dataclasses


@dataclasses.dataclass
class FitResult:
    """Outcome of a fit() run.

    ``preempted`` is the signal the pod entrypoint must act on (exit 143 so
    the operator's exit-code policy restarts the gang); ``len(losses) <
    steps`` alone cannot distinguish a preemption from a successful resumed
    run that simply had fewer steps left.
    """

    state: dict
    losses: list
    preempted: bool = False
    start_step: int = 0
    # (step, loss) pairs from the held-out eval_fn, when one was passed
    eval_losses: list = dataclasses.field(default_factory=list)

    def __iter__(self):  # (state, losses) unpacking compatibility
        yield self.state
        yield self.losses


def fit(
    apply_fn: Callable,
    loss_fn: Callable,
    optimizer,
    state: dict,
    mesh: Mesh,
    data_iter,
    *,
    steps: int,
    checkpoint_dir: str = "",
    checkpoint_every: int = 100,
    preemption_save: bool = True,
    log_every: int = 0,
    step_fn: Optional[Callable] = None,
    state_shardings: Any = None,
    skip_data_on_resume: bool = True,
    eval_fn: Optional[Callable] = None,
    eval_every: int = 0,
    grad_accum: int = 1,
    metrics_path: str = "",
) -> FitResult:
    """The canonical training loop: shard state over the mesh, jit the step,
    checkpoint/resume via k8s_tpu.models.checkpoint.

    ``data_iter`` yields (inputs, targets) global batches.  With
    ``checkpoint_dir`` set (the operator injects CHECKPOINT_DIR — see
    launcher.bootstrap.LauncherConfig), the loop resumes from the latest
    step after a gang restart, saves every ``checkpoint_every`` steps, and —
    if ``preemption_save`` — registers a SIGTERM hook so TPU preemptions
    (retryable exit 143 under the operator's exit-code policy) leave a fresh
    checkpoint behind.  Returns a FitResult; check ``.preempted`` to decide
    the process exit code (True -> exit 143, the retryable contract).

    Note: the jitted step donates the state buffers, so the caller's
    ``state`` arrays are consumed — use the returned state.

    A prebuilt ``step_fn(state, batch) -> (state, loss)`` (e.g.
    pp_lm.make_pp_train_step's pipelined step, whose gradient schedule
    fit cannot derive from an apply_fn) bypasses the default
    FSDP-shard-and-jit path; pass ``state_shardings`` with it so the
    initial state is placed the way the step expects.

    ``eval_fn(state) -> float`` (see make_eval_fn) runs every
    ``eval_every`` steps and once more after the final step; results land
    in FitResult.eval_losses as (step, loss) pairs.  Held-out evaluation
    parity: the reference's dist-mnist logs test-set metrics alongside
    training (test/e2e/dist-mnist/dist_mnist.py).

    ``metrics_path``: append training/eval scalars as JSONL
    (MetricsWriter) — a loss record every log_every'th step (every step
    when log_every=0) plus the final step and each eval; curves survive
    preemption because records stream as they happen.
    """
    import logging

    log = logging.getLogger(__name__)

    if step_fn is None:
        state, shardings = shard_train_state(state, mesh)
        step_fn = make_sharded_train_step(
            apply_fn, loss_fn, optimizer, mesh, shardings,
            grad_accum=grad_accum)
    elif state_shardings is not None:
        state = jax.device_put(state, state_shardings)

    ckpt = None
    start_step = 0
    if checkpoint_dir:
        from k8s_tpu.models.checkpoint import Checkpointer

        ckpt = Checkpointer(
            checkpoint_dir, save_interval_steps=checkpoint_every)
        state, start_step = ckpt.restore_or_init(state)
        if start_step > 0 and skip_data_on_resume:
            # Fast-forward the (deterministic, seeded) data stream so resume
            # continues where training stopped instead of re-seeing the
            # epoch head.  Iterators exposing skip(n) (TokenDataset.batches,
            # PrefetchIterator) jump by index; anything else is drained
            # batch by batch.  NOTE: ``data_iter`` must be freshly
            # positioned at stream start — re-passing a partially-consumed
            # iterator (e.g. looping fit() on preemption in-process) would
            # double-skip; build a new stream per fit() call.
            skip = getattr(data_iter, "skip", None)
            try:
                if callable(skip):
                    skip(start_step)
                else:
                    for _ in range(start_step):
                        next(data_iter)
            except StopIteration:
                raise ValueError(
                    f"data stream exhausted before the resume point "
                    f"(start_step={start_step}); the stream must cover at "
                    f"least as many batches as the checkpointed run "
                    f"consumed") from None
            log.info("resume: fast-forwarded %d data batches", start_step)

    # Cooperative preemption: SIGTERM sets a flag; the loop saves at the
    # next step boundary and returns early with FitResult.preempted=True.
    # A handler-side synchronous save is deliberately NOT used here — it can
    # race an in-flight interval save (see Checkpointer.save_on_preemption).
    import threading

    preempted = threading.Event()
    unsubscribe = None
    if preemption_save:
        from k8s_tpu.util import signals

        unsubscribe = signals.on_shutdown(preempted.set)

    # chief-only: in a multi-host gang every process runs fit() and
    # metrics_path usually points at the SHARED checkpoint volume — N
    # writers appending the same file would duplicate every record and
    # can interleave partial lines on network filesystems (orbax
    # coordinates its own writes; scalars need this gate instead)
    metrics = MetricsWriter(metrics_path) \
        if metrics_path and jax.process_index() == 0 else None

    losses = []
    eval_losses = []

    def run_eval(step_no):
        el = float(eval_fn(state))
        eval_losses.append((step_no, el))
        log.info("step %d eval loss %.4f", step_no, el)
        if metrics is not None:
            metrics.write(step_no, eval_loss=el)

    last_ran = None
    try:
        for i in range(start_step, steps):
            batch = next(data_iter)
            state, loss = step_fn(state, batch)
            losses.append(loss)
            last_ran = i
            if log_every and (i + 1) % log_every == 0:
                log.info("step %d loss %.4f", i + 1, float(loss))
            if metrics is not None and (
                    not log_every or (i + 1) % log_every == 0
                    or i + 1 == steps):
                metrics.write(i + 1, loss=float(loss))
            if eval_fn is not None and eval_every \
                    and (i + 1) % eval_every == 0 and (i + 1) != steps:
                run_eval(i + 1)
            if ckpt is not None:
                ckpt.maybe_save(i, state)
            if preempted.is_set():
                log.warning(
                    "preemption: checkpointing step %d and stopping", i)
                break
        if eval_fn is not None and last_ran is not None \
                and not preempted.is_set():
            run_eval(last_ran + 1)  # final held-out number for the run

        if ckpt is not None:
            # Final/preemption save, labeled with the last step actually
            # run.  A no-op run (start_step >= steps) saves nothing: the
            # restored state already lives at its own step label.
            if last_ran is not None and ckpt.latest_step() != last_ran:
                ckpt.save(last_ran, state, force=True)
            ckpt.wait()
            ckpt.close()
    finally:
        if unsubscribe is not None:
            unsubscribe()
        if metrics is not None:
            metrics.close()
    return FitResult(
        state=state,
        losses=[float(l) for l in losses],
        preempted=preempted.is_set(),
        start_step=start_step,
        eval_losses=eval_losses,
    )


def make_fused_lm_apply_fn(model, *, vocab_chunk: int = 8192, mesh=None,
                           z_loss: float = 0.0):
    """apply_fn computing the LM loss WITHOUT materializing logits: the
    model returns pre-head hidden states and ops.fused_ce folds the
    tied-embedding matmul into a chunked online-softmax loss (the largest
    activation in LM training — [T, vocab] — never exists).

    Use with ``fused_loss_passthrough`` as the loss_fn:
        step = make_sharded_train_step(
            make_fused_lm_apply_fn(model), fused_loss_passthrough, ...)
    """
    from k8s_tpu.ops.fused_ce import fused_linear_cross_entropy

    if getattr(model, "config", None) is not None and             getattr(model.config, "num_experts", 0) > 0:
        # sow() into a non-mutable collection is a silent no-op: the MoE
        # load-balance loss would vanish and routers would collapse
        raise ValueError(
            "make_fused_lm_apply_fn does not collect the MoE aux loss; "
            "use make_moe_apply_fn for expert models")

    def apply_fn(params, tokens):
        hidden = model.apply(params, tokens, mesh=mesh, return_hidden=True)
        emb = params["params"]["embedding"]
        # next-token shift, as lm_loss does on logits
        return fused_linear_cross_entropy(
            hidden[:, :-1], emb, tokens[:, 1:], vocab_chunk=vocab_chunk,
            z_loss=z_loss)

    return apply_fn


def fused_loss_passthrough(loss, targets):
    """loss_fn for apply_fns that already computed the scalar loss."""
    return loss


def make_moe_apply_fn(model, *, aux_loss_weight: float = 0.01, mesh=None):
    """apply_fn for make_train_step/fit over an MoE transformer: runs the
    model with the "losses" collection mutable, sums every sown
    moe_aux_loss (one per MoE layer), and returns (logits, weighted_aux) so
    the train step adds the load-balance pressure to the task loss.

    Without this the routers get no balancing gradient, collapse onto a few
    experts, and capacity-bounded dispatch silently drops most tokens.
    """

    def apply_fn(params, inputs):
        logits, cols = model.apply(
            params, inputs, mesh=mesh, mutable=["losses"])
        aux_leaves = jax.tree.leaves(cols.get("losses", {}))
        aux = sum(aux_leaves) if aux_leaves else jnp.zeros(())
        return logits, aux_loss_weight * aux

    return apply_fn

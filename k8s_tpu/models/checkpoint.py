"""Checkpoint/resume for training state (orbax-backed).

The reference has no checkpoint subsystem: user code owned checkpoints via
mounted volumes, and the operator contributed only retryable restarts +
stable pod identity (SURVEY.md §5 "Checkpoint / resume").  The TPU-native
rebuild keeps that division but supplies the workload half: an
orbax CheckpointManager wrapper that

- saves the full train state (params / opt_state / step) atomically, with
  ``max_to_keep`` pruning and optional async saves;
- restores **sharding-aware**: the target state's NamedShardings are used as
  restore args so each host reads only its shards (multi-host resume after a
  gang restart lands shards directly on the right devices);
- implements the resume contract ``restore_or_init``: a fresh pod started by
  the operator after a retryable failure (SIGTERM/143 preemption — exit-code
  policy in k8s_tpu.util.train_util) finds CHECKPOINT_DIR via the launcher
  env (k8s_tpu.launcher.bootstrap.LauncherConfig.checkpoint_dir) and picks
  up at the last saved step;
- ``save_on_preemption`` wires the operator's SIGTERM grace window into a
  final synchronous save.

Directory layout is plain orbax (``<dir>/<step>/...``), so checkpoints are
inspectable with stock tooling.
"""

from __future__ import annotations

import logging
from k8s_tpu.analysis import checkedlock
from typing import Any, Optional

log = logging.getLogger(__name__)


class Checkpointer:
    """Train-state checkpoint manager.

    Args:
      directory: checkpoint root (CHECKPOINT_DIR from the operator env).
      max_to_keep: newest N checkpoints kept, older pruned.
      save_interval_steps: ``maybe_save`` only saves on multiples of this.
      async_save: overlap serialization with the next train steps
        (``wait()`` or a subsequent save joins the writer).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = str(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        self._lock = checkedlock.make_lock("checkpoint")

    # -- save ------------------------------------------------------------

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save ``state`` at ``step``.  Returns True if a save happened
        (CheckpointManagerOptions may skip off-interval steps unless
        ``force``)."""
        with self._lock:
            return self._save_locked(step, state, force)

    def _save_locked(self, step: int, state: Any, force: bool) -> bool:
        return self._mgr.save(
            int(step), args=self._ocp.args.StandardSave(state), force=force)

    def maybe_save(self, step: int, state: Any) -> bool:
        """Interval-respecting save (the per-step call site in train loops)."""
        return self.save(step, state)

    # -- restore ---------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, target_state: Any) -> Any:
        """Restore ``step`` shaped/sharded like ``target_state`` (abstract
        arrays with NamedShardings restore shard-local per host)."""
        import jax

        abstract = jax.tree.map(_as_abstract, target_state)
        return self._mgr.restore(
            int(step), args=self._ocp.args.StandardRestore(abstract))

    def restore_latest(self, target_state: Any) -> tuple[Any, Optional[int]]:
        step = self.latest_step()
        if step is None:
            return target_state, None
        return self.restore(step, target_state), step

    def restore_or_init(self, target_state: Any) -> tuple[Any, int]:
        """The resume contract: (restored_state, next_step) if a checkpoint
        exists, else (target_state, 0).  Fresh pods after a gang restart call
        this unconditionally."""
        state, step = self.restore_latest(target_state)
        if step is None:
            log.info("no checkpoint under %s; fresh start", self.directory)
            return target_state, 0
        log.info("resumed from step %d under %s", step, self.directory)
        return state, step + 1

    # -- lifecycle -------------------------------------------------------

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait(self) -> None:
        """Join any in-flight async save."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def save_on_preemption(self, get_state, get_step):
        """Register a SIGTERM hook that synchronously saves before the pod's
        grace period expires (TPU preemptions surface as SIGTERM/143, which
        the operator's exit-code policy treats as retryable — the checkpoint
        makes that restart cheap).  ``get_state``/``get_step`` are callables
        so the hook reads the *current* values at signal time.

        Best-effort by design: Python signal handlers run on the main
        thread between bytecodes, so if the signal lands while a regular
        interval save holds the manager lock, blocking here would deadlock
        the process inside its grace window — instead the hook skips (the
        in-flight save is at most one interval stale).  Cooperative loops
        (train.fit with preemption handling) save deterministically at the
        next step boundary regardless.

        Returns the unsubscribe callable from signals.on_shutdown."""
        from k8s_tpu.util import signals

        def _save_now():
            if not self._lock.acquire(blocking=False):
                log.warning(
                    "SIGTERM during an in-flight save; skipping final save")
                return
            try:
                step = int(get_step())
                log.warning("SIGTERM: checkpointing step %d before exit", step)
                self._save_locked(step, get_state(), force=True)
                self._mgr.wait_until_finished()
            except Exception:  # pragma: no cover - best effort on the way out
                log.exception("preemption checkpoint failed")
            finally:
                self._lock.release()

        return signals.on_shutdown(_save_now)


def _as_abstract(x):
    """Leaf → jax.ShapeDtypeStruct carrying sharding when present."""
    import jax

    if hasattr(x, "shape") and hasattr(x, "dtype"):
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
    return x

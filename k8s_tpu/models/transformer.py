"""Transformer family: one decoder/encoder implementation covering the
BASELINE.json workload configs — BERT-base-style fine-tune (bidirectional)
and Llama-style causal LM with FSDP/TP/SP shardings.

TPU-first choices:
- RMSNorm + SwiGLU + rotary embeddings (modern decoder recipe), all fusible
  elementwise chains around the MXU matmuls;
- bf16 activations, f32 params/softmax accumulation;
- attention is pluggable: plain XLA attention for short context, ring
  attention over the ``sp`` mesh axis for long context
  (k8s_tpu.parallel.ring_attention);
- logical sharding annotations (``nn.with_logical_partitioning`` style is
  hand-rolled: params are plain, shardings applied by
  k8s_tpu.parallel.sharding rules keyed on param-tree paths).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    ffn_hidden: int = 11008
    layers: int = 32
    heads: int = 32
    kv_heads: int = 32
    head_dim: Optional[int] = None
    max_seq_len: int = 4096
    causal: bool = True
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    use_ring_attention: bool = False
    # sequence-parallel strategy when use_ring_attention is set: "ring"
    # rotates K/V (memory-optimal, works for any head count); "ulysses"
    # all-to-alls seq<->head shards (two collectives per layer, needs
    # heads % sp == 0) — parallel/ulysses.py
    sp_strategy: str = "ring"
    # ring K/V placement: "contiguous" | "zigzag" (causal load balancing —
    # rank r owns blocks (r, 2sp-1-r) so every ring step costs every rank
    # one chunk of flash work; parallel/ring_flash.py).  Zigzag needs the
    # flash ring (use_flash_attention) and an even sp; it silently falls
    # back to contiguous on an odd ring.
    ring_layout: str = "contiguous"
    use_flash_attention: bool = False  # Pallas fused kernel (k8s_tpu.ops)
    # flash kernel tile sizes (None -> kernel defaults); sweepable per
    # device generation without touching the kernel
    flash_block_q: Optional[int] = None
    flash_block_k: Optional[int] = None
    use_fused_norm: bool = False  # Pallas RMSNorm kernel (k8s_tpu.ops)
    # Sliding-window attention (Mistral/Gemma-style): each query attends
    # the window most recent positions (0 <= q - k < window, causal only).
    # Flash path bounds the kernel GRID (out-of-window key blocks are never
    # DMA'd — O(L*window) compute); the plain path applies the same mask
    # over the O(L^2) scores; the sp ring composes via the windowed ring
    # (bounded neighbor hops).  Decode uses an O(window) ring-buffer cache.
    window_size: Optional[int] = None
    # Chunked prefill (decode.py make_generate_fn(prefill_chunk=...)):
    # the largest multi-token chunk the decode-mode cache must serve in
    # one call.  Windowed caches size their ring window+chunk-1 so a
    # chunk's earliest query still sees its full window before the
    # chunk's own writes evict it; irrelevant for full-length caches.
    prefill_chunk: int = 1
    # KV-cache storage dtype for DECODE: None stores cfg.dtype; "int8"
    # stores per-(slot, head)-scaled int8 (absmax/127 symmetric), halving
    # the per-token KV HBM reads decode is bound by (see bench.py's
    # roofline: bytes/token = params/batch + 2*layers*kv_heads*head_dim*
    # len*itemsize).  Dequantization happens after the HBM load, fused
    # into the attention einsum's operand feed by XLA.  Training/prefill
    # attention math is untouched — only cache storage quantizes.
    kv_cache_dtype: Optional[str] = None
    remat: bool = True  # jax.checkpoint each layer: HBM for FLOPs
    # MoE (k8s_tpu.models.moe): >0 swaps the dense MLP for routed experts
    # sharded over the ep mesh axis
    num_experts: int = 0
    expert_top_k: int = 2
    expert_capacity_factor: float = 1.25

    @property
    def dims_per_head(self) -> int:
        return self.head_dim or self.hidden // self.heads


# Preset configs matching BASELINE.json workloads.
def llama_8b() -> TransformerConfig:
    """Llama-3-8B-shaped (stretch config, v5p-32 FSDP)."""
    return TransformerConfig(
        vocab_size=128256, hidden=4096, ffn_hidden=14336, layers=32,
        heads=32, kv_heads=8, max_seq_len=8192, rope_theta=500000.0,
    )


def bert_base() -> TransformerConfig:
    """BERT-base-shaped bidirectional encoder (fine-tune config)."""
    return TransformerConfig(
        vocab_size=30522, hidden=768, ffn_hidden=3072, layers=12,
        heads=12, kv_heads=12, max_seq_len=512, causal=False,
    )


def tiny_test() -> TransformerConfig:
    """CPU-testable config."""
    return TransformerConfig(
        vocab_size=256, hidden=64, ffn_hidden=128, layers=2, heads=4,
        kv_heads=4, max_seq_len=128, dtype=jnp.float32, remat=False,
    )


class RMSNorm(nn.Module):
    eps: float = 1e-6
    fused: bool = False  # Pallas row kernel instead of XLA chain

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        if self.fused:
            from k8s_tpu.ops import rms_norm

            return rms_norm(x, scale, eps=self.eps)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


def rotary_embedding(x, positions, theta: float):
    """Apply RoPE to [B, L, H, D] given [B, L] positions."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, L, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _plain_attention(q, k, v, causal: bool, window: int | None = None):
    """XLA attention with f32 softmax; fused by the compiler on TPU.

    ``window`` applies the sliding-window mask ``0 <= q_pos - k_pos <
    window`` — the same convention as the flash kernels'
    ``_window_visible`` (ops/flash_attention.py), so the two paths are
    interchangeable in exactness tests.  Here it is a mask over the full
    O(L^2) score matrix (the flash path is where the compute bound lives).
    The flash kernels' contract is enforced here too: a window is a causal
    construction and must be >= 1 (window=0 would mask EVERY key and
    softmax a row of -1e30s into uniform garbage).
    """
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (matching "
                             "ops.flash_attention's contract)")
        if window < 1:
            raise ValueError("window must be >= 1")
    B, L, H, D = q.shape
    kv_heads = k.shape[2]
    if kv_heads != H:  # grouped-query: repeat kv heads
        rep = H // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (D ** -0.5)
    if causal or window is not None:
        qpos = jnp.arange(L)[:, None]
        kpos = jnp.arange(L)[None, :]
        mask = jnp.ones((L, L), bool)
        if causal:
            mask &= kpos <= qpos
        if window is not None:
            mask &= qpos - kpos < window
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


class Attention(nn.Module):
    # mesh is a module attribute (static metadata), not a call argument:
    # under nn.remat a call argument would be treated as a traced array and
    # jax.sharding.Mesh has no dtype, crashing every remat-enabled config.
    config: TransformerConfig
    mesh: Any = None

    def _cache_vars(self, batch: int):
        """KV cache for autoregressive decoding (flax ``cache`` collection).

        Cache length is window-sized when sliding-window attention is
        configured — a RING BUFFER (slot = position % S): decode memory is
        O(window), not O(max_seq_len), which is the whole point of SWA at
        inference (Mistral-style).  The ring holds ``window +
        prefill_chunk - 1`` slots: a multi-token chunk writes itself
        before attending, so the chunk's FIRST query (needing keys back to
        q - window + 1) must still find them un-evicted after the chunk's
        last write — the extra chunk-1 slots are exactly that headroom,
        and the window upper bound is enforced by the mask instead of the
        ring size.  Keys are stored post-rotary (RoPE is
        absolute-position, applied at write time), and per-slot absolute
        positions make the validity/causal/window mask exact in all
        regimes.
        """
        cfg = self.config
        # ring size is window-based, not min'd with max_seq_len: a window
        # wider than max_seq_len still needs all window slots once decoding
        # runs past max_seq_len, or the cache would silently narrow it
        if cfg.window_size:
            S = cfg.window_size + max(1, cfg.prefill_chunk) - 1
        else:
            S = cfg.max_seq_len
        shape = (batch, S, cfg.kv_heads, cfg.dims_per_head)
        if cfg.kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_cache_dtype must be None or 'int8', "
                f"got {cfg.kv_cache_dtype!r}")
        if cfg.kv_cache_dtype == "int8":
            ck = self.variable("cache", "k", jnp.zeros, shape, jnp.int8)
            cv = self.variable("cache", "v", jnp.zeros, shape, jnp.int8)
            # per-(slot, head) absmax scales; float32 (4B per 64-128B
            # vector — negligible traffic, no precision stacking).  Batch
            # axis first so beam search's cache-pytree gather reorders
            # scales with their vectors.
            cks = self.variable("cache", "k_scale", jnp.zeros,
                                shape[:3], jnp.float32)
            cvs = self.variable("cache", "v_scale", jnp.zeros,
                                shape[:3], jnp.float32)
        else:
            ck = self.variable("cache", "k", jnp.zeros, shape, cfg.dtype)
            cv = self.variable("cache", "v", jnp.zeros, shape, cfg.dtype)
            cks = cvs = None
        cp = self.variable(
            "cache", "pos", lambda: jnp.full((batch, S), -1, jnp.int32))
        return ck, cv, cks, cvs, cp, S

    def _kv_cache_write(self, ck, scale_var, b, slots, x):
        """Store [B, L, H, D] vectors at cache slots, quantizing when the
        cache is int8 (symmetric absmax per vector — models/paged.py's
        quantize_kv, the one definition shared with the pool write)."""
        if self.config.kv_cache_dtype == "int8":
            from k8s_tpu.models.paged import quantize_kv

            q, scale = quantize_kv(x)
            ck.value = ck.value.at[b, slots].set(q)
            scale_var.value = scale_var.value.at[b, slots].set(scale)
        else:
            ck.value = ck.value.at[b, slots].set(x.astype(self.config.dtype))

    def _kv_cache_read(self, ck, scale_var):
        """The full cache as cfg.dtype vectors (dequantized when int8 —
        the int8 load IS the HBM saving; the convert+scale fuses into the
        attention einsum's operand feed)."""
        if self.config.kv_cache_dtype == "int8":
            # dequantize in f32 (int8 * f32 scale), cast the PRODUCT once:
            # casting the scale itself to bf16 first would stack ~0.2%
            # scale-rounding error on the int8 step error it was stored
            # as f32 to avoid
            return (ck.value.astype(jnp.float32)
                    * scale_var.value[..., None]).astype(self.config.dtype)
        return ck.value

    def _paged_decode_step(self, q, k, v, positions):
        """Decode over the serving engine's block-pool cache: new K/V
        scatter straight into pool blocks through the per-row block
        table (write-masked slots at position -1 are dropped, never
        clipped into a live block) and attention runs behind the
        ``paged_attention`` seam (models/paged.py) — no per-row gathered
        view is materialized or written back.  The engine provides the
        cache collection: pool-shaped ``k``/``v`` (+ int8 scales) leaves
        plus ``table`` [B, max_blocks] and ``len`` [B] (each row's
        written length before this chunk, the validity bound)."""
        cfg = self.config
        if cfg.window_size:
            raise ValueError(
                "paged decode needs a full cache: a windowed ring wraps "
                "positions per row and does not decompose into "
                "absolute-position pool blocks")
        from k8s_tpu.models import paged

        def _missing():
            raise ValueError("paged cache collections are built by the "
                             "serving engine, never initialized here")

        ck = self.variable("cache", "k", _missing)
        cv = self.variable("cache", "v", _missing)
        int8 = cfg.kv_cache_dtype == "int8"
        cks = self.variable("cache", "k_scale", _missing) if int8 else None
        cvs = self.variable("cache", "v_scale", _missing) if int8 else None
        tables = self.variable("cache", "table", _missing).value
        lengths = self.variable("cache", "len", _missing).value
        # tensor-parallel serving (ISSUE 14): with a tp>1 mesh the pool
        # is sharded along the kv-head axis per host and both the write
        # scatter and the attention read run inside shard_map islands
        # (models/paged.py) — zero collectives, same per-head math, and
        # the sharding is PINNED so GSPMD can never re-materialize the
        # pool.  Everything above this routing is untouched.
        tp = self.mesh.shape.get("tp", 1) if self.mesh is not None else 1
        if tp > 1:
            ck.value, ks = paged.paged_kv_write_tp(
                self.mesh, ck.value, tables, positions, k,
                scale_leaf=cks.value if int8 else None, quantize=int8)
            cv.value, vs = paged.paged_kv_write_tp(
                self.mesh, cv.value, tables, positions, v,
                scale_leaf=cvs.value if int8 else None, quantize=int8)
            if int8:
                cks.value, cvs.value = ks, vs
            return paged.paged_attention_tp(
                self.mesh, q, ck.value, cv.value, tables, lengths,
                positions, k_scale=cks.value if int8 else None,
                v_scale=cvs.value if int8 else None, dtype=cfg.dtype)
        ck.value, ks = paged.paged_kv_write(
            ck.value, tables, positions, k,
            scale_leaf=cks.value if int8 else None, quantize=int8)
        cv.value, vs = paged.paged_kv_write(
            cv.value, tables, positions, v,
            scale_leaf=cvs.value if int8 else None, quantize=int8)
        if int8:
            cks.value, cvs.value = ks, vs
        return paged.paged_attention(
            q, ck.value, cv.value, tables, lengths, positions,
            k_scale=cks.value if int8 else None,
            v_scale=cvs.value if int8 else None, dtype=cfg.dtype)

    def _decode_step(self, q, k, v, positions):
        """One cached decode call: write this chunk's K/V, attend the cache.

        q/k/v are [B, Lc, H(kv), D] post-rotary (Lc = 1 for the token
        loop, up to config.prefill_chunk for chunked prefill); positions
        is [B, Lc] absolute.  Writes happen BEFORE attending; the mask
        then does all the work — slot validity (kpos >= 0), causality
        (kpos <= qpos, which also hides the chunk's own future tokens),
        and the sliding window (qpos - kpos < window) when configured,
        since a chunk-sized ring holds slightly more than one window.

        When the engine hands over a block-pool cache (a ``table``
        variable is present), the paged path takes over: pool-direct
        writes plus the ``paged_attention`` seam.
        """
        cfg = self.config
        if self.has_variable("cache", "table"):
            return self._paged_decode_step(q, k, v, positions)
        B, Lc = q.shape[0], q.shape[1]
        if cfg.window_size and Lc > max(1, cfg.prefill_chunk):
            raise ValueError(
                f"decode chunk of {Lc} tokens exceeds prefill_chunk "
                f"({cfg.prefill_chunk}): the windowed ring cache only has "
                "window + prefill_chunk - 1 slots, so a larger chunk "
                "would evict keys its own earliest query still needs")
        ck, cv, cks, cvs, cp, S = self._cache_vars(B)
        b = jnp.arange(B)[:, None]
        slot = positions % S  # [B, Lc]
        self._kv_cache_write(ck, cks, b, slot, k)
        self._kv_cache_write(cv, cvs, b, slot, v)
        cp.value = cp.value.at[b, slot].set(positions)
        keys = self._kv_cache_read(ck, cks)
        values = self._kv_cache_read(cv, cvs)
        kpos = cp.value
        # grouped-query via grouped einsum: query head j attends kv head
        # j // rep (the same consecutive-duplication order as jnp.repeat
        # on axis 2) WITHOUT materializing a heads/kv_heads-times larger
        # copy of the cache inside the token loop's hot path
        rep = cfg.heads // cfg.kv_heads
        qg = q.reshape(B, Lc, cfg.kv_heads, rep, cfg.dims_per_head)
        scores = jnp.einsum("bqhrd,bkhd->bhrqk", qg, keys).astype(jnp.float32)
        scores = scores * (cfg.dims_per_head ** -0.5)
        mask = (kpos >= 0)[:, None, :] & \
            (kpos[:, None, :] <= positions[:, :, None])  # [B, Lc, S]
        if cfg.window_size:
            mask &= positions[:, :, None] - kpos[:, None, :] \
                < cfg.window_size
        scores = jnp.where(mask[:, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhrqk,bkhd->bqhrd", probs.astype(values.dtype),
                         values)
        return out.reshape(B, Lc, cfg.heads, cfg.dims_per_head)

    def _prefill_write(self, k, v, positions):
        """Scatter the prompt's last min(L, S) K/V into the cache."""
        B, L = k.shape[:2]
        ck, cv, cks, cvs, cp, S = self._cache_vars(B)
        keep = min(L, S)
        b = jnp.arange(B)[:, None]
        last_pos = positions[:, L - keep:]
        slots = last_pos % S
        self._kv_cache_write(ck, cks, b, slots, k[:, L - keep:])
        self._kv_cache_write(cv, cvs, b, slots, v[:, L - keep:])
        cp.value = cp.value.at[b, slots].set(last_pos)

    @nn.compact
    def __call__(self, x, positions, mode: str = "train"):
        cfg = self.config
        mesh = self.mesh
        D = cfg.dims_per_head
        dense = lambda feats, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name,
        )
        q = dense((cfg.heads, D), "q_proj")(x)
        k = dense((cfg.kv_heads, D), "k_proj")(x)
        v = dense((cfg.kv_heads, D), "v_proj")(x)
        q = rotary_embedding(q, positions, cfg.rope_theta)
        k = rotary_embedding(k, positions, cfg.rope_theta)

        if mode == "decode":
            out = self._decode_step(q, k, v, positions)
        elif mode == "prefill":
            # prompt attention is the ordinary causal (+window) pass; the
            # only extra work is writing K/V into the cache for the token
            # loop that follows
            self._prefill_write(k, v, positions)
            if cfg.use_flash_attention:
                from k8s_tpu.ops import flash_attention
                from k8s_tpu.ops.flash_attention import (
                    DEFAULT_BLOCK_K,
                    DEFAULT_BLOCK_Q,
                )

                out = flash_attention(
                    q, k, v, causal=True, window=cfg.window_size,
                    block_q=cfg.flash_block_q or DEFAULT_BLOCK_Q,
                    block_k=cfg.flash_block_k or DEFAULT_BLOCK_K,
                )
            else:
                out = _plain_attention(
                    q, k, v, causal=True, window=cfg.window_size)
        elif cfg.use_ring_attention and mesh is not None:
            if cfg.window_size is not None and not (
                    cfg.sp_strategy == "ring" and cfg.use_flash_attention):
                raise ValueError(
                    "window_size under sequence parallelism needs the flash "
                    "ring (sp_strategy='ring' + use_flash_attention); the "
                    "plain ring and ulysses paths would silently ignore it")
            if cfg.window_size is not None and not cfg.causal:
                raise ValueError(
                    "window_size requires causal=True (the windowed ring is "
                    "a causal construction); matching flash_attention's "
                    "single-device contract")
            if cfg.sp_strategy not in ("ring", "ulysses"):
                raise ValueError(
                    f"unknown sp_strategy {cfg.sp_strategy!r} "
                    "(expected 'ring' or 'ulysses')")
            kv_heads = k.shape[2]
            # The flash ring handles GQA natively: K/V ride the ring at
            # Hkv heads (ICI traffic / group) and expand per flash call.
            # Everything else still wants the pre-ring repeat, as does a
            # tp size the native kv head count can't shard.
            ring_flash_path = (cfg.sp_strategy == "ring"
                               and cfg.use_flash_attention)
            tp_size = mesh.shape.get("tp", 1)
            if kv_heads != cfg.heads and not (
                    ring_flash_path and kv_heads % tp_size == 0):
                rep = cfg.heads // kv_heads
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            if cfg.sp_strategy == "ulysses":
                from k8s_tpu.parallel.ulysses import ulysses_attention

                out = ulysses_attention(
                    mesh, q, k, v, causal=cfg.causal,
                    use_flash=cfg.use_flash_attention,
                    block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
                )
            elif cfg.use_flash_attention:
                # ring + flash compose: ring for O(L/sp) memory across the
                # mesh, the Pallas kernel for the per-shard block compute
                from k8s_tpu.parallel.ring_flash import ring_flash_attention
                from k8s_tpu.ops.flash_attention import (
                    DEFAULT_BLOCK_K,
                    DEFAULT_BLOCK_Q,
                )

                if cfg.window_size is not None:
                    # windowed ring: only the ceil(window/chunk) neighbor
                    # chunks are exchanged — ICI hops O(window/Lc), not sp
                    from k8s_tpu.parallel.ring_flash import (
                        ring_flash_attention_windowed,
                    )

                    out = ring_flash_attention_windowed(
                        mesh, q, k, v, window=cfg.window_size,
                        block_q=cfg.flash_block_q or DEFAULT_BLOCK_Q,
                        block_k=cfg.flash_block_k or DEFAULT_BLOCK_K,
                    )
                else:
                    out = ring_flash_attention(
                        mesh, q, k, v, causal=cfg.causal,
                        block_q=cfg.flash_block_q or DEFAULT_BLOCK_Q,
                        block_k=cfg.flash_block_k or DEFAULT_BLOCK_K,
                        layout=cfg.ring_layout if cfg.causal else "contiguous",
                    )
            else:
                from k8s_tpu.parallel.ring_attention import ring_attention

                out = ring_attention(mesh, q, k, v, causal=cfg.causal)
        elif cfg.use_flash_attention:
            from k8s_tpu.ops import flash_attention
            from k8s_tpu.ops.flash_attention import (
                DEFAULT_BLOCK_K,
                DEFAULT_BLOCK_Q,
            )

            out = flash_attention(
                q, k, v, causal=cfg.causal,
                block_q=cfg.flash_block_q or DEFAULT_BLOCK_Q,
                block_k=cfg.flash_block_k or DEFAULT_BLOCK_K,
                window=cfg.window_size,
            )
        else:
            out = _plain_attention(q, k, v, cfg.causal,
                                   window=cfg.window_size)

        return nn.DenseGeneral(
            x.shape[-1], axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name="o_proj",
        )(out)


class MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32, name=name
        )
        gate = dense(cfg.ffn_hidden, "gate_proj")(x)
        up = dense(cfg.ffn_hidden, "up_proj")(x)
        return dense(x.shape[-1], "down_proj")(nn.silu(gate) * up)


class Block(nn.Module):
    config: TransformerConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, x, positions, mode: str = "train"):
        cfg = self.config
        fused = cfg.use_fused_norm
        y = Attention(cfg, mesh=self.mesh, name="attn")(
            RMSNorm(fused=fused, name="attn_norm")(x), positions, mode
        )
        x = x + y
        if cfg.num_experts > 0:
            from k8s_tpu.models.moe import MoeMLP

            mlp = MoeMLP(
                num_experts=cfg.num_experts,
                ffn_hidden=cfg.ffn_hidden,
                top_k=cfg.expert_top_k,
                capacity_factor=cfg.expert_capacity_factor,
                dtype=cfg.dtype,
                mesh=self.mesh,
                name="moe_mlp",
            )
        else:
            mlp = MLP(cfg, name="mlp")
        y = mlp(RMSNorm(fused=fused, name="mlp_norm")(x))
        return x + y


class Transformer(nn.Module):
    """Token-in, logits-out decoder (or encoder when config.causal=False)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens, mesh=None, return_hidden: bool = False,
                 positions=None, mode: str = "train"):
        """``mode``: "train" (the default full teacher-forced pass),
        "prefill" (same pass + KV-cache population), or "decode" (one
        cached token step; ``positions`` carries the absolute position).

        Decode modes are single-device (or dp/tp-sharded) paths: the sp
        ring is a training-scale construction and is rejected rather than
        silently mis-composed (models/decode.py is the driver).  MoE
        configs decode (see the capacity note below).
        """
        cfg = self.config
        B, L = tokens.shape
        if mode not in ("train", "prefill", "decode"):
            raise ValueError(f"unknown mode {mode!r}")
        if mode != "train":
            if not cfg.causal:
                raise ValueError("decode modes require causal=True")
            if cfg.use_ring_attention:
                raise ValueError(
                    "decode modes do not compose with the sp ring "
                    "(use_ring_attention); decode on the unsharded or "
                    "dp/tp mesh instead")
            # MoE decodes: routing is per-token, so cached decode matches
            # the teacher-forced pass EXACTLY whenever no (token, choice)
            # pair overflows expert capacity.  Capacity competition is per
            # CALL (batch*1 tokens per decode step vs batch*seq in
            # training) — raise capacity_factor for serving if drops are
            # observed; the aux-loss sow is a no-op outside training
            # (the "losses" collection is not mutable here).
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(L), (B, L))
        emb = self.param(
            "embedding",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden),
            jnp.float32,
        )
        x = emb[tokens].astype(cfg.dtype)

        # remat trades HBM for recompute in the backward pass; decode has
        # no backward, and threading the static mode string through
        # nn.remat would need static_argnums plumbing for zero benefit
        block = nn.remat(Block) if (cfg.remat and mode == "train") else Block
        for i in range(cfg.layers):
            if mode == "train":
                x = block(cfg, mesh=mesh, name=f"layer_{i}")(x, positions)
            else:
                x = block(cfg, mesh=mesh, name=f"layer_{i}")(
                    x, positions, mode)

        x = RMSNorm(fused=cfg.use_fused_norm, name="final_norm")(x)
        if return_hidden:
            # pre-head hidden states for the fused-CE path
            # (ops.fused_ce.fused_linear_cross_entropy takes hidden + the
            # embedding matrix and never materializes [B, L, V] logits)
            return x.astype(cfg.dtype)
        # tied embeddings: logits = x @ emb.T.  bf16 operands on the MXU
        # with f32 accumulation (preferred_element_type) — an f32 matmul
        # here would run at a fraction of MXU peak while the vocab
        # projection is a double-digit share of forward FLOPs; the f32
        # accumulate keeps the softmax stable.
        logits = jnp.einsum(
            "bld,vd->blv", x.astype(cfg.dtype), emb.astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        )
        return logits

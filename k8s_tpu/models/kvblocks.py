"""Host-side bookkeeping for the engine's block-granular KV cache:
a refcounted block allocator and a radix-style prefix tree over
block-sized token runs (models/engine.py is the only consumer).

The DEVICE side — one pooled pytree of ``[num_blocks, block_size, ...]``
leaves per cache tensor, gathered into per-request views by block
tables — lives in the engine; this module owns the invariants:

- **Refcounts.**  Every reference to a block holds exactly one count: a
  slot's block table entry, or a prefix-tree node.  ``release`` returns
  the block to the free list only at zero — retiring a request can
  never free a block another slot (or the tree) still references.
- **Null block.**  Block 0 is reserved and never allocated: block-table
  padding points at it, and the batched step routes inactive rows'
  stray writes into it (position -1, so nothing ever attends it).
- **Radix tree.**  Nodes are block-sized token runs; a child either
  matches the next ``block_size`` prompt tokens exactly (attach the
  whole block by reference) or shares a proper prefix with them (the
  DIVERGENCE block: the engine copy-on-writes it and prefills only the
  unshared remainder).  Matching is capped so at least one prompt token
  is always prefilled privately — the engine needs the last prompt
  position's logits, and recomputing one token is cheaper than any
  scheme for resurrecting them from a shared block.
- **Eviction.**  The tree is a cache: when the free list runs dry the
  engine evicts least-recently-hit LEAF nodes (dropping only the
  tree's reference — a block a live slot still uses survives until
  that slot retires).  With ``num_blocks >= 1 + slots * blocks_per_row``
  allocation therefore always succeeds.

All mutation happens on the single engine thread; nothing here locks.
"""

from __future__ import annotations

from collections import deque
from typing import Optional


class BlockPool:
    """Refcounted free-list allocator over ``num_blocks`` device blocks
    (block 0 reserved as the null block)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"pool needs >= 2 blocks (null + one usable), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: deque[int] = deque(range(1, num_blocks))
        self._ref = [0] * num_blocks

    def alloc(self) -> Optional[int]:
        """Pop a free block at refcount 1, or None when the free list is
        empty (the caller evicts tree leaves and retries)."""
        if not self._free:
            return None
        idx = self._free.popleft()
        self._ref[idx] = 1
        return idx

    def retain(self, idx: int) -> None:
        if idx <= 0 or self._ref[idx] < 1:
            raise AssertionError(f"retain of dead/null block {idx}")
        self._ref[idx] += 1

    def release(self, idx: int) -> bool:
        """Drop one reference; True when the block was actually freed."""
        if idx <= 0 or self._ref[idx] < 1:
            raise AssertionError(f"release of dead/null block {idx}")
        self._ref[idx] -= 1
        if self._ref[idx] == 0:
            self._free.append(idx)
            return True
        return False

    def refcount(self, idx: int) -> int:
        return self._ref[idx]

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Live blocks excluding the null block."""
        return self.num_blocks - 1 - len(self._free)


class PrefixNode:
    __slots__ = ("tokens", "block", "parent", "children", "last_hit")

    def __init__(self, tokens: tuple, block: int,
                 parent: Optional["PrefixNode"]):
        self.tokens = tokens          # the block's token run (len == bs)
        self.block = block            # pool block holding its K/V
        self.parent = parent
        self.children: dict[tuple, PrefixNode] = {}
        self.last_hit = 0


def chain_tokens(node: PrefixNode) -> list[int]:
    """The full token chain a node's block terminates — its run plus
    every ancestor's, root-first.  The spill tier (ISSUE 17) keys
    demoted blocks by the chain's cumulative fingerprint, and the chain
    is only reachable through ``parent`` links, so demotion reads it
    BEFORE the node detaches."""
    runs: list[tuple] = []
    cur: Optional[PrefixNode] = node
    while cur is not None and cur.parent is not None:
        runs.append(cur.tokens)
        cur = cur.parent
    out: list[int] = []
    for run in reversed(runs):
        out.extend(run)
    return out


class PrefixTree:
    """Radix tree over block-sized token-id runs.  The root is a
    sentinel (no tokens, no block); every real node pins one pool block
    with one reference (taken by the engine at insert, dropped at
    evict)."""

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        self.root = PrefixNode((), 0, None)
        self._clock = 0
        self.nodes = 0
        # lifetime leaf evictions (pool-pressure signal: the engine's
        # stats() and the request recorder's evict phase both read it)
        self.evictions = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, ids, max_tokens: int
              ) -> tuple[list[PrefixNode], Optional[tuple[PrefixNode, int]]]:
        """Longest cached prefix of ``ids`` using at most ``max_tokens``
        tokens: ``(full_nodes, partial)`` where ``full_nodes`` are
        whole-block matches in order and ``partial`` is ``(node, j)``
        for a divergence block sharing its first ``j`` (< block_size)
        tokens — the engine copy-on-writes that one.
        """
        bs = self.block_size
        ids = [int(t) for t in ids]
        now = self._tick()
        cur = self.root
        full: list[PrefixNode] = []
        while (len(full) + 1) * bs <= max_tokens:
            run = tuple(ids[len(full) * bs:(len(full) + 1) * bs])
            child = cur.children.get(run)
            if child is None:
                break
            child.last_hit = now
            full.append(child)
            cur = child
        base = len(full) * bs
        budget = max_tokens - base
        best: Optional[tuple[PrefixNode, int]] = None
        if budget >= 1:
            rest = ids[base:base + bs]
            for child in cur.children.values():
                j = 0
                for a, b in zip(child.tokens, rest):
                    if a != b:
                        break
                    j += 1
                j = min(j, budget)
                if j >= 1 and (best is None or j > best[1]):
                    best = (child, j)
            if best is not None:
                best[0].last_hit = now
        return full, best

    def insert(self, matched: list[PrefixNode], ids, blocks: list[int],
               ) -> list[PrefixNode]:
        """Extend the matched path with nodes for the remaining full
        blocks of ``ids``; ``blocks[i]`` is the pool block holding block
        ``i``'s K/V (the inserting request's table).  Returns the NEW
        nodes — the caller retains one pool reference per new node.
        Already-present runs are reused, never duplicated."""
        bs = self.block_size
        ids = [int(t) for t in ids]
        n_full = len(ids) // bs
        now = self._tick()
        cur = self.root
        for node in matched:
            cur = node
        created: list[PrefixNode] = []
        for i in range(len(matched), n_full):
            run = tuple(ids[i * bs:(i + 1) * bs])
            child = cur.children.get(run)
            if child is None:
                child = PrefixNode(run, blocks[i], cur)
                cur.children[run] = child
                self.nodes += 1
                created.append(child)
            child.last_hit = now
            cur = child
        return created

    def graft(self, ids, blocks: list[int]) -> list[PrefixNode]:
        """Import seam (ISSUE 15): insert a MIGRATED prompt's full-block
        runs so a prefix that was prefilled on another pod is
        immediately shareable here — ``blocks[i]`` is the LOCAL pool
        block the i-th run was grafted into.  Match-then-insert with the
        engine's exact budget (the last prompt token stays private), so
        runs already cached locally are reused, never duplicated.
        Returns the NEW nodes; the caller retains one pool reference
        per new node, exactly like :meth:`insert`."""
        matched, _partial = self.match(ids, max(0, len(ids) - 1))
        return self.insert(matched, ids, blocks)

    def evict_leaf(self, pinned=None) -> Optional["PrefixNode"]:
        """Remove the least-recently-hit LEAF node and return it (the
        caller drops the tree's pool reference — and, with a spill tier
        (ISSUE 17), demotes the node's content first, reconstructing
        its chain via :func:`chain_tokens` while ``node.parent`` is
        still wired).  ``pinned(block) -> bool`` marks blocks other
        holders (live slots) still reference: evicting those frees
        nothing AND loses a hot cache entry, so they are skipped —
        their pins drop when the holding request retires.  The walk is
        O(nodes) per call; nodes are bounded by the pool size (tens to
        hundreds), so no separate LRU structure is kept."""
        best: Optional[PrefixNode] = None

        def walk(node: PrefixNode) -> None:
            nonlocal best
            for child in node.children.values():
                if child.children:
                    walk(child)
                elif (pinned is None or not pinned(child.block)) and (
                        best is None or child.last_hit < best.last_hit):
                    best = child

        walk(self.root)
        if best is None:
            return None
        del best.parent.children[best.tokens]
        self.nodes -= 1
        self.evictions += 1
        return best

    def evict_one(self, pinned=None) -> Optional[int]:
        """Block-id convenience over :meth:`evict_leaf` (the pre-spill
        call shape: evict means the block's content dies)."""
        node = self.evict_leaf(pinned)
        return None if node is None else node.block

    def clear(self) -> list[int]:
        """Drop every node; returns their block ids for deref."""
        out: list[int] = []

        def walk(node: PrefixNode) -> None:
            for child in node.children.values():
                out.append(child.block)
                walk(child)

        walk(self.root)
        self.root.children = {}
        self.nodes = 0
        return out

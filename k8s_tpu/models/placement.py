"""Placement-agnostic compute seam for the serving engine (ISSUE 14).

The engine (models/engine.py) is two layers that used to be welded
together: a host-side scheduler (slot admission, block-pool bookkeeping,
batch-plan construction — pure Python over numpy) and a set of jitted
device programs (batched decode step, variable-width spec verify,
chunked prefill, copy-on-write).  This module is the seam between them:

- :class:`PagedCompute` holds the pure jittable bodies — exactly the
  math the engine's ``_paged_step_impl`` / ``_spec_step_impl`` /
  prefill / CoW closures used to carry, moved verbatim so they can be
  compiled under ANY placement.  Everything above these functions (the
  transformer, the ``paged_attention`` seam) is untouched.
- :class:`LocalPlacement` compiles them with plain ``jax.jit`` on the
  default device — byte-for-byte today's single-host path: same
  donation, same static arguments, same program inventory, same
  compile-ledger seams.
- ``MeshPlacement`` (models/mesh_serve.py) compiles the SAME bodies
  over a multi-device / multi-process mesh: parameters tensor-sharded
  over the ``tp`` axis, the KV block pool sharded along the head axis
  (each host holds its head slice of every block, addressed by the SAME
  block tables), batch-plan ints replicated.  The chief process runs
  the scheduler unchanged; worker processes replay the per-step plan
  broadcast over the plan bus (models/mp_plan.py).

The seam contract the engine relies on:

- ``wrap(op, fn, ...)`` returns a callable with ``fn``'s signature.
  ``resident_argnums`` marks device-resident state (params, pool,
  tables) that survives between calls on every process; every other
  array argument is per-call host plan data (numpy) that a mesh
  placement must broadcast before executing.
- ``put_tables(np_stack)`` uploads the slot block tables; the returned
  handle is passed back through a resident argument slot.
- Host plan arguments are NUMPY; the placement owns the host→device
  transfer (plain jit accepts numpy directly, so the local path pays
  exactly what it always paid).
- Outputs that the engine reads (sampled tokens, PRNG keys, last-chunk
  logits, acceptance counts) come back fully replicated so
  ``np.asarray`` works identically on a single device and on a
  multi-process mesh.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Mapping

log = logging.getLogger(__name__)


def env_mesh() -> int:
    """K8S_TPU_SERVE_MESH: number of processes in the serving mesh
    (0/unset = single-host LocalPlacement; >= 1 = MeshPlacement over
    ``jax.process_count()`` processes — the launcher env contract
    brings the world up before the server constructs the engine)."""
    raw = os.environ.get("K8S_TPU_SERVE_MESH", "").strip()
    try:
        return max(0, int(raw)) if raw else 0
    except ValueError:
        log.warning("ignoring non-integer K8S_TPU_SERVE_MESH=%r", raw)
        return 0


def env_tp() -> int:
    """K8S_TPU_SERVE_TP: tensor-parallel degree over the serving mesh
    (0/unset = all visible devices)."""
    raw = os.environ.get("K8S_TPU_SERVE_TP", "").strip()
    try:
        return max(0, int(raw)) if raw else 0
    except ValueError:
        log.warning("ignoring non-integer K8S_TPU_SERVE_TP=%r", raw)
        return 0


def _is_cache_node(node) -> bool:
    # detect by k/v (not pos): the POOL's cache nodes carry no pos leaf —
    # validity is synthesized from row lengths at view time
    return isinstance(node, Mapping) and "k" in node and "v" in node \
        and not isinstance(node["k"], Mapping)


def map_cache(tree, fn):
    """Rebuild a cache pytree applying ``fn`` to every attention cache
    node (the dict holding the k/v/pos(/scale) leaves)."""
    if _is_cache_node(tree):
        return fn(tree)
    if isinstance(tree, Mapping):
        return {k: map_cache(v, fn) for k, v in tree.items()}
    return tree


class PagedCompute:
    """The engine's pure jittable compute bodies over one transformer.

    Every method is placement-free: it sees params / pool / plan arrays
    and returns new arrays.  The engine compiles them through a
    :class:`LocalPlacement` (plain jit — today's path) or a mesh
    placement (sharded jit + shard_map'd paged attention); the math is
    the same object either way, so the single-host and multi-host
    engines can never drift apart numerically.

    ``apply_mesh`` threads a device mesh into the transformer's
    ``Attention`` module, which routes the paged decode path through the
    shard_map'd ``paged_attention_tp`` island (models/paged.py) when the
    mesh carries a tp axis > 1 — the transformer body above that seam is
    untouched.
    """

    def __init__(self, config, *, apply_mesh=None):
        from k8s_tpu.models.transformer import Transformer

        self.config = config
        self.model = Transformer(config)
        self.apply_mesh = apply_mesh

    # ---------------------------------------------------- cache helpers

    def paged_cache(self, pool, tables, lens):
        """Attach the per-row block ``table`` and written-``len`` bound
        to every pool cache node: the collection the transformer's paged
        decode path consumes (write straight into pool blocks, attend
        behind the ``paged_attention`` seam)."""
        def build(node):
            return {**node, "table": tables, "len": lens}

        return map_cache(pool, build)

    @staticmethod
    def pool_from_cache(cache):
        """Strip the table/len addressing back off a returned cache
        collection, leaving just the pool leaves."""
        def strip(node):
            return {k: v for k, v in node.items()
                    if k not in ("table", "len")}

        return map_cache(cache, strip)

    def init_cache(self, params, batch: int):
        """Batched dense cache pytree for ``batch`` rows, every slot
        invalid: zeros for K/V(/scale) leaves, -1 for every ``pos`` leaf
        (the mask keys validity off ``pos``, so nothing is reachable) —
        exactly the flax cache init + pos reset, built from the
        eval_shape skeleton so no eager device apply runs (a mesh
        placement cannot run eager ops over global params)."""
        import jax
        import jax.numpy as jnp

        def build(p):
            toks = jnp.zeros((batch, 1), jnp.int32)
            pos = jnp.zeros((batch, 1), jnp.int32)
            _, varz = self.model.apply(
                {"params": p}, toks, positions=pos, mode="decode",
                mutable=["cache"])
            return varz["cache"]

        shapes = jax.eval_shape(build, params)

        def materialize(path_key, leaf):
            if path_key == "pos":
                return jnp.full(leaf.shape, -1, leaf.dtype)
            return jnp.zeros(leaf.shape, leaf.dtype)

        def rec(node):
            if isinstance(node, Mapping):
                return {k: (materialize(k, v)
                            if hasattr(v, "shape")
                            and not isinstance(v, Mapping) else rec(v))
                        for k, v in node.items()}
            return node

        return rec(shapes)

    def pool_manifest(self, params, pool_blocks: int, block_size: int):
        """Shape/dtype skeleton of the block-granular KV pool: every
        dense-cache K/V(/scale) leaf ``[1, S, ...]`` becomes a
        ``[num_blocks, block_size, ...]`` ShapeDtypeStruct.  No pos
        leaf is pooled: validity is synthesized from each row's written
        length at view time, so recycled blocks need no reset pass and
        stale content is unreachable by construction.  NOTHING is
        materialized here — the placement builds the zero pool from
        this skeleton (shard-by-shard on a mesh, so no host ever holds
        a full-size leaf: the 1/N-memory point of multi-host serving
        must hold on the chief too)."""
        import jax
        import jax.numpy as jnp

        def build_shapes(p):
            toks = jnp.zeros((1, 1), jnp.int32)
            pos = jnp.zeros((1, 1), jnp.int32)
            _, varz = self.model.apply(
                {"params": p}, toks, positions=pos, mode="decode",
                mutable=["cache"])
            return varz["cache"]

        template = jax.eval_shape(build_shapes, params)
        N, blk = pool_blocks, block_size

        def build(node):
            return {k: jax.ShapeDtypeStruct(
                (N, blk) + tuple(v.shape[2:]), v.dtype)
                for k, v in node.items() if k != "pos"}

        return map_cache(template, build)

    # ---------------------------------------------------- step programs

    def paged_step(self, params, pool, tables, ints, keys, temps,
                   k: int, sampling: bool):
        """``k`` fused batched decode iterations over the block pool
        (``k`` is jit-static, bounded by the engine's MAX_STEP_TOKENS):
        feed each row's last token at its own position, sample/argmax
        per row from its own distribution (decode.sample_logits_rows —
        the exclusive lane's exact key schedule, one split per emitted
        token), carry the POOL itself through a scan.  K/V writes
        scatter straight into each row's blocks inside the model call
        and attention indexes the pool through the block tables behind
        the ``paged_attention`` seam — nothing is gathered into a
        per-row view or written back.  ``ints`` packs [toks, poss,
        topks] into one [3, B] transfer; a row's position doubles as its
        written length for validity masking.  Inactive rows ride at
        position -1: their writes are dropped before they reach the
        pool."""
        import jax
        import jax.numpy as jnp

        from k8s_tpu.models.decode import sample_logits_rows

        toks0, poss0, topks = ints[0], ints[1], ints[2]

        def body(carry, _):
            pool, toks, poss, kk = carry
            cache = self.paged_cache(pool, tables, jnp.maximum(poss, 0))
            logits, varz = self.model.apply(
                {"params": params, "cache": cache}, toks[:, None],
                positions=poss[:, None], mode="decode",
                mutable=["cache"], mesh=self.apply_mesh)
            pool = self.pool_from_cache(varz["cache"])
            if sampling:
                new_keys, nxt = sample_logits_rows(logits[:, -1], kk,
                                                   temps, topks)
            else:
                # all-greedy batch: the raw-dtype argmax the exclusive
                # lane takes at temperature 0; no key ever advances
                # because no row will ever draw from one
                new_keys = kk
                nxt = jnp.argmax(logits[:, -1],
                                 axis=-1).astype(jnp.int32)
            act = poss >= 0
            return (pool, jnp.where(act, nxt, toks),
                    jnp.where(act, poss + 1, poss), new_keys), nxt

        (pool, _, _, keys_out), toks_all = jax.lax.scan(
            body, (pool, toks0, poss0, keys), None, length=k)
        return pool, toks_all, keys_out  # toks_all [k, B]

    def spec_step(self, params, pool, tables, chunk, ints, keys,
                  temps, k: int, sampling: bool):
        """ONE write-masked variable-width batched step (``k`` = the
        jit-static chunk width W): every participating slot feeds its
        own row of ``chunk`` [B, W] — a speculative slot its last token
        plus ``draft_k - 1`` prompt-lookup drafts (width W), a plain
        slot just its last token (width 1) — at per-slot positions.
        Lanes past a row's width ride at position -1, so their K/V
        writes are DROPPED before reaching the pool (the write mask: a
        mixed-width batch can never scribble past a short row's block
        capacity) and their queries attend nothing.  Accept/reject runs
        row-wise in decode.spec_verify_rows with the exclusive lane's
        exact per-iteration key schedule.  ``ints`` packs [poss, widths,
        topks]; returns (pool, emit [B, W], n_emit [B], new_keys)."""
        import jax.numpy as jnp

        from k8s_tpu.models.decode import spec_verify_rows

        poss, widths, topks = ints[0], ints[1], ints[2]
        ar = jnp.arange(k, dtype=jnp.int32)
        cpos = jnp.where(
            (poss >= 0)[:, None] & (ar[None, :] < widths[:, None]),
            poss[:, None] + ar[None, :], -1)  # [B, W]; -1 = write-masked
        cache = self.paged_cache(pool, tables, jnp.maximum(poss, 0))
        logits, varz = self.model.apply(
            {"params": params, "cache": cache}, chunk,
            positions=cpos, mode="decode", mutable=["cache"],
            mesh=self.apply_mesh)
        pool = self.pool_from_cache(varz["cache"])
        new_keys, emit, n_emit = spec_verify_rows(
            logits, chunk, keys, temps, topks, widths, sampling)
        return pool, emit, n_emit, new_keys

    def gather_blocks(self, pool, idxs):
        """Export seam (ISSUE 15): a whole block CHAIN's K/V(/scale)
        content — ``idxs`` is ``[n]`` pool block ids in table order,
        each leaf comes back ``[n, block_size, ...]`` (the exact array
        the kv-transfer plane ships).  ONE program call per export;
        the chain length is part of the compiled shape, so the program
        set is bounded by ``ceil(max_seq_len / block_size)``, never by
        traffic."""
        def g(node):
            return {k: v[idxs] for k, v in node.items()}

        return map_cache(pool, g)

    def graft_blocks(self, pool, values, dsts):
        """Import seam (ISSUE 15): write a migrated chain (``values`` —
        the :meth:`gather_blocks` pytree, host numpy off the wire) into
        the freshly-allocated LOCAL blocks ``dsts`` (``[n]`` int32) in
        one scatter.  Donor safety is by construction: every ``dsts``
        entry came off the free list at refcount 1, so a graft can
        never touch a block a live slot or the tree shares (the CoW
        invariant the tests bit-check).  One program call per seat —
        the decode tier's engine loop pays a single dispatch per
        migration, not one per block."""
        def rec(p, v):
            if _is_cache_node(p):
                return {k: leaf.at[dsts].set(v[k].astype(leaf.dtype))
                        for k, leaf in p.items()}
            if isinstance(p, Mapping):
                return {k: rec(val, v[k]) for k, val in p.items()}
            return p

        return rec(pool, values)

    def cow(self, pool, src, dst):
        """Copy-on-write at the divergence block: duplicate block
        ``src`` into the private block ``dst``.  Only the shared prefix
        of the run is ever valid for the attaching row (validity is
        length-based); the divergent tail is overwritten by its own
        prefill before the row's length reaches it."""
        def cw(node):
            return {k: v.at[dst].set(v[src]) for k, v in node.items()}

        return map_cache(pool, cw)

    def prefill_paged(self, params, pool, table, chunk, positions):
        """One chunked decode-mode prefill call writing straight into
        the request's pool blocks through its table (the
        paged_attention seam).  Written length BEFORE this chunk = its
        first position (chunks land in order)."""
        cache = self.paged_cache(pool, table[None, :], positions[:, 0])
        logits, varz = self.model.apply(
            {"params": params, "cache": cache}, chunk,
            positions=positions, mode="decode", mutable=["cache"],
            mesh=self.apply_mesh)
        return self.pool_from_cache(varz["cache"]), logits[:, -1]

    def prefill_dense(self, params, cache, chunk, positions):
        """Dense-mode batch-1 row-cache prefill (scattered into the slot
        later by :meth:`scatter`)."""
        logits, varz = self.model.apply(
            {"params": params, "cache": cache}, chunk,
            positions=positions, mode="decode", mutable=["cache"])
        return varz["cache"], logits[:, -1]

    def dense_step(self, params, cache, toks, poss, keys, temps,
                   topks, sampling: bool):
        """One batched decode step over the dense per-slot rows
        (windowed fallback): same row-wise sampling (or all-greedy
        argmax fast path) as the paged step."""
        import jax.numpy as jnp

        from k8s_tpu.models.decode import sample_logits_rows

        logits, varz = self.model.apply(
            {"params": params, "cache": cache}, toks[:, None],
            positions=poss[:, None], mode="decode", mutable=["cache"])
        if sampling:
            new_keys, nxt = sample_logits_rows(logits[:, -1], keys,
                                               temps, topks)
        else:
            new_keys = keys
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return varz["cache"], nxt, new_keys

    @staticmethod
    def scatter(cache, row, idx):
        """Replace batch row ``idx`` of every cache leaf with the
        freshly prefilled batch-1 row (dense-mode slot join)."""
        import jax

        return jax.tree_util.tree_map(
            lambda full, r: full.at[idx].set(r[0]), cache, row)


class LocalPlacement:
    """Single-device placement: plain ``jax.jit`` on the default device
    — the engine's original compile path, program for program.  Every
    method is the identity where a mesh placement would shard,
    broadcast, or assemble."""

    is_mesh = False
    mesh = None

    def info(self) -> dict:
        """Mesh identity for stats()/healthz: a single-host engine is a
        1-process, tp=1 'mesh' so the fleet plane reads one schema."""
        return {"num_processes": 1, "mesh_shape": {}, "tp_degree": 1,
                "placement": "local"}

    def wrap(self, op: str, fn: Callable, *, donate_argnums=(),
             static_argnums=(), resident_argnums=()) -> Callable:
        """Compile ``fn`` for this placement.  ``op`` names the program
        for plan-bus replay (unused locally); ``resident_argnums`` marks
        device-resident state (unused locally — jit takes every argument
        by value either way)."""
        import jax

        del op, resident_argnums
        return jax.jit(fn, donate_argnums=donate_argnums,
                       static_argnums=static_argnums)

    def globalize_params(self, params):
        """Params as the step programs consume them (sharded on a mesh;
        untouched locally)."""
        return params

    def build_pool(self, manifest):
        """The zero KV pool from its shape manifest (head-sharded
        shard-by-shard on a mesh; plain device zeros locally)."""
        import jax
        import jax.numpy as jnp

        return jax.tree.map(lambda leaf: jnp.zeros(leaf.shape, leaf.dtype),
                            manifest)

    def put_tables(self, stack):
        """Upload the [slots, max_blocks] block-table stack (broadcast
        to every process on a mesh)."""
        import jax.numpy as jnp

        return jnp.asarray(stack)

    def close(self) -> None:
        pass

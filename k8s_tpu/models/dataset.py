"""Token-shard dataset: checksummed binary shards streamed into training.

The reference ships no loader at all — its workloads read data inside the
user container (dist_mnist via tf input_data,
test/e2e/dist-mnist/dist_mnist.py:120-138).  The TPU rebuild's flagship LM
needs a real token path, not synthetic draws (VERDICT r2 weak #4): this
module defines the on-disk format, a writer, and a streaming reader that
feeds models.data.PrefetchIterator.

Format: a directory of ``tokens-NNNNN.npy`` shards, each a 1-D packed token
stream (uint16 or int32), plus ``MANIFEST.json``::

    {"dtype": "uint16", "total_tokens": N, "vocab_size": V,
     "shards": [{"file": "tokens-00000.npy", "sha256": "...",
                 "n_tokens": n}, ...]}

Shards are memory-mapped (np.load mmap_mode="r"), so reading scales to
corpora far beyond host RAM; sha256 is verified per shard on open (a
corrupted shard fails loudly, not as silently-wrong training data).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Iterator, Optional, Sequence

import numpy as np

MANIFEST = "MANIFEST.json"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def encode_bytes(text: bytes | str) -> np.ndarray:
    """Byte-level tokenization: vocab 256, identity over raw bytes.  The
    zero-dependency tokenizer for tests/examples; real runs can write
    shards from any tokenizer's ids via write_token_shards."""
    if isinstance(text, str):
        text = text.encode("utf-8")
    return np.frombuffer(text, dtype=np.uint8).astype(np.uint16)


def decode_bytes(tokens: np.ndarray) -> str:
    return bytes(np.asarray(tokens, dtype=np.uint8)).decode(
        "utf-8", errors="replace")


def write_token_shards(
    out_dir: str,
    tokens: np.ndarray,
    *,
    shard_tokens: int = 1 << 20,
    vocab_size: Optional[int] = None,
) -> dict:
    """Split a packed 1-D token array into checksummed .npy shards +
    manifest.  Returns the manifest dict."""
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        raise ValueError(f"tokens must be a packed 1-D stream, got {tokens.shape}")
    if tokens.size == 0:
        raise ValueError("empty token stream")
    if tokens.min() < 0:
        # a -1 sentinel would wrap to 65535 under uint16 and checksum as
        # valid — reject up front rather than shipping corrupted shards
        raise ValueError(
            f"negative token ids (min {int(tokens.min())}); token shards "
            "store vocabulary indices — map padding/sentinel ids first")
    dtype = np.uint16 if tokens.max() < (1 << 16) else np.int32
    tokens = tokens.astype(dtype)
    os.makedirs(out_dir, exist_ok=True)
    shards = []
    for i, start in enumerate(range(0, len(tokens), shard_tokens)):
        chunk = tokens[start:start + shard_tokens]
        name = f"tokens-{i:05d}.npy"
        path = os.path.join(out_dir, name)
        np.save(path, chunk)
        shards.append({
            "file": name,
            "sha256": _sha256(path),
            "n_tokens": int(chunk.size),
        })
    manifest = {
        "dtype": np.dtype(dtype).name,
        "total_tokens": int(tokens.size),
        "vocab_size": int(vocab_size if vocab_size is not None
                          else int(tokens.max()) + 1),
        "shards": shards,
    }
    with open(os.path.join(out_dir, MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)
    return manifest


class TokenDataset:
    """Streaming reader over a token-shard directory.

    Shards are opened lazily as memory-maps; ``verify=True`` (default)
    checks each shard's sha256 against the manifest the first time that
    shard is opened — fail-loud before any of its tokens are consumed, but
    no full-corpus hashing stall at startup (a multi-hundred-GB corpus
    would otherwise re-scan every disk byte on every gang restart).
    """

    def __init__(self, data_dir: str, *, verify: bool = True):
        self.data_dir = data_dir
        self._verify = verify
        mpath = os.path.join(data_dir, MANIFEST)
        if not os.path.exists(mpath):
            raise FileNotFoundError(
                f"no {MANIFEST} in {data_dir} — not a token-shard directory "
                f"(write one with write_token_shards)")
        with open(mpath) as f:
            self.manifest = json.load(f)
        self.vocab_size = int(self.manifest.get("vocab_size", 0))
        self.total_tokens = int(self.manifest["total_tokens"])
        declared = sum(s["n_tokens"] for s in self.manifest["shards"])
        if declared != self.total_tokens:
            raise ValueError(
                f"manifest inconsistent: shards sum to {declared}, "
                f"total_tokens says {self.total_tokens}")
        self._sums = {s["file"]: s["sha256"] for s in self.manifest["shards"]}
        self._mmaps: dict[str, np.ndarray] = {}
        self._verified: set[str] = set()

    def _check_shard(self, name: str) -> str:
        """Verify one shard's checksum (once, shared by both readers);
        returns its path."""
        path = os.path.join(self.data_dir, name)
        if self._verify and name not in self._verified:
            got = _sha256(path)
            if got != self._sums[name]:
                raise ValueError(
                    f"checksum mismatch for {name}: manifest "
                    f"{self._sums[name][:12]}…, file {got[:12]}…")
            self._verified.add(name)
        return path

    def _shard(self, name: str) -> np.ndarray:
        if name not in self._mmaps:
            self._mmaps[name] = np.load(self._check_shard(name), mmap_mode="r")
        return self._mmaps[name]

    def num_sequences(self, seq_len: int) -> int:
        """Whole non-overlapping seq_len windows per epoch (windows never
        straddle a shard boundary — each shard is an independent stream)."""
        return sum(s["n_tokens"] // seq_len
                   for s in self.manifest["shards"])

    @staticmethod
    def _split_bounds(total: int, split: str, eval_fraction: float):
        """(base, size) of a split's window range within [0, total).

        The eval split is the LAST ceil-ish slice of the UNSHUFFLED global
        window order — a stable function of (corpus, seq_len,
        eval_fraction) only, so train/eval never overlap across runs,
        resumes, or reader implementations.  train is the complementary
        prefix, which keeps its shuffled index math identical to the
        no-split path (a permutation of [0, train_total)).
        """
        if split not in ("all", "train", "eval"):
            raise ValueError(f"unknown split {split!r} "
                             "(expected 'all', 'train' or 'eval')")
        if split == "all":
            if eval_fraction:
                raise ValueError(
                    "eval_fraction requires split='train' or 'eval' "
                    "(split='all' would silently leak the holdout into "
                    "training)")
            return 0, total
        if not 0.0 < eval_fraction < 1.0:
            raise ValueError(
                f"split={split!r} needs 0 < eval_fraction < 1, "
                f"got {eval_fraction}")
        n_eval = max(1, int(total * eval_fraction))
        if n_eval >= total:
            raise ValueError(
                f"eval_fraction {eval_fraction} leaves no training windows "
                f"(total {total})")
        return (total - n_eval, n_eval) if split == "eval" \
            else (0, total - n_eval)

    def num_split_sequences(self, seq_len: int, split: str = "all",
                            eval_fraction: float = 0.0) -> int:
        """Windows per epoch in a holdout split (see _split_bounds)."""
        return self._split_bounds(
            self.num_sequences(seq_len), split, eval_fraction)[1]

    def _window_index(self, seq_len: int):
        """(names, cum) for O(num_shards) global-window-index decoding."""
        counts = [s["n_tokens"] // seq_len for s in self.manifest["shards"]]
        names = [s["file"] for s in self.manifest["shards"]]
        cum = np.cumsum([0] + counts)  # cum[i] = first global index of shard i
        if int(cum[-1]) == 0:
            raise ValueError(
                f"seq_len {seq_len} longer than every shard "
                f"(max {max(s['n_tokens'] for s in self.manifest['shards'])})")
        return names, cum

    def sequences(
        self,
        seq_len: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        epochs: Optional[int] = None,
        reader: str = "auto",
        start_window: int = 0,
        split: str = "all",
        eval_fraction: float = 0.0,
    ) -> Iterator[np.ndarray]:
        """Yield [seq_len] int32 windows; shuffle permutes the global window
        order each epoch.

        Window bookkeeping is O(num_shards) (a global window index decodes
        to (shard, offset) through a cumulative count table; no per-window
        tuple list), and reads touch only the windows actually yielded.
        With ``shuffle=True`` each epoch still materializes one
        rng.permutation(num_windows) int64 array — O(num_windows) MEMORY
        (~800 MB at 100M windows).  For corpora past that scale, plug a
        block- or Feistel-style streaming shuffle in here; unshuffled
        streams stay O(num_shards) end to end.

        ``reader``: "mmap" reads through numpy memory maps (page faults
        hold the GIL); "native" streams windows through the C++ loader
        (k8s_tpu/native/dataloader.py — reads on C++ threads, GIL-free);
        "auto" picks native when the toolchain built it, else mmap.  Both
        yield identical streams.

        ``split``/``eval_fraction``: holdout evaluation — "eval" is the
        stable last slice of the unshuffled global window order, "train"
        the complementary prefix (see _split_bounds); shuffle/seed/
        start_window all operate WITHIN the chosen split.
        """
        if reader not in ("auto", "mmap", "native"):
            raise ValueError(f"unknown reader {reader!r}")
        if reader == "auto":
            from k8s_tpu.native import dataloader as native_dl

            reader = "native" if native_dl.available() else "mmap"
        if reader == "native":
            yield from self._sequences_native(seq_len, shuffle, seed, epochs,
                                              start_window, split,
                                              eval_fraction)
            return
        names, cum = self._window_index(seq_len)
        base, total = self._split_bounds(int(cum[-1]), split, eval_fraction)
        rng = np.random.default_rng(seed)
        epoch, offset = self._fast_forward(rng, total, start_window, shuffle)
        while epochs is None or epoch < epochs:
            order = rng.permutation(total) if shuffle else range(total)
            for i in order[offset:]:
                i = base + int(i)
                shard_i = int(np.searchsorted(cum, i, side="right")) - 1
                start = (i - int(cum[shard_i])) * seq_len
                yield np.asarray(
                    self._shard(names[shard_i])[start:start + seq_len],
                    dtype=np.int32)
            offset = 0
            epoch += 1

    @staticmethod
    def _fast_forward(rng, total: int, start_window: int, shuffle: bool):
        """Advance the stream position to ``start_window`` (flat index over
        the multi-epoch stream) without reading anything: whole skipped
        epochs burn one permutation draw each so shuffle determinism is
        preserved."""
        if start_window < 0:
            raise ValueError(f"start_window must be >= 0, got {start_window}")
        epoch, offset = divmod(start_window, total)
        if shuffle:
            for _ in range(epoch):
                rng.permutation(total)
        return epoch, offset

    def _sequences_native(self, seq_len: int, shuffle: bool, seed: int,
                          epochs: Optional[int],
                          start_window: int = 0, split: str = "all",
                          eval_fraction: float = 0.0
                          ) -> Iterator[np.ndarray]:
        """The C++-reader stream: same windows, same order as mmap.

        Checksums stay LAZY (matching the class docstring's no-startup-
        stall contract): a shard is hashed the first time one of its
        windows is submitted, not at registration.
        """
        from k8s_tpu.native.dataloader import NativeWindowReader

        names, cum = self._window_index(seq_len)
        base, total = self._split_bounds(int(cum[-1]), split, eval_fraction)
        dtype = np.dtype(self.manifest["dtype"])
        window_bytes = seq_len * dtype.itemsize
        paths = [os.path.join(self.data_dir, n) for n in names]
        # npy payload starts after the header: size - n_tokens * itemsize
        data_off = [
            os.path.getsize(p) - s["n_tokens"] * dtype.itemsize
            for p, s in zip(paths, self.manifest["shards"])
        ]
        rng = np.random.default_rng(seed)

        with NativeWindowReader(paths, window_bytes) as r:
            epoch, offset = self._fast_forward(rng, total, start_window,
                                               shuffle)
            while epochs is None or epoch < epochs:
                order = rng.permutation(total) if shuffle else range(total)

                def descriptors(offset=offset):
                    for i in order[offset:]:
                        i = base + int(i)
                        shard_i = int(np.searchsorted(cum, i, side="right")) - 1
                        self._check_shard(names[shard_i])  # lazy, once each
                        start = (i - int(cum[shard_i])) * seq_len
                        yield shard_i, data_off[shard_i] + start * dtype.itemsize

                for raw in r.stream(descriptors()):
                    yield np.frombuffer(raw, dtype=dtype).astype(np.int32)
                offset = 0
                epoch += 1

    def batches(
        self,
        batch_size: int,
        seq_len: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        epochs: Optional[int] = None,
        split: str = "all",
        eval_fraction: float = 0.0,
    ) -> "BatchStream":
        """(tokens, tokens) [B, L] pairs — the (inputs, targets) shape
        train.fit consumes for next-token prediction (lm_loss shifts
        internally).  Incomplete trailing batches are dropped.

        Returns a BatchStream: an iterator that additionally supports
        ``skip(n)`` BEFORE consumption — an index jump over the first n
        batches with no disk reads, which is how train.fit fast-forwards
        the stream on checkpoint resume.

        ``split``/``eval_fraction`` select the holdout partition (see
        sequences); batch accounting (skip bounds, the batch_size guard)
        is against the SPLIT's window count.
        """
        n = self.num_split_sequences(seq_len, split, eval_fraction)
        if n < batch_size:
            raise ValueError(
                f"dataset split {split!r} has {n} windows of "
                f"{seq_len}, need >= batch_size {batch_size}")
        return BatchStream(self, batch_size, seq_len, shuffle=shuffle,
                           seed=seed, epochs=epochs, split=split,
                           eval_fraction=eval_fraction)


class BatchStream:
    """Iterator over token batches with a pre-consumption ``skip(n)``.

    The skip advances the deterministic window order WITHOUT touching the
    shards (the permutation is recomputed per epoch from the seed), so
    resuming at step 100k costs index arithmetic, not 100k batch reads.
    """

    def __init__(self, ds: "TokenDataset", batch_size: int, seq_len: int,
                 *, shuffle: bool, seed: int, epochs: Optional[int],
                 split: str = "all", eval_fraction: float = 0.0):
        self._ds = ds
        self._batch_size = batch_size
        self._seq_len = seq_len
        self._shuffle = shuffle
        self._seed = seed
        self._epochs = epochs
        self._split = split
        self._eval_fraction = eval_fraction
        self._skip_windows = 0
        self._iter = None

    def skip(self, n_batches: int) -> None:
        if self._iter is not None:
            raise RuntimeError("skip() must be called before consumption")
        self._skip_windows += int(n_batches) * self._batch_size
        # Bounded streams validate the jump target eagerly: silently
        # skipping past the end would make iteration yield nothing and a
        # resumed fit() "complete" zero steps, while the drain fallback
        # raises for the same condition — the two paths must agree.
        if self._epochs is not None:
            total_windows = self._ds.num_split_sequences(
                self._seq_len, self._split, self._eval_fraction
            ) * self._epochs
            usable = (total_windows // self._batch_size) * self._batch_size
            # strictly greater: skipping EXACTLY to the end is the
            # completed-run resume (fit's documented no-op path), matching
            # the drain fallback, which only fails when a next() is missing
            if self._skip_windows > usable:
                raise ValueError(
                    f"skip({n_batches}) jumps past the stream: "
                    f"{usable // self._batch_size} batches available over "
                    f"{self._epochs} epoch(s)")

    def __iter__(self):
        return self

    def __next__(self) -> tuple[np.ndarray, np.ndarray]:
        if self._iter is None:
            self._iter = self._ds.sequences(
                self._seq_len, shuffle=self._shuffle, seed=self._seed,
                epochs=self._epochs, start_window=self._skip_windows,
                split=self._split, eval_fraction=self._eval_fraction)
        rows = []
        for seq in self._iter:
            rows.append(seq)
            if len(rows) == self._batch_size:
                break
        if len(rows) < self._batch_size:
            raise StopIteration
        batch = np.stack(rows)
        return batch, batch


def write_text_corpus(out_dir: str, texts: Sequence[str | bytes], *,
                      shard_tokens: int = 1 << 16) -> dict:
    """Byte-tokenize real text into a shard directory (the fixture builder
    for tests/examples; vocab is fixed at 256)."""
    stream = np.concatenate([encode_bytes(t) for t in texts])
    return write_token_shards(out_dir, stream, shard_tokens=shard_tokens,
                              vocab_size=256)

"""Per-request serving observability (ISSUE 12): the serving-side
analogue of the control plane's flight recorder.

Two bounded instruments behind one process-global recorder:

- a **request lifecycle recorder** — one timeline per generation
  request, from submit through shed/admission, prefill chunks (with the
  prefix-reuse outcome: hit / copy-on-write / miss, blocks attached,
  tokens saved), every decode step the slot participated in, spec
  propose/accept counts per verify chunk, block-pool evictions that
  touched the request, and the retire reason.  A finished timeline
  closes with a computed **dominant-phase attribution** — the phase
  (``queue`` / ``prefill`` / ``decode`` / ``spec_reject`` / ``compile``
  / ``evict``) that owned the largest share of the request's wall time
  — so "why was this request slow" is a lookup, not an investigation;
- an **engine step ledger** — one record per batched program call
  (occupancy, fused width, speculative group, tokens emitted, step wall
  time) in a bounded ring with windowed rollups (mean occupancy,
  tokens/s, step p50/p99).

Activation mirrors ``trace``/``flight``/``fleet``/``compileledger``:
``K8S_TPU_REQUEST_LOG=1`` plus the :func:`set_active`/:func:`active`
process-global registry; a zero-overhead no-op when unset (the engine
binds ``maybe_active()`` at construction and guards every call site on
``is None``).  ``K8S_TPU_REQUEST_LOG_RING`` bounds the finished-request
ring (default 512, oldest-finished evicted — a traffic storm can never
grow the recorder past a fixed footprint).

Served at ``/debug/requests`` (``?id=`` one full timeline with events,
``?slow=`` seconds filter, ``?phase=`` dominant-phase filter, ``?n=``
limit) and ``/debug/engine`` (``?n=`` recent step records + rollups) on
the metrics server, the dashboard backend, AND the serving pod's HTTP
server — the shared-responder / 404-when-inactive pattern every other
``/debug`` route follows.

This module is deliberately stdlib-only (the metrics server and
dashboard — operator processes — import it for the debug routes; pulling
jax through a debug endpoint would be absurd) and its lock is a leaf:
the recorder never calls back into the engine, so it can be invoked from
any engine code path without extending the lock order.
"""

from __future__ import annotations

import json
import os
import time
from collections import OrderedDict, deque
from typing import Optional
from urllib.parse import parse_qs

from k8s_tpu.analysis import checkedlock
from k8s_tpu.util.util import quantile_nearest as _quantile

ENV_ENABLE = "K8S_TPU_REQUEST_LOG"
ENV_RING = "K8S_TPU_REQUEST_LOG_RING"

DEFAULT_MAX_REQUESTS = 512
DEFAULT_MAX_STEPS = 2048
DEFAULT_MAX_EVENTS_PER_REQUEST = 256

#: canonical phase order — also the tie-break order for the dominant-
#: phase attribution (earlier wins on equal seconds, so an all-zero
#: timeline attributes to "queue", the only phase every request has).
#: ``migrate`` (ISSUE 15) is the disaggregated cross-pod hop: block
#: transfer on the prefill side, graft-and-seat on the decode side.
#: ``spill``/``promote`` (ISSUE 17) are the host-RAM KV tier: demoting
#: evicted tree leaves to host buffers on this request's behalf, and
#: re-grafting spilled chain blocks back into the pool at attach time.
#: The spill span rides INSIDE the evict walk's wall span (the demote
#: happens mid-eviction), so those two phases deliberately overlap —
#: attribution names the tier, it does not partition wall time.
PHASES = ("queue", "prefill", "migrate", "decode", "spec_reject",
          "compile", "evict", "spill", "promote")


def _dominant(phase_s: dict) -> str:
    """Argmax phase with the canonical-order tie-break (earlier wins:
    an all-zero timeline attributes to "queue")."""
    return max(PHASES, key=lambda p: (phase_s[p], -PHASES.index(p)))


def enabled_from_env() -> bool:
    """K8S_TPU_REQUEST_LOG: truthy activates the recorder (default off
    — the zero-overhead compatibility default)."""
    return os.environ.get(ENV_ENABLE, "").lower() in ("1", "true", "on",
                                                      "yes")


def ring_from_env() -> int:
    """K8S_TPU_REQUEST_LOG_RING: finished-timeline ring bound (positive
    int; garbage and non-positive fall back to the default)."""
    try:
        n = int(os.environ.get(ENV_RING, ""))
    except ValueError:
        return DEFAULT_MAX_REQUESTS
    return n if n > 0 else DEFAULT_MAX_REQUESTS




class RequestRecorder:
    """Thread-safe bounded recorder of per-request serving timelines
    plus the engine step ledger.  Writers are the engine thread and the
    HTTP handler threads (submit/shed); readers are debug endpoints and
    bench rollups.  Methods never raise into the serving hot path."""

    def __init__(self, max_requests: Optional[int] = None,
                 max_steps: int = DEFAULT_MAX_STEPS,
                 max_events_per_request: int =
                 DEFAULT_MAX_EVENTS_PER_REQUEST):
        if max_requests is None:
            max_requests = ring_from_env()
        if max_requests < 1 or max_steps < 1 \
                or max_events_per_request < 1:
            raise ValueError("recorder bounds must be >= 1")
        self.max_requests = max_requests
        self.max_events_per_request = max_events_per_request
        self._lock = checkedlock.make_lock("requestlog.recorder")
        self._next_id = 1
        self._live: dict[int, dict] = {}
        # finished timelines, oldest-finished evicted at max_requests
        self._done: "OrderedDict[int, dict]" = OrderedDict()
        self._evicted = 0
        self._shed_total = 0
        self._finished_total = 0
        # engine step ledger: bounded ring of per-program-call records
        self._steps: deque[dict] = deque(maxlen=max_steps)
        self._steps_total = 0
        self._tokens_total = 0
        self.created_at = time.time()

    # -- writers (engine / server) ------------------------------------

    def begin(self, prompt_len: Optional[int], max_new: int, *,
              temperature: float = 0.0, top_k: Optional[int] = None,
              speculative: int = 0, kind: str = "batched",
              trace_id: Optional[str] = None) -> int:
        """Open a timeline at submit time; returns the request id the
        engine threads through every later call."""
        entry = {
            "state": "live",
            "kind": kind,
            "wall_submit": round(time.time(), 3),
            "t_submit": time.monotonic(),
            "prompt_len": prompt_len,
            "max_new": max_new,
            "temperature": temperature,
            "top_k": top_k,
            "speculative": speculative,
            "trace_id": trace_id,
            "events": [],
            "events_dropped": 0,
            "phase_s": {p: 0.0 for p in PHASES},
            "queue_wait_s": None,
            "ttft_s": None,
            "tpot_s": None,
            "e2e_s": None,
            "tokens": 0,
            "steps": 0,
            "prefix": None,
            "spec": {"chunks": 0, "proposed": 0, "accepted": 0},
            # the prefill→decode hop (ISSUE 15): direction/blocks/peer,
            # None for requests that never migrated
            "migrate": None,
            "evictions": 0,
            # tiered KV hierarchy (ISSUE 17): blocks this request's
            # allocations demoted to the host spill tier, and spilled
            # blocks promoted back to the pool for its prefix attach
            "spilled": 0,
            "promoted": 0,
            "slot": None,
            "retire": None,
            "dominant_phase": None,
        }
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            entry["id"] = rid
            self._live[rid] = entry
        return rid

    def _event(self, entry: dict, kind: str, **attrs) -> None:
        # caller holds self._lock
        if len(entry["events"]) >= self.max_events_per_request:
            entry["events_dropped"] += 1
            return
        evt = {"t": round(time.monotonic() - entry["t_submit"], 6),
               "kind": kind}
        if attrs:
            evt.update(attrs)
        entry["events"].append(evt)

    def _phase(self, entry: dict, phase: str, seconds: float) -> None:
        entry["phase_s"][phase] += max(0.0, seconds)

    def shed(self, rid: Optional[int], depth: int, limit: int) -> None:
        """Admission-queue rejection: the timeline finishes immediately
        with retire reason ``shed`` and dominant phase ``queue``."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.pop(rid, None)
            if entry is None:
                return
            self._event(entry, "shed", depth=depth, limit=limit)
            self._shed_total += 1
            self._finish_locked(entry, "shed")

    def admitted(self, rid: Optional[int], slot: int,
                 queue_wait_s: float) -> None:
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            entry["slot"] = slot
            entry["queue_wait_s"] = round(queue_wait_s, 6)
            self._phase(entry, "queue", queue_wait_s)
            self._event(entry, "admitted", slot=slot,
                        queue_wait_s=round(queue_wait_s, 6))

    def prefix_outcome(self, rid: Optional[int], outcome: str,
                       blocks: int, tokens_saved: int) -> None:
        """The radix-tree result for this request's prompt: ``hit``
        (whole blocks attached by reference), ``cow`` (divergence block
        copy-on-written), or ``miss``."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            entry["prefix"] = {"outcome": outcome, "blocks": blocks,
                               "tokens_saved": tokens_saved}
            self._event(entry, "prefix", outcome=outcome, blocks=blocks,
                        tokens_saved=tokens_saved)

    def prefill_chunk(self, rid: Optional[int], bucket: int,
                      dur_s: float, compiled: bool) -> None:
        """One chunked-prefill dispatch.  A chunk that compiled a fresh
        bucket program bills its wall time to ``compile``, not
        ``prefill`` — a compile stall mid-admission is its own phase."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            self._phase(entry, "compile" if compiled else "prefill",
                        dur_s)
            self._event(entry, "prefill_chunk", bucket=bucket,
                        dur_s=round(dur_s, 6), compiled=compiled)

    def prefill_done(self, rid: Optional[int], total_s: float,
                     ttft_s: float) -> None:
        """Close the prefill span: any wall time the per-chunk dispatch
        records did not cover (device execution forced by the first-
        token sync) lands in ``prefill``."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            covered = sum(e.get("dur_s", 0.0) for e in entry["events"]
                          if e["kind"] == "prefill_chunk")
            self._phase(entry, "prefill", total_s - covered)
            entry["ttft_s"] = round(ttft_s, 6)
            self._event(entry, "first_token",
                        ttft_s=round(ttft_s, 6))

    def convoy(self, rid: Optional[int], dur_s: float) -> None:
        """This request's decode-ready slot stalled behind ANOTHER
        request's prefill (the prefill convoy): the stall bills to the
        victim's ``prefill`` phase."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            self._phase(entry, "prefill", dur_s)
            self._event(entry, "convoy", dur_s=round(dur_s, 6))

    def step(self, rid: Optional[int], seq: int, width: int,
             emitted: int, dur_s: float, *, compiled: bool = False,
             spec: bool = False, proposed: int = 0,
             accepted: int = 0) -> None:
        """One decode step this request's slot participated in.  Spec
        verify steps split their wall time between ``decode`` (accepted
        share) and ``spec_reject`` (rejected-draft share); a step that
        compiled a fresh program bills to ``compile`` instead."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            entry["steps"] += 1
            entry["tokens"] += emitted
            if compiled:
                self._phase(entry, "compile", dur_s)
            elif spec and width > 0:
                reject_frac = max(0.0, (width - emitted) / width)
                self._phase(entry, "spec_reject", dur_s * reject_frac)
                self._phase(entry, "decode", dur_s * (1 - reject_frac))
            else:
                self._phase(entry, "decode", dur_s)
            if spec:
                entry["spec"]["chunks"] += 1
                entry["spec"]["proposed"] += proposed
                entry["spec"]["accepted"] += accepted
            self._event(entry, "spec_chunk" if spec else "step",
                        seq=seq, width=width, emitted=emitted,
                        dur_s=round(dur_s, 6),
                        **({"proposed": proposed, "accepted": accepted}
                           if spec else {}))

    def migrated(self, rid: Optional[int], blocks: int, dur_s: float,
                 peer: Optional[str] = None) -> None:
        """Decode-side half of the prefill→decode hop (ISSUE 15): the
        imported chain was grafted into the local pool and the request
        seated — the graft wall time bills to the ``migrate`` phase."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            self._phase(entry, "migrate", dur_s)
            entry["migrate"] = {"direction": "in", "blocks": blocks,
                                "peer": peer}
            self._event(entry, "migrate_in", blocks=blocks,
                        dur_s=round(dur_s, 6),
                        **({"peer": peer} if peer else {}))

    def migrate_send(self, rid: Optional[int], blocks: int,
                     dur_s: float, dest: Optional[str] = None) -> None:
        """Prefill-side half of the hop: the block chain was shipped and
        the decode pod acked the seat — transfer wall time bills to
        ``migrate`` (the HTTP layer closes the timeline with retire
        reason ``migrated`` right after)."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            self._phase(entry, "migrate", dur_s)
            entry["migrate"] = {"direction": "out", "blocks": blocks,
                                "peer": dest}
            self._event(entry, "migrate_out", blocks=blocks,
                        dur_s=round(dur_s, 6),
                        **({"dest": dest} if dest else {}))

    def evicted(self, rid: Optional[int], blocks: int,
                dur_s: float) -> None:
        """Block-pool allocation for this request had to evict prefix-
        tree leaves (the pool ran dry on its behalf)."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            entry["evictions"] += blocks
            self._phase(entry, "evict", dur_s)
            self._event(entry, "evict", blocks=blocks,
                        dur_s=round(dur_s, 6))

    def spilled(self, rid: Optional[int], blocks: int,
                dur_s: float) -> None:
        """Block-pool allocation for this request demoted evicted tree
        leaves to the host spill tier (ISSUE 17) instead of dropping
        them.  The span rides inside the evict walk's wall time — see
        the PHASES note on the deliberate overlap."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            entry["spilled"] += blocks
            self._phase(entry, "spill", dur_s)
            self._event(entry, "spill", blocks=blocks,
                        dur_s=round(dur_s, 6))

    def promoted(self, rid: Optional[int], blocks: int,
                 dur_s: float) -> None:
        """Spilled chain blocks were re-grafted into the pool so this
        request's prompt attaches them as a tree hit (ISSUE 17) — the
        gather/dequantize/graft wall time bills to ``promote``."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.get(rid)
            if entry is None:
                return
            entry["promoted"] += blocks
            self._phase(entry, "promote", dur_s)
            self._event(entry, "promote", blocks=blocks,
                        dur_s=round(dur_s, 6))

    def retire(self, rid: Optional[int], reason: str,
               tokens: Optional[int] = None,
               ttft_s: Optional[float] = None) -> None:
        """Close the timeline (idempotent — a second retire of the same
        id is a no-op): stamps e2e, derives TPOT, computes the dominant
        phase, and moves the entry to the finished ring."""
        if rid is None:
            return
        with self._lock:
            entry = self._live.pop(rid, None)
            if entry is None:
                return
            if tokens is not None:
                entry["tokens"] = tokens
            if ttft_s is not None and entry["ttft_s"] is None:
                entry["ttft_s"] = round(ttft_s, 6)
            self._event(entry, "retire", reason=reason)
            self._finish_locked(entry, reason)

    def _finish_locked(self, entry: dict, reason: str) -> None:
        e2e = time.monotonic() - entry["t_submit"]
        entry["e2e_s"] = round(e2e, 6)
        entry["retire"] = reason
        entry["state"] = "done"
        if entry["ttft_s"] is not None and entry["tokens"] \
                and entry["tokens"] > 1:
            entry["tpot_s"] = round(
                (e2e - entry["ttft_s"]) / (entry["tokens"] - 1), 6)
        entry["phase_s"] = {p: round(s, 6)
                            for p, s in entry["phase_s"].items()}
        entry["dominant_phase"] = _dominant(entry["phase_s"])
        self._finished_total += 1
        self._done[entry["id"]] = entry
        while len(self._done) > self.max_requests:
            self._done.popitem(last=False)
            self._evicted += 1

    def engine_step(self, seq: int, active: int, width: int,
                    spec_group: int, tokens: int, dur_s: float) -> None:
        """One batched program call into the step ledger ring."""
        with self._lock:
            self._steps_total += 1
            self._tokens_total += tokens
            self._steps.append({
                "seq": seq, "active": active, "width": width,
                "spec_group": spec_group, "tokens": tokens,
                "dur_s": round(dur_s, 6),
                "t": round(time.monotonic(), 3),
            })

    def clear(self) -> None:
        """Drop all data (bench warmup boundary); live ids stay valid —
        their in-flight entries are simply forgotten."""
        with self._lock:
            self._live.clear()
            self._done.clear()
            self._steps.clear()
            self._evicted = 0
            self._shed_total = 0
            self._finished_total = 0
            self._steps_total = 0
            self._tokens_total = 0

    # -- readers ------------------------------------------------------

    def request(self, rid: int) -> Optional[dict]:
        """One full timeline (events included), live or finished.  The
        copy is plain dict/list cloning, NOT a json round-trip: this
        lock is the one the decode loop contends on, and a debug poll
        must not stall in-flight steps for a serialization pass."""
        with self._lock:
            entry = self._live.get(rid) or self._done.get(rid)
            if entry is None:
                return None
            out = {k: (dict(v) if isinstance(v, dict) else v)
                   for k, v in entry.items() if k != "events"}
            out["events"] = [dict(e) for e in entry["events"]]
        return out

    @staticmethod
    def _summary(entry: dict, now: Optional[float] = None) -> dict:
        out = {k: entry[k] for k in (
            "id", "state", "kind", "wall_submit", "prompt_len",
            "max_new", "speculative", "trace_id", "queue_wait_s",
            "ttft_s", "tpot_s", "e2e_s", "tokens", "steps", "prefix",
            "spec", "migrate", "evictions", "spilled", "promoted",
            "slot", "retire", "dominant_phase")}
        out["phase_s"] = dict(entry["phase_s"])
        if out["dominant_phase"] is None:
            # provisional attribution for LIVE entries, so
            # ?slow=&phase= surfaces a currently-stuck request instead
            # of hiding it until it finishes: argmax over the phases
            # accrued so far; a still-queued entry (nothing accrued)
            # lands on "queue" via the tie-break — all its elapsed time
            # IS queue wait
            out["dominant_phase"] = _dominant(entry["phase_s"])
        # elapsed so far: e2e for finished entries, time-since-submit
        # for live ones — what ?slow= filters on, so a request STUCK in
        # the queue or a wedged slot for 30s is visible, not hidden
        # behind its unset e2e
        out["elapsed_s"] = entry["e2e_s"] if entry["e2e_s"] is not None \
            else round((now if now is not None else time.monotonic())
                       - entry["t_submit"], 6)
        return out

    def snapshot(self, slow_s: Optional[float] = None,
                 phase: Optional[str] = None,
                 limit: Optional[int] = None) -> list[dict]:
        """Finished-timeline summaries, most recent last, plus live
        entries at the tail; ``slow_s`` keeps elapsed (e2e, or
        time-since-submit for live entries) >= the bound, ``phase``
        keeps one dominant phase, ``limit`` the most recent N."""
        now = time.monotonic()
        with self._lock:
            entries = [self._summary(e, now)
                       for e in self._done.values()]
            entries += [self._summary(e, now)
                        for e in self._live.values()]
        if slow_s is not None:
            entries = [e for e in entries if e["elapsed_s"] >= slow_s]
        if phase is not None:
            entries = [e for e in entries
                       if e["dominant_phase"] == phase]
        if limit is not None and limit >= 0:
            entries = entries[-limit:] if limit else []
        return entries

    def stats(self) -> dict:
        with self._lock:
            by_phase: dict[str, int] = {}
            for e in self._done.values():
                p = e["dominant_phase"]
                by_phase[p] = by_phase.get(p, 0) + 1
            return {
                "live": len(self._live),
                "finished": len(self._done),
                "finished_total": self._finished_total,
                "shed_total": self._shed_total,
                "evicted_timelines": self._evicted,
                "max_requests": self.max_requests,
                "dominant_phases": by_phase,
                "ledger_steps": len(self._steps),
                "ledger_steps_total": self._steps_total,
                "ledger_tokens_total": self._tokens_total,
            }

    def percentiles(self) -> dict:
        """TTFT / TPOT / queue-wait / e2e p50+p99 over the finished
        ring — what the bench artifact embeds per phase."""
        with self._lock:
            done = list(self._done.values())
        out = {"requests": len(done)}
        for field in ("ttft_s", "tpot_s", "queue_wait_s", "e2e_s"):
            vals = sorted(e[field] for e in done
                          if e[field] is not None)
            key = field[:-2]  # strip the _s suffix
            out[f"{key}_p50_s"] = round(_quantile(vals, 0.50), 6)
            out[f"{key}_p99_s"] = round(_quantile(vals, 0.99), 6)
        return out

    def engine_rollup(self, window: int = 128) -> dict:
        """Windowed step-ledger rollup: occupancy, tokens/s, and step
        wall-time quantiles over the most recent ``window`` records."""
        with self._lock:
            recent = list(self._steps)[-window:] if window else []
            total = {"steps_total": self._steps_total,
                     "tokens_total": self._tokens_total}
        out = {"window": len(recent), **total}
        if not recent:
            out.update({"mean_occupancy": 0.0, "tokens_per_s": 0.0,
                        "step_p50_s": 0.0, "step_p99_s": 0.0,
                        "spec_steps": 0})
            return out
        durs = sorted(r["dur_s"] for r in recent)
        wall = sum(durs)
        out["mean_occupancy"] = round(
            sum(r["active"] for r in recent) / len(recent), 3)
        out["tokens_per_s"] = round(
            sum(r["tokens"] for r in recent) / wall, 1) if wall else 0.0
        out["step_p50_s"] = round(_quantile(durs, 0.50), 6)
        out["step_p99_s"] = round(_quantile(durs, 0.99), 6)
        out["spec_steps"] = sum(1 for r in recent if r["spec_group"])
        return out

    def engine_steps(self, limit: int = 64) -> list[dict]:
        with self._lock:
            recent = list(self._steps)
        if limit >= 0:
            recent = recent[-limit:] if limit else []
        return [dict(r) for r in recent]

    def audit_payload(self, slowest: int = 8) -> dict:
        """The requests_audit.json shape: recorder stats, the phase
        percentiles, the engine rollup, and the slowest finished
        timelines (summaries) with their dominant phases."""
        with self._lock:
            done = [self._summary(e) for e in self._done.values()]
        done.sort(key=lambda e: e["e2e_s"] or 0.0, reverse=True)
        return {
            "stats": self.stats(),
            "percentiles": self.percentiles(),
            "engine": self.engine_rollup(),
            "slowest": done[:slowest],
        }


# -- process-global active recorder (trace.TRACER / fleet pattern) ------------

_ACTIVE: Optional[RequestRecorder] = None


def set_active(recorder: Optional[RequestRecorder]) -> None:
    global _ACTIVE
    _ACTIVE = recorder


def active() -> Optional[RequestRecorder]:
    return _ACTIVE


def maybe_active() -> Optional[RequestRecorder]:
    """The active recorder, auto-created on first use when
    ``K8S_TPU_REQUEST_LOG`` is set — the activation seam the engine
    calls at construction (mirroring ``compileledger.maybe_active``)."""
    global _ACTIVE
    if _ACTIVE is None and enabled_from_env():
        _ACTIVE = RequestRecorder()
    return _ACTIVE


# -- /debug/requests and /debug/engine ----------------------------------------

_INACTIVE_BODY = ("request recorder inactive (set K8S_TPU_REQUEST_LOG=1 "
                  "so the serving engine records per-request "
                  "timelines)\n")


def debug_requests_response(query: str = "") -> tuple[int, str, str]:
    """(status, body, content-type) for GET /debug/requests — the ONE
    responder the metrics server, the dashboard backend, and the
    serving pod all route to (404 with an explicit body while no
    recorder is active, like every other /debug route)."""
    rec = _ACTIVE
    if rec is None:
        return 404, _INACTIVE_BODY, "text/plain"
    params = parse_qs(query or "")

    def _num(key, cast):
        raw = (params.get(key) or [None])[0]
        if raw is None:
            return None
        try:
            return cast(raw)
        except ValueError:
            return None

    rid = _num("id", int)
    if rid is not None:
        entry = rec.request(rid)
        if entry is None:
            return (404, f"no request timeline with id {rid}\n",
                    "text/plain")
        body = json.dumps({"request": entry}, indent=2)
        return 200, body + "\n", "application/json"
    slow = _num("slow", float)
    phase = (params.get("phase") or [None])[0]
    if phase is not None and phase not in PHASES:
        return (400, f"unknown phase {phase!r} (expected one of "
                f"{list(PHASES)})\n", "text/plain")
    limit = _num("n", int)
    payload = {
        "stats": rec.stats(),
        "percentiles": rec.percentiles(),
        "requests": rec.snapshot(slow_s=slow, phase=phase,
                                 limit=50 if limit is None else limit),
    }
    return 200, json.dumps(payload, indent=2) + "\n", "application/json"


def debug_engine_response(query: str = "") -> tuple[int, str, str]:
    """(status, body, content-type) for GET /debug/engine: the step
    ledger's recent records plus windowed rollups (404 with an explicit
    body while no recorder is active)."""
    rec = _ACTIVE
    if rec is None:
        return 404, _INACTIVE_BODY, "text/plain"
    params = parse_qs(query or "")
    raw_n = (params.get("n") or [None])[0]
    try:
        limit = int(raw_n) if raw_n is not None else 64
    except ValueError:
        limit = 64
    payload = {
        "rollup": rec.engine_rollup(),
        "rollup_recent": rec.engine_rollup(window=32),
        "steps": rec.engine_steps(limit=limit),
    }
    return 200, json.dumps(payload, indent=2) + "\n", "application/json"

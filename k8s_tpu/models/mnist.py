"""MNIST conv net — the dist-mnist equivalent (reference:
test/e2e/dist-mnist/dist_mnist.py, between-graph PS/worker training).

The reference trained this over 2 PS + 4 workers with asynchronous gradient
pushes; here it is a synchronous SPMD data-parallel step over the mesh — the
"sync_replicas" mode (dist_mnist.py:70-74) made default, the PS deleted.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MnistCNN(nn.Module):
    @nn.compact
    def __call__(self, x):
        # x: [B, 28, 28, 1]
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def synthetic_batch(key, batch_size: int = 64):
    """Deterministic synthetic data for smoke/e2e runs without a dataset."""
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (batch_size, 28, 28, 1), jnp.float32)
    y = jax.random.randint(ky, (batch_size,), 0, 10)
    return x, y

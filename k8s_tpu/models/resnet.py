"""ResNet-50 in Flax — the flagship benchmark workload (BASELINE.md:
"ResNet-50 images/sec/chip" on a v5e slice; manifest examples/tf_job_tpu.yaml).

TPU-first choices:
- bfloat16 activations/compute with float32 params and batch-norm statistics
  (MXU-native mixed precision);
- NHWC layout (XLA TPU's native conv layout);
- no data-dependent control flow — the whole step jits to one XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = self.conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=[(1, 1), (1, 1)], use_bias=False, name="conv2",
        )(y)
        y = self.norm(name="bn2")(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False, name="conv3")(y)
        # zero-init the last BN scale: identity residual at init (standard
        # ResNet-v1.5 trick, keeps early training stable at large batch)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)

        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, name="conv_proj",
            )(residual)
            residual = self.norm(name="bn_proj")(residual)

        return nn.relu(residual + y)


def space_to_depth(x, block: int = 2):
    """NHWC space-to-depth: (B, H, W, C) -> (B, H/b, W/b, b*b*C).

    Channel packing order is (row_offset, col_offset, channel) — the order
    ``stem_weights_to_s2d`` assumes when transforming 7x7 stem weights.
    """
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def stem_weights_to_s2d(w7):
    """Map 7x7-stride-2 stem weights [7,7,C,O] to the equivalent
    4x4-stride-1 weights [4,4,4C,O] over a 2x2 space-to-depth input.

    The 7x7 kernel is zero-padded to 8x8 at the top-left (tap k of the
    original covers input row 2i-3+k; block di holds rows 2i-4+2di and
    2i-3+2di, so tap (di, r) of the block kernel is original tap 2di+r-1,
    with (di=0, r=0) falling off the kernel — the zero row/col).
    """
    import numpy as np

    k, k2, c, o = w7.shape
    assert (k, k2) == (7, 7), "stem transform is specific to the 7x7 stem"
    p = np.zeros((8, 8, c, o), dtype=np.asarray(w7).dtype)
    p[1:, 1:] = np.asarray(w7)
    # [8,8,C,O] -> [4, 2(row off), 4, 2(col off), C, O] -> [4,4,2,2,C,O]
    p = p.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
    return p.reshape(4, 4, 4 * c, o)


class ResNet(nn.Module):
    """ResNet-v1.5 family; stage_sizes (3,4,6,3) is ResNet-50.

    ``stem="s2d"`` uses the space-to-depth stem: mathematically the same
    function class as the 7x7/s2 conv (see ``stem_weights_to_s2d``), but the
    conv the MXU actually runs is 4x4/s1 over 12 input channels instead of
    7x7/s2 over 3 — no stride decimation, 4x the input-channel depth
    (the standard TPU ResNet trick from the MLPerf submissions).
    """

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    stem: str = "conv"  # "conv" (7x7/s2) | "s2d" (space-to-depth 4x4/s1)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype, param_dtype=jnp.float32)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=None,
        )

        x = x.astype(self.dtype)
        if self.stem == "s2d":
            x = space_to_depth(x, 2)
            x = conv(
                self.num_filters, (4, 4), strides=(1, 1),
                padding=[(2, 1), (2, 1)], use_bias=False, name="conv_init",
            )(x)
        else:
            x = conv(
                self.num_filters, (7, 7), strides=(2, 2),
                padding=[(3, 3), (3, 3)], use_bias=False, name="conv_init",
            )(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    name=f"stage{i}_block{j}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="head"
        )(x)
        return x


def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16, stem: str = "conv") -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3), num_classes=num_classes, dtype=dtype, stem=stem
    )


def resnet18_thin(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    """Small variant for CPU tests."""
    return ResNet(
        stage_sizes=(1, 1), num_classes=num_classes, num_filters=8, dtype=dtype
    )

"""ResNet-50 in Flax — the flagship benchmark workload (BASELINE.md:
"ResNet-50 images/sec/chip" on a v5e slice; manifest examples/tf_job_tpu.yaml).

TPU-first choices:
- bfloat16 activations/compute with float32 params and batch-norm statistics
  (MXU-native mixed precision);
- NHWC layout (XLA TPU's native conv layout);
- no data-dependent control flow — the whole step jits to one XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = self.conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=[(1, 1), (1, 1)], use_bias=False, name="conv2",
        )(y)
        y = self.norm(name="bn2")(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False, name="conv3")(y)
        # zero-init the last BN scale: identity residual at init (standard
        # ResNet-v1.5 trick, keeps early training stable at large batch)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)

        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, name="conv_proj",
            )(residual)
            residual = self.norm(name="bn_proj")(residual)

        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet-v1.5 family; stage_sizes (3,4,6,3) is ResNet-50."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype, param_dtype=jnp.float32)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=None,
        )

        x = x.astype(self.dtype)
        x = conv(
            self.num_filters, (7, 7), strides=(2, 2),
            padding=[(3, 3), (3, 3)], use_bias=False, name="conv_init",
        )(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    name=f"stage{i}_block{j}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32, name="head"
        )(x)
        return x


def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, dtype=dtype)


def resnet18_thin(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    """Small variant for CPU tests."""
    return ResNet(
        stage_sizes=(1, 1), num_classes=num_classes, num_filters=8, dtype=dtype
    )

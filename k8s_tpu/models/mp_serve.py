"""Multi-process serving gang: entrypoint + local gang driver (ISSUE 14).

Every process of a multi-host serving TFJob runs THIS module (the
serving analogue of e2e.rendezvous_worker):

1. reads the operator-injected env contract VERBATIM through
   ``launcher.bootstrap`` and brings up ``jax.distributed`` — a serving
   gang rendezvouses exactly like a training gang;
2. builds the identical model (same artifact / same seed init) on every
   process;
3. process 0 (the chief) constructs the engine over a
   ``MeshPlacement`` — params tensor-sharded, KV pool head-sharded, the
   per-step batch plan broadcast over the plan bus — and serves either
   a fixed request script (bench / token-identity proof) or the real
   HTTP server (models/server.py --mesh path);
4. every other process runs ``mesh_serve.follower_loop``: replay the
   plan, exit 0 on the chief's bye, exit NONZERO when the plan stream
   dies — the operator's whole-gang restart policy applies to serving
   gangs unchanged (a half-dead gang can only hang inside a
   collective).

``run_serve_gang`` is the CPU-provable local driver (the
e2e/multiprocess.py supervision pattern): N real OS processes, one
virtual CPU device each, operator-generated env with only the k8s DNS
seam mapped to loopback.  tests/test_serve_mp.py pins fixed-seed token
identity across 1/2/4-process meshes with it, and ``bench_operator
--serve-mp`` extends the MULTIPROC artifact trajectory on top of it.

    python -m k8s_tpu.models.mp_serve --gang 4        # spawn + supervise
    python -m k8s_tpu.models.mp_serve --script r.json # one gang member
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import tempfile
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)

CHIEF_OK = "SERVE_MP_OK "
WORKER_OK = "SERVE_MP_WORKER "


def build_model(seed: int = 0, *, vocab: int = 256, hidden: int = 64,
                ffn: Optional[int] = None, layers: int = 2, heads: int = 4,
                kv_heads: Optional[int] = None, max_seq_len: int = 128):
    """Deterministic tiny serving model: same seed → bitwise-identical
    params on every process, so no parameter broadcast is needed (the
    production path loads the same artifact on every pod for the same
    reason)."""
    import jax
    import jax.numpy as jnp

    from k8s_tpu.models.transformer import Transformer, TransformerConfig

    config = TransformerConfig(
        vocab_size=vocab, hidden=hidden, ffn_hidden=ffn or 2 * hidden,
        layers=layers, heads=heads, kv_heads=kv_heads or heads,
        max_seq_len=max_seq_len, dtype=jnp.float32, remat=False)
    params = Transformer(config).init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]
    return config, params


def default_script(n_per_lane: int = 2, max_new: int = 8) -> list[dict]:
    """The three-lane fixed-seed request set (greedy, sampled,
    speculative) the token-identity bar is asserted over."""
    out: list[dict] = []
    for i in range(n_per_lane):
        base = [(i * 13 + j * 7 + 1) % 256 for j in range(6 + i)]
        out.append({"tokens": base, "max_new_tokens": max_new})
        out.append({"tokens": base, "max_new_tokens": max_new,
                    "temperature": 1.0, "seed": 100 + i})
        cycle = [(i * 29 + j * 11 + 3) % 256 for j in range(5)]
        out.append({"tokens": [cycle[j % 5] for j in range(15)],
                    "max_new_tokens": max_new, "speculative": 3,
                    "seed": 200 + i})
        out.append({"tokens": [cycle[j % 5] for j in range(15)],
                    "max_new_tokens": max_new, "speculative": 4,
                    "temperature": 0.8, "top_k": 7, "seed": 300 + i})
    return out


def warmup_script(script: list[dict]) -> list[dict]:
    """Same SHAPES (prompt lengths, max_new, lanes, draft widths),
    different token content and seeds: warms every jit program the real
    script will hit — prefill buckets, fused widths, spec pairs —
    without seeding the prefix tree with the measured prompts, so the
    timed pass is compile-free but reuse-neutral."""
    out = []
    for r in script:
        w = dict(r)
        w["tokens"] = [(int(t) + 1) % 251 for t in r["tokens"]]
        w["seed"] = int(r.get("seed", 0)) + 7919
        out.append(w)
    return out


def _run_script(engine, script: list[dict], threads: int = 1) -> dict:
    """Submit every request (``threads`` closed-loop submitters for the
    bench; 1 keeps strict order for identity runs — though the engine's
    batching-invariance makes outputs independent of interleaving
    either way) and collect per-request tokens in script order."""
    import numpy as np

    results: list = [None] * len(script)
    errors: list[str] = []
    lock = threading.Lock()
    cursor = [0]

    def submit(i: int) -> None:
        r = script[i]
        try:
            toks = engine.submit(
                np.asarray(r["tokens"], np.int32),
                int(r.get("max_new_tokens", 8)),
                eos_id=r.get("eos"),
                temperature=float(r.get("temperature", 0.0)),
                top_k=r.get("top_k"),
                seed=int(r.get("seed", 0)),
                speculative=int(r.get("speculative", 0)))
            results[i] = [int(t) for t in toks]
        except Exception as e:  # noqa: BLE001 - collected, gang-fatal below
            with lock:
                errors.append(f"request {i}: {type(e).__name__}: {e}")

    t0 = time.monotonic()
    if threads <= 1:
        for i in range(len(script)):
            submit(i)
    else:
        def worker() -> None:
            while True:
                with lock:
                    if cursor[0] >= len(script):
                        return
                    i = cursor[0]
                    cursor[0] += 1
                submit(i)

        ts = [threading.Thread(target=worker, daemon=True)
              for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    wall = time.monotonic() - t0
    tokens = sum(len(r) for r in results if r)
    return {"results": results, "errors": errors,
            "wall_s": round(wall, 4), "tokens": tokens,
            "tokens_per_s": round(tokens / max(wall, 1e-9), 2)}


def member_main(args) -> int:
    """One gang member (chief or worker), inside the operator env."""
    from k8s_tpu.launcher import bootstrap
    from k8s_tpu.models import mesh_serve

    pin = os.environ.get("K8S_TPU_SERVE_MP_CPU", "")
    if pin and hasattr(os, "sched_setaffinity"):
        # the bench's one-core-per-process "chip" model: per-chip
        # efficiency on a CPU mesh only means something if each process
        # gets exactly one core's worth of compute (XLA CPU otherwise
        # fans every matmul across the whole box, so a 1-process run
        # already uses every core and the comparison measures nothing)
        os.sched_setaffinity(0, {int(pin) % (os.cpu_count() or 1)})
    bootstrap.apply_platform_env()
    lcfg = bootstrap.LauncherConfig.from_env()
    lcfg = bootstrap.initialize_distributed(lcfg)
    config, params = build_model(
        args.seed, vocab=args.vocab, hidden=args.hidden,
        layers=args.layers, heads=args.heads, max_seq_len=args.max_seq_len)
    chief_host = (lcfg.coordinator_address.rsplit(":", 1)[0]
                  if lcfg.coordinator_address else "127.0.0.1")
    if lcfg.num_processes > 1 and lcfg.process_id != 0:
        return mesh_serve.follower_loop(config, params,
                                        chief_host=chief_host)

    # ---- chief: engine over the mesh placement, then the script ------
    from k8s_tpu.models.engine import Engine

    placement = mesh_serve.MeshPlacement.from_env(config)
    engine = Engine(config, params, slots=args.slots,
                    queue_limit=max(64, len(args.script_requests) + 1),
                    placement=placement)
    try:
        if args.warmup:
            # compile warming (shape-identical, content-distinct): the
            # timed pass below measures serving, not tracing
            warm = _run_script(engine, warmup_script(args.script_requests),
                               threads=args.threads)
            if warm["errors"]:
                raise RuntimeError(f"warmup failed: {warm['errors'][:3]}")
        out = _run_script(engine, args.script_requests,
                          threads=args.threads)
        stats = engine.stats()
        audit = engine.compile_audit()
    finally:
        engine.shutdown()
    # after shutdown: the bus is drained+closed, so the send/enqueue
    # totals cover every broadcast of the run
    plan_bus = placement.plan_bus_stats()
    payload = {
        "plan_bus": plan_bus,
        "num_processes": lcfg.num_processes,
        "tp_degree": stats["tp_degree"],
        "mesh_shape": stats["mesh_shape"],
        "placement": stats["placement"],
        "decode_programs": stats["decode_programs"],
        "prefill_programs": stats["prefill_programs"],
        "spec_mean_accepted": stats["spec_mean_accepted"],
        "compile_ledger": audit,
        **out,
    }
    print(CHIEF_OK + json.dumps(payload, sort_keys=True), flush=True)
    return 1 if out["errors"] else 0


# ------------------------------------------------------------ gang driver

def run_serve_gang(n_processes: int, *, script: Optional[list] = None,
                   threads: int = 1, slots: int = 4, seed: int = 0,
                   hidden: int = 64, layers: int = 2, heads: int = 4,
                   vocab: int = 256, max_seq_len: int = 128,
                   timeout: float = 420.0, kill_chief_after: Optional[float]
                   = None, extra_env: Optional[dict] = None,
                   pin_cpus: bool = False, warmup: bool = False):
    """Spawn an n-process serving gang as real OS processes under the
    operator env contract and supervise it with gang semantics (the
    e2e/multiprocess.py pattern).  Returns the GangResult plus the
    chief's parsed payload on ``.chief_result``.

    ``kill_chief_after`` hard-kills process 0 after that many seconds of
    runtime — the chief-crash drill: the assertion is that WORKERS exit
    nonzero rather than hang (plan-bus EOF → rc 1), so the operator's
    whole-gang restart policy fires."""
    import subprocess

    from k8s_tpu.e2e import multiprocess as mp_e2e

    script = script if script is not None else default_script()
    port = mp_e2e.free_port()
    plan_port = mp_e2e.free_port()
    tfjob = mp_e2e.build_gang_tfjob(n_processes, port, name="serve-mp",
                                    namespace="serve")
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) \
            as f:
        json.dump(script, f)
        script_path = f.name
    argv = ["--script", script_path, "--slots", str(slots),
            "--seed", str(seed), "--hidden", str(hidden),
            "--layers", str(layers), "--heads", str(heads),
            "--vocab", str(vocab), "--max-seq-len", str(max_seq_len),
            "--threads", str(threads),
            "--warmup", "1" if warmup else "0"]

    procs: list = []
    logs = []
    t0 = time.time()
    try:
        for i in range(n_processes):
            env = dict(os.environ)
            env.update(mp_e2e.localhost_env(tfjob, "worker", i))
            env["K8S_TPU_PLATFORM"] = "cpu"
            flags = [fl for fl in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in fl]
            env["XLA_FLAGS"] = " ".join(
                flags + ["--xla_force_host_platform_device_count=1"])
            env["PYTHONPATH"] = mp_e2e.REPO_ROOT + os.pathsep \
                + env.get("PYTHONPATH", "")
            env["K8S_TPU_SERVE_MESH"] = str(n_processes)
            env["K8S_TPU_SERVE_PLAN_PORT"] = str(plan_port)
            if pin_cpus:
                env["K8S_TPU_SERVE_MP_CPU"] = str(i)
            if extra_env:
                env.update(extra_env)
            logf = tempfile.TemporaryFile()
            logs.append(logf)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "k8s_tpu.models.mp_serve"] + argv,
                env=env, cwd=mp_e2e.REPO_ROOT,
                stdout=logf, stderr=subprocess.STDOUT))

        deadline = t0 + timeout
        exit_codes: list = [None] * n_processes
        death_order: list = []
        chief_killed_at: Optional[float] = None
        while time.time() < deadline:
            if kill_chief_after is not None and chief_killed_at is None \
                    and time.time() > t0 + kill_chief_after \
                    and procs[0].poll() is None:
                procs[0].kill()  # the drill: chief dies without a bye
                chief_killed_at = time.time()
            for i, p in enumerate(procs):
                if exit_codes[i] is None and p.poll() is not None:
                    exit_codes[i] = p.returncode
                    death_order.append(i)
            if all(rc is not None for rc in exit_codes):
                break
            time.sleep(0.1)
        else:
            for p in procs:
                if p.poll() is None:
                    p.kill()

        outputs = []
        chief_result = None
        worker_results = []
        for i, p in enumerate(procs):
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
            exit_codes[i] = p.returncode
            logs[i].seek(0)
            out = logs[i].read().decode(errors="replace")
            logs[i].close()
            outputs.append(out or "")
            for line in (out or "").splitlines():
                if line.startswith(CHIEF_OK):
                    chief_result = json.loads(line[len(CHIEF_OK):])
                elif line.startswith(WORKER_OK):
                    worker_results.append(json.loads(line[len(WORKER_OK):]))
        return mp_e2e.GangResult(
            exit_codes=exit_codes, chief_result=chief_result,
            worker_outputs=outputs, duration_s=time.time() - t0,
            death_order=death_order), worker_results
    finally:
        # an exception mid-spawn or mid-supervision (ENOMEM, Ctrl-C in
        # the bench) must not orphan live gang members: a chief parked
        # in accept_workers and workers parked in rendezvous would burn
        # CPU long past the run
        for p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=10)
                # except-ok: best-effort teardown of a KILLed process —
                # raising would mask the original supervision error
                except Exception:  # noqa: BLE001
                    pass
        try:
            os.unlink(script_path)
        except OSError:
            pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--gang", type=int, default=0,
                   help="driver mode: spawn and supervise an N-process "
                   "local serving gang (0 = run as one gang member)")
    p.add_argument("--script", default=None,
                   help="JSON request-script path (member mode)")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--threads", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--max-seq-len", type=int, default=128)
    p.add_argument("--warmup", type=int, choices=(0, 1), default=0,
                   help="run a shape-identical warmup pass before the "
                   "timed script (the bench arms use this)")
    p.add_argument("--timeout", type=float, default=420.0)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.gang > 0:
        res, workers = run_serve_gang(
            args.gang, slots=args.slots, threads=args.threads,
            seed=args.seed, hidden=args.hidden, layers=args.layers,
            heads=args.heads, vocab=args.vocab,
            max_seq_len=args.max_seq_len, timeout=args.timeout)
        print(json.dumps({
            "success": res.success, "exit_codes": res.exit_codes,
            "chief": res.chief_result, "workers": workers,
            "duration_s": round(res.duration_s, 1)}, sort_keys=True))
        if not res.success:
            for i, out in enumerate(res.worker_outputs):
                sys.stderr.write(f"--- proc {i} rc={res.exit_codes[i]} "
                                 f"---\n{out[-2000:]}\n")
        return 0 if res.success else 1
    if args.script:
        with open(args.script) as f:
            args.script_requests = json.load(f)
    else:
        args.script_requests = default_script()
    return member_main(args)


if __name__ == "__main__":
    sys.exit(main())

"""Multi-host tensor-parallel serving placement (ISSUE 14).

``MeshPlacement`` compiles the engine's placement-agnostic compute
bodies (models/placement.PagedCompute) over a device mesh built by
``k8s_tpu.parallel.mesh``:

- **params** are tensor-sharded over the ``tp`` axis (Megatron split —
  q/k/v and gate/up column-sharded, o_proj/down_proj row-sharded with
  GSPMD inserting the per-layer psums; parallel/sharding.serve_tp_*);
- **the KV block pool** is sharded along the kv-head axis, so each host
  holds its head slice of every block while the chief's block tables
  address every shard identically; the pool write scatter and the
  paged-attention read run inside ``shard_map`` islands
  (models/paged.paged_kv_write_tp / paged_attention_tp) that PIN that
  sharding — no collective ever touches the pool;
- **the batch plan** (slot/table/position/token ints, PRNG keys,
  temperatures) is per-step host data on the chief: it is broadcast to
  every worker process over the stdlib plan bus (models/mp_plan.py) and
  uploaded replicated, and sampled tokens come back replicated so only
  the chief ever reads them.

The chief process runs the full engine (scheduler, HTTP, metrics) —
unchanged host-side logic; worker processes run :func:`follower_loop`,
replaying the plan so every process dispatches the same program
sequence.  ``jax.distributed`` brings the world up through the SAME
operator env contract training gangs use (launcher.bootstrap), and the
gang driver below reuses the e2e/multiprocess.py supervision pattern —
a serving gang is launched, supervised, and failure-classified exactly
like a training gang.  A chief crash closes the plan bus and every
worker exits nonzero (asserted in tests): a half-dead serving gang
restarts whole, it never hangs.

CPU-provable: ``run_serve_gang`` spawns N local processes with one
virtual CPU device each (the MULTIPROC bench trajectory), which is how
CI pins token-identity across 1/2/4-process meshes with no TPU.

Knobs: ``K8S_TPU_SERVE_MESH`` (process count; 0/unset = single-host),
``K8S_TPU_SERVE_TP`` (tp degree, default = all visible devices),
``K8S_TPU_SERVE_PLAN_PORT`` (the plan bus port workers dial).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Callable, Optional

import numpy as np

from k8s_tpu.models import mp_plan
from k8s_tpu.models import placement as placement_lib

log = logging.getLogger(__name__)

ENV_PLAN_PORT = "K8S_TPU_SERVE_PLAN_PORT"

# plan-bus opcodes (the closed protocol the follower replays)
OP_INIT = "init"
OP_TABLES = "tables"
OP_PAGED_STEP = "paged_step"
OP_SPEC_STEP = "spec_step"
OP_COW = "cow"
OP_PREFILL = "prefill"


def build_serve_mesh(tp: Optional[int] = None):
    """The serving tp mesh over the visible devices (all of them by
    default — in a multi-process world every process's devices must
    participate or its jit dispatches would deadlock the collectives)."""
    import jax

    from k8s_tpu.parallel.mesh import MeshConfig, make_mesh

    devices = jax.devices()
    tp = tp or placement_lib.env_tp() or len(devices)
    if len(devices) % tp:
        raise ValueError(
            f"{len(devices)} devices not divisible by tp={tp}")
    if jax.process_count() > 1 and tp != len(devices):
        raise ValueError(
            f"a multi-process serving mesh must span every device "
            f"(tp={tp}, devices={len(devices)}): a process outside the "
            "mesh would never join the collectives")
    return make_mesh(MeshConfig(tp=tp), devices[:tp])


def _tree_manifest(tree) -> list:
    """JSON-able (path, dtype, shape) list for a nested-dict pytree of
    arrays — how the chief tells workers the pool's exact shape."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [[[str(getattr(k, "key", k)) for k in path],
             str(leaf.dtype), list(leaf.shape)] for path, leaf in flat]


def _tree_from_manifest(manifest: list, build: Callable) -> dict:
    """Rebuild the nested dict, calling ``build(dtype, shape)`` per
    leaf.  Key order is irrelevant: jax sorts dict keys at flatten time,
    so chief and worker traces see one canonical structure."""
    root: dict = {}
    for path, dtype, shape in manifest:
        node = root
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = build(dtype, tuple(shape))
    return root


class MeshPrograms:
    """The sharded jit programs for one ``PagedCompute`` over one mesh —
    used identically by the chief placement and worker followers, so
    both sides always dispatch the same computation.

    ``ledger=True`` (workers) declares this process's own compile-budget
    seams on the active compile ledger — the chief's are declared by the
    engine as always — so the "budgets honored per process" bench
    assertion reads real per-process data.
    """

    def __init__(self, compute, mesh, *, ledger: bool = False,
                 prefill_budget: Optional[int] = None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.compute = compute
        self.mesh = mesh
        self._repl = NamedSharding(mesh, P())
        self._jits: dict[str, Callable] = {}
        self._ledger = None
        if ledger:
            from k8s_tpu.analysis import compileledger

            self._ledger = compileledger.maybe_active()
            if self._ledger is not None:
                try:
                    from jax import monitoring as _monitoring
                except Exception:  # noqa: BLE001 - wrap fallback covers it
                    _monitoring = None
                compileledger.ensure_listener(_monitoring)
                fused = 1
                widths = 0
                while fused <= 8:  # mirrors engine.MAX_STEP_TOKENS cover
                    widths += 1
                    fused *= 2
                self._seams = {
                    OP_PREFILL: self._ledger.declare(
                        "worker.prefill", prefill_budget,
                        note="one prefill program per bucket, per "
                        "process"),
                    OP_PAGED_STEP: self._ledger.declare(
                        "worker.decode_step", widths * 2,
                        note="one decode program per (fused width, "
                        "sampling) pair, per process"),
                    OP_SPEC_STEP: self._ledger.declare(
                        "worker.spec_step",
                        compileledger.DEFAULT_SPEC_BUDGET,
                        note="one verify program per (draft_k, "
                        "sampling) pair, per process"),
                    OP_COW: self._ledger.declare(
                        "worker.aux", 4,
                        note="shape-constant pool auxiliaries"),
                }
        self._jax = jax

    def ledger_audit(self) -> Optional[dict]:
        if self._ledger is None:
            return None
        return self._ledger.seam_audit(list(self._seams.values()))

    # ------------------------------------------------------ array plumbing

    def to_global(self, arr) -> Any:
        """A host numpy value as a committed fully-replicated global
        array (every process passes the same bytes — the plan bus
        guarantees it)."""
        import jax

        arr = np.asarray(arr)
        return jax.make_array_from_callback(
            arr.shape, self._repl, lambda idx: arr[idx])

    def globalize(self, tree, specs) -> Any:
        """A host-value pytree as committed global arrays under the
        given PartitionSpec pytree.  Every process holds the identical
        host value (same artifact / same seed / zeros), so each supplies
        its own shards with no cross-process transfer."""
        import jax
        from jax.sharding import NamedSharding

        def put(leaf, spec):
            local = np.asarray(leaf)
            sharding = NamedSharding(self.mesh, spec)
            return jax.make_array_from_callback(
                local.shape, sharding, lambda idx: local[idx])

        return jax.tree.map(put, tree, specs,
                            is_leaf=lambda x: hasattr(x, "shape"))

    def zeros_pool(self, manifest: list) -> Any:
        """A global zero KV pool from the chief's init manifest,
        head-sharded per serve_pool_spec — built shard-by-shard so no
        process ever materializes a full pool leaf."""
        import jax
        from jax.sharding import NamedSharding

        from k8s_tpu.parallel.sharding import serve_pool_spec

        def build(dtype, shape):
            sharding = NamedSharding(self.mesh,
                                     serve_pool_spec(_Shaped(shape)))
            return jax.make_array_from_callback(
                shape, sharding,
                lambda idx: np.zeros(_index_shape(shape, idx), dtype))

        return _tree_from_manifest(manifest, build)

    def _pool_shardings(self, pool):
        return self._jax.tree.map(lambda a: a.sharding, pool)

    def _get_jit(self, op: str, pool) -> Callable:
        fn = self._jits.get(op)
        if fn is not None:
            return fn
        import jax

        pool_sh = self._pool_shardings(pool)
        if op == OP_PAGED_STEP:
            fn = jax.jit(self.compute.paged_step, donate_argnums=(1,),
                         static_argnums=(6, 7),
                         out_shardings=(pool_sh, self._repl, self._repl))
        elif op == OP_SPEC_STEP:
            fn = jax.jit(self.compute.spec_step, donate_argnums=(1,),
                         static_argnums=(7, 8),
                         out_shardings=(pool_sh, self._repl, self._repl,
                                        self._repl))
        elif op == OP_COW:
            fn = jax.jit(self.compute.cow, donate_argnums=(0,),
                         out_shardings=pool_sh)
        elif op == OP_PREFILL:
            fn = jax.jit(self.compute.prefill_paged, donate_argnums=(1,),
                         out_shardings=(pool_sh, self._repl))
        else:
            raise ValueError(f"unknown mesh op {op!r}")
        if self._ledger is not None:
            statics = {OP_PAGED_STEP: (6, 7), OP_SPEC_STEP: (7, 8)}.get(op, ())
            fn = self._ledger.wrap(fn, self._seams[op],
                                   name=f"worker.{op}",
                                   static_argnums=statics)
        self._jits[op] = fn
        return fn

    # ---------------------------------------------------------- execution

    def execute(self, op: str, statics: dict, arrays: dict,
                params, pool, tables):
        """Run one plan op; returns ``(new_pool, new_tables, outputs)``.
        The chief calls this right after broadcasting the same message;
        followers call it on receipt — one code path, one program."""
        if op == OP_TABLES:
            return pool, self.to_global(arrays["tables"]), None
        if op == OP_PAGED_STEP:
            fn = self._get_jit(op, pool)
            out = fn(params, pool, tables, self.to_global(arrays["ints"]),
                     self.to_global(arrays["keys"]),
                     self.to_global(arrays["temps"]),
                     int(statics["k"]), bool(statics["sampling"]))
            return out[0], tables, out
        if op == OP_SPEC_STEP:
            fn = self._get_jit(op, pool)
            out = fn(params, pool, tables,
                     self.to_global(arrays["chunk"]),
                     self.to_global(arrays["ints"]),
                     self.to_global(arrays["keys"]),
                     self.to_global(arrays["temps"]),
                     int(statics["k"]), bool(statics["sampling"]))
            return out[0], tables, out
        if op == OP_COW:
            fn = self._get_jit(op, pool)
            new_pool = fn(pool, self.to_global(arrays["src"]),
                          self.to_global(arrays["dst"]))
            return new_pool, tables, new_pool
        if op == OP_PREFILL:
            fn = self._get_jit(op, pool)
            out = fn(params, pool, self.to_global(arrays["table"]),
                     self.to_global(arrays["chunk"]),
                     self.to_global(arrays["positions"]))
            return out[0], tables, out
        raise ValueError(f"unknown plan op {op!r}")


class _Shaped:
    """Shape-only stand-in so serve_pool_spec (which reads ndim via
    ``.shape``) works before any array exists."""

    def __init__(self, shape):
        self.shape = shape


def _index_shape(shape: tuple, idx) -> tuple:
    """Concrete shard shape for an Index tuple over ``shape``."""
    out = []
    for dim, sl in zip(shape, idx):
        start, stop, step = sl.indices(dim)
        out.append(max(0, (stop - start + (step - 1)) // step))
    return tuple(out)


class MeshPlacement:
    """The engine-facing seam for multi-host serving: same ``wrap`` /
    ``globalize`` / ``put_tables`` surface as LocalPlacement, but every
    program is sharded over the tp mesh and every per-call host array is
    broadcast to the worker processes first."""

    is_mesh = True

    def __init__(self, config, mesh=None, *, bus: Optional[mp_plan.PlanBus]
                 = None):
        import jax

        from k8s_tpu.parallel.sharding import check_serve_tp_config

        self.mesh = mesh if mesh is not None else build_serve_mesh()
        self.tp = int(self.mesh.shape.get("tp", 1))
        check_serve_tp_config(config, self.tp)
        self.config = config
        self._bus = bus
        self._progs = MeshPrograms(
            placement_lib.PagedCompute(config, apply_mesh=self.mesh),
            self.mesh)
        self._num_processes = jax.process_count()

    @classmethod
    def from_env(cls, config) -> "MeshPlacement":
        """The serving pod's placement: mesh over the already-initialized
        ``jax.distributed`` world (launcher env contract), plan bus bound
        on ``K8S_TPU_SERVE_PLAN_PORT`` when there are workers to feed."""
        import jax

        bus = None
        if jax.process_count() > 1:
            port = int(os.environ.get(ENV_PLAN_PORT, "0") or 0)
            # bind ALL interfaces: in a real multi-pod gang the workers
            # dial the chief POD's hostname (the coordinator host), not
            # loopback — a 127.0.0.1 bind would strand every worker in
            # connect-retry until the gang crash-loops
            # pipelined (ISSUE 15 satellite): the chief's dispatch
            # overlaps the plan's socket I/O — multi-host chunked
            # prefill stops paying one serialized bus round per chunk
            bus = mp_plan.PlanBus(jax.process_count() - 1, host="",
                                  port=port, pipelined=True)
            bus.accept_workers()
        return cls(config, bus=bus)

    def info(self) -> dict:
        return {
            "num_processes": self._num_processes,
            "mesh_shape": {k: int(v) for k, v in self.mesh.shape.items()
                           if int(v) > 1} or {"tp": 1},
            "tp_degree": self.tp,
            "placement": "mesh",
        }

    # ------------------------------------------------------------- seam API

    def _broadcast(self, op: str, statics: dict, arrays: dict) -> None:
        if self._bus is not None:
            self._bus.broadcast(op, statics, arrays)

    def wrap(self, op: str, fn: Callable, *, donate_argnums=(),
             static_argnums=(), resident_argnums=()) -> Callable:
        """A callable with ``fn``'s signature that broadcasts the
        per-call host plan (everything not resident/static) and executes
        the sharded program.  ``fn`` itself is ignored: the sharded
        programs compile the same PagedCompute bodies (one compute, one
        math — the local jit and the mesh jit can't drift)."""
        del fn, donate_argnums, static_argnums, resident_argnums
        progs = self._progs

        if op == OP_PAGED_STEP:
            def step(params, pool, tables, ints, keys, temps, k, sampling):
                msg = {"ints": ints, "keys": keys, "temps": temps}
                self._broadcast(op, {"k": int(k),
                                     "sampling": bool(sampling)}, msg)
                _, _, out = progs.execute(
                    op, {"k": k, "sampling": sampling}, msg,
                    params, pool, tables)
                return out
            return step
        if op == OP_SPEC_STEP:
            def spec(params, pool, tables, chunk, ints, keys, temps, k,
                     sampling):
                msg = {"chunk": chunk, "ints": ints, "keys": keys,
                       "temps": temps}
                self._broadcast(op, {"k": int(k),
                                     "sampling": bool(sampling)}, msg)
                _, _, out = progs.execute(
                    op, {"k": k, "sampling": sampling}, msg,
                    params, pool, tables)
                return out
            return spec
        if op == OP_COW:
            def cow(pool, src, dst):
                msg = {"src": np.int32(src), "dst": np.int32(dst)}
                self._broadcast(op, {}, msg)
                new_pool, _, _ = progs.execute(op, {}, msg,
                                               None, pool, None)
                return new_pool
            return cow
        if op == OP_PREFILL:
            def prefill(params, pool, table, chunk, positions):
                msg = {"table": table, "chunk": chunk,
                       "positions": positions}
                self._broadcast(op, {}, msg)
                _, _, out = progs.execute(op, {}, msg,
                                          params, pool, None)
                return out
            return prefill
        raise ValueError(
            f"mesh placement has no program for op {op!r} (windowed "
            "dense configs are single-host)")

    def globalize_params(self, params):
        from k8s_tpu.parallel.sharding import serve_tp_param_specs

        return self._progs.globalize(params, serve_tp_param_specs(params))

    def build_pool(self, pool_shapes):
        """Build the head-sharded zero pool from its shape manifest and
        tell the workers — the ``init`` message every follower builds
        its own pool from (no pool bytes cross the wire: zeros are
        zeros on every host, and no host — chief included — ever
        materializes a full-size leaf)."""
        manifest = _tree_manifest(pool_shapes)
        self._broadcast(OP_INIT, {"pool": manifest}, {})
        return self._progs.zeros_pool(manifest)

    def put_tables(self, stack):
        self._broadcast(OP_TABLES, {}, {"tables": stack})
        return self._progs.to_global(stack)

    def plan_bus_stats(self) -> Optional[dict]:
        """The plan bus's pipelining telemetry (None without workers) —
        the bench asserts enqueue-wait ≪ send seconds on it."""
        return self._bus.stats() if self._bus is not None else None

    def close(self) -> None:
        if self._bus is not None:
            self._bus.close()


# ---------------------------------------------------------------- follower

def local_fraction(tree) -> float:
    """MEASURED per-host share of a global-array pytree: addressable
    shard elements over global elements.  ~1/N for the head-sharded
    pool, between 1/N and 1 for params (replicated embedding/norms) —
    the bench asserts on this, not on the spec functions, so a
    regression that silently replicates the pool at runtime fails."""
    import jax

    total = 0
    local = 0
    for leaf in jax.tree.leaves(tree):
        total += leaf.size
        local += sum(s.data.size for s in leaf.addressable_shards)
    return local / max(total, 1)


def follower_loop(config, params, *, chief_host: str = "127.0.0.1",
                  plan_port: Optional[int] = None) -> int:
    """Worker-process main loop: build the same mesh/params the chief
    holds, then replay plan messages until the chief says bye (exit 0)
    or the stream dies (exit 1 — the gang restarts whole).  Returns the
    exit code; prints one ``SERVE_MP_WORKER {json}`` line with the
    per-process compile audit on clean shutdown."""
    import jax

    mesh = build_serve_mesh()
    from k8s_tpu.models.decode import prefill_buckets_for
    from k8s_tpu.parallel.sharding import (
        check_serve_tp_config,
        serve_tp_param_specs,
    )

    tp = int(mesh.shape.get("tp", 1))
    check_serve_tp_config(config, tp)
    progs = MeshPrograms(
        placement_lib.PagedCompute(config, apply_mesh=mesh), mesh,
        ledger=True, prefill_budget=len(prefill_buckets_for(config)))
    params_g = progs.globalize(params, serve_tp_param_specs(params))
    port = plan_port if plan_port is not None \
        else int(os.environ.get(ENV_PLAN_PORT, "0") or 0)
    follower = mp_plan.PlanFollower(chief_host, port)
    pool = None
    tables = None
    steps = 0
    pool_frac = None
    try:
        while True:
            try:
                op, statics, arrays = follower.recv()
            except mp_plan.PlanBusClosed as e:
                if e.clean:
                    audit = progs.ledger_audit()
                    print("SERVE_MP_WORKER " + json.dumps({
                        "process_id": jax.process_index(),
                        "ops": steps,
                        "compile_ledger": audit,
                        # MEASURED per-host memory shares (what the
                        # bench asserts ~1/N on — not the spec math)
                        "pool_local_fraction": pool_frac,
                        "params_local_fraction": round(
                            local_fraction(params_g), 4),
                    }, sort_keys=True), flush=True)
                    return 0
                log.error("plan bus died (chief crashed?): %s", e)
                return 1
            if op == OP_INIT:
                pool = progs.zeros_pool(statics["pool"])
                pool_frac = round(local_fraction(pool), 4)
                continue
            pool, tables, _ = progs.execute(op, statics, arrays,
                                            params_g, pool, tables)
            steps += 1
    finally:
        follower.close()

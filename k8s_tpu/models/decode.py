"""Autoregressive inference driver: jit-able prefill + KV-cached token loop.

The reference repo has no serving story at all (it is a training operator);
this is the framework's inference surface for the Transformer family, built
TPU-first:

- the whole generation — prefill, every decode step, and sampling — is one
  jit program: the token loop is a ``lax.scan`` with a static step count
  (no data-dependent Python control flow, one compile, static shapes);
- K/V caches live in the flax ``cache`` collection threaded through the
  scan carry as ordinary pytree state (transformer.Attention._decode_step);
- sliding-window configs decode from an O(window) ring-buffer cache, so
  long-context inference memory is bounded by the window, not the sequence;
- EOS is handled with a done-mask (finished rows emit ``pad_id`` and stop
  advancing), keeping the scan shape-static instead of early-exiting.

Reference parity note: the closest upstream artifact is the smoke
workload's inference-free matmul graph (tf_smoke); decode exists because a
complete LM framework needs it, not because the operator did.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from k8s_tpu.models.transformer import Transformer, TransformerConfig


def _process_logits(logits, temperature: float, top_k: Optional[int]):
    """Temperature/top-k-processed logits (f32): the softmax of THIS is
    the sampling distribution — the single definition shared by vanilla
    sampling and speculative rejection sampling, which must match it
    EXACTLY."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        # clamp the large side: top_k >= vocab is a no-op filter, not a
        # trace-time shape error (serve_lm lets arbitrary --top_k through)
        kk = min(top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, kk)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    return logits


def sample_logits(logits, rng, temperature: float = 0.0,
                  top_k: Optional[int] = None):
    """Sample next tokens from [B, V] logits.

    temperature == 0 is greedy argmax (rng unused); otherwise softmax
    sampling at the given temperature, optionally truncated to the top_k
    highest-probability tokens (mask, not gather — XLA-friendly and
    shape-static).
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _process_logits(logits, temperature, top_k)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_logits_rows(logits, keys, temperature, top_k):
    """Row-wise sampling for the batched decode step: each batch row
    samples from ITS OWN distribution with ITS OWN rng key.

    ``logits`` is [B, V]; ``keys`` is [B, 2] uint32 (one PRNG key per
    row); ``temperature`` is [B] float32 (0 = greedy); ``top_k`` is [B]
    int32 (0 = disabled).  Returns ``(new_keys [B, 2], tokens [B])``.

    EXACTNESS CONTRACT (the batched-sampling lane's correctness claim,
    asserted in tests/test_engine.py and over HTTP in
    tests/test_serve_http.py): for every row this computes token-for-
    token what the exclusive lane's jit program computes for a batch-1
    request —

    - the key schedule is ``rng, sub = jax.random.split(rng)`` per step
      (``new_keys`` carries ``rng`` forward, ``sub`` draws the sample),
      the same unconditional split :func:`make_generate_fn` performs;
    - temperature/top-k processing mirrors :func:`_process_logits`
      value-for-value — the kth-largest threshold comes from a full
      descending sort instead of ``lax.top_k`` (per-row k is a traced
      value here, so the static-k gather is unavailable), but the kth
      VALUE and the ``logits < kth`` mask are identical;
    - the draw is ``jax.random.categorical`` over a [1, V] row under
      ``vmap`` — vmap semantics guarantee the per-row result equals the
      unbatched batch-1 call with the same key;
    - temperature-0 rows take the raw-dtype argmax (no f32 cast), the
      same greedy path :func:`sample_logits` takes, and their sampled
      draw is discarded (their key still advances — make_generate_fn
      splits unconditionally too, so the schedule stays aligned even
      for requests that never use the sub key).
    """
    V = logits.shape[-1]

    def row(key, lg, t, tk):
        ks = jax.random.split(key)  # [2, 2]: ks[0] carries, ks[1] draws
        greedy = jnp.argmax(lg).astype(jnp.int32)
        # _process_logits, row-wise: divide by the row's temperature
        # (guarded for greedy rows whose division result is unused)
        x = lg.astype(jnp.float32) / jnp.where(t > 0, t, 1.0)
        srt = jnp.sort(x)[::-1]
        kth = srt[jnp.clip(tk, 1, V) - 1]  # kth-largest == lax.top_k [-1]
        x = jnp.where((tk > 0) & (x < kth), -1e30, x)
        s = jax.random.categorical(ks[1], x[None, :], axis=-1)[0]
        return ks[0], jnp.where(t > 0, s.astype(jnp.int32), greedy)

    return jax.vmap(row)(keys, logits, temperature, top_k)


def _spec_draft_padded(draft, pad_id: int = 0):
    """Pad one column onto [B, K-1] drafts so acceptance-count gathers
    stay in bounds; the pad value is never selected (masked by the
    acceptance count everywhere it could surface)."""
    return jnp.concatenate(
        [draft, jnp.full((draft.shape[0], 1), pad_id, jnp.int32)], axis=1)


def spec_accept_greedy(logits, draft):
    """Greedy speculative acceptance: ``logits`` [B, K, V] are the
    verify chunk's raw logits, ``draft`` [B, K-1] the proposals.
    draft[i] is accepted iff it equals the model's own argmax after
    consuming the (accepted) chunk prefix 0..i; the bonus token is the
    argmax at the first mismatch (or the chunk's last position when all
    drafts survive).  Returns ``(acc [B], bonus [B])`` — output is
    argmax-EXACT with vanilla greedy by construction.

    The ONE definition shared by the exclusive lane
    (:func:`make_speculative_generate_fn`) and the engine's batched
    variable-width step (:func:`spec_verify_rows`), so routing can never
    change a token."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    match = (draft == greedy[:, :-1]).astype(jnp.int32)
    acc = jnp.cumprod(match, axis=1).sum(axis=1)  # [B] in 0..K-1
    bonus = jnp.take_along_axis(greedy, acc[:, None], 1)[:, 0]
    return acc, bonus


def spec_accept_sampled(x, draft, ku, kc, pad_id: int = 0):
    """Rejection sampling against the point-mass draft proposal
    (Leviathan et al.): ``x`` [B, K, V] are the verify chunk's
    temperature/top-k PROCESSED logits (the softmax of ``x`` is the
    sampling distribution), ``draft`` [B, K-1] the proposals, ``ku`` /
    ``kc`` the uniform- and categorical-draw keys of this iteration.
    draft[i] is accepted w.p. ``p_i(draft[i])``; on the first rejection
    the emitted token is drawn from the renormalized residual (``p``
    with the draft masked — the proposal's mass sits only at the draft,
    so the residual IS renormalized ``p`` without it); when every draft
    survives, a bonus token is drawn from the unmasked final
    distribution.  Each emitted token is therefore distributed EXACTLY
    as vanilla temperature/top-k sampling.  Returns ``(acc [B],
    bonus [B])``.

    One definition across the exclusive and batched spec lanes (see
    :func:`spec_accept_greedy`); fixed-seed equivalence additionally
    needs the caller to follow the shared key schedule
    (``rng, ku, kc = jax.random.split(rng, 3)`` per verify)."""
    logp = jax.nn.log_softmax(x, axis=-1)
    pd = jnp.exp(jnp.take_along_axis(
        logp[:, :-1], draft[..., None], 2)[..., 0])  # [B, K-1]
    u = jax.random.uniform(ku, pd.shape)
    accept = (u < pd).astype(jnp.int32)
    acc = jnp.cumprod(accept, axis=1).sum(axis=1)
    x_acc = jnp.take_along_axis(x, acc[:, None, None], 1)[:, 0]  # [B, V]
    d_acc = jnp.take_along_axis(
        _spec_draft_padded(draft, pad_id), acc[:, None], 1)[:, 0]
    rejected = acc < draft.shape[1]
    vocab = jnp.arange(x.shape[-1])[None, :]
    x_res = jnp.where(
        rejected[:, None] & (vocab == d_acc[:, None]), -1e30, x_acc)
    bonus = jax.random.categorical(kc, x_res, axis=-1).astype(jnp.int32)
    return acc, bonus


def lookup_draft_host(ctx, draft_k: int) -> list[int]:
    """Host-side prompt-lookup drafting for the engine's batched
    speculative lane: propose ``draft_k - 1`` continuations of ``ctx``
    (the row's full context: prompt plus every emitted token) by copying
    what followed the most recent earlier occurrence of the trailing
    2-gram; fallback is repeating the last token.

    EXACTNESS CONTRACT: token-for-token what
    :func:`make_speculative_generate_fn`'s device-side ``lookup_draft``
    proposes for the same context — latest occurrence wins, matches are
    only sought strictly before the trailing 2-gram itself, and
    continuations past the written length fall back to the last token —
    so the batched lane verifies the same chunks the exclusive lane
    would and fixed-seed output stays identical.

    The backward scan is O(len(ctx)) per verify step on the engine's
    dispatch thread; contexts are bounded by max_seq_len, but an
    incremental per-slot 2-gram -> latest-index map (updated as tokens
    append) is the upgrade path if host drafting ever shows up in step
    latency — it must preserve the latest-occurrence/j < n-2 contract
    above bit-for-bit."""
    n = len(ctx)
    if n < 2:
        raise ValueError("prompt-lookup drafting needs context >= 2")
    a, last = ctx[-2], ctx[-1]
    j = -1
    for i in range(n - 3, -1, -1):  # j < n - 2, latest occurrence wins
        if ctx[i] == a and ctx[i + 1] == last:
            j = i
            break
    out = []
    for d in range(draft_k - 1):
        off = j + 2 + d
        out.append(int(ctx[off]) if j >= 0 and off < n else int(last))
    return out


def spec_verify_rows(logits, chunk, keys, temperature, top_k, widths,
                     sampling: bool):
    """Row-wise accept/reject for the engine's batched variable-width
    decode step: each slot advances a per-slot number of tokens from ONE
    shared [B, W]-chunk model call.

    ``logits`` is [B, W, V] (the verify chunk's logits); ``chunk`` [B, W]
    is what each row fed (its last token, then its drafts; width-1 rows
    pad); ``keys`` [B, 2] per-row PRNG carries; ``temperature`` [B] f32;
    ``top_k`` [B] int32 (0 = off); ``widths`` [B] — 1 for plain
    greedy/sampled rows, the row's ``draft_k`` for speculative rows (all
    speculative rows in one call share draft_k == W; the engine groups
    by draft_k).  ``sampling`` is the jit-static any-row-samples flag.
    Returns ``(new_keys [B, 2], emit [B, W], n_emit [B])`` — row ``b``
    emitted ``emit[b, :n_emit[b]]``.

    EXACTNESS CONTRACT (asserted in tests/test_engine.py and over HTTP):

    - width-1 rows compute exactly :func:`sample_logits_rows`'s per-row
      math on position 0 — split once, draw with the sub key, raw-dtype
      argmax for temperature-0 rows;
    - speculative rows follow the exclusive lane's per-iteration
      schedule: ``rng, ku, kc = split(rng, 3)``, temperature/top-k
      processing mirroring :func:`_process_logits` value-for-value
      (sort-based kth threshold, per-position), then the shared
      :func:`spec_accept_sampled` / :func:`spec_accept_greedy` — for
      every row this emits token-for-token what
      :func:`make_speculative_generate_fn` emits for a batch-1 request
      with the same seed.
    """
    W = logits.shape[1]
    V = logits.shape[-1]

    def row(key, lg, ck, t, tk, w):
        is_spec = w > 1
        draft = ck[1:]  # [W-1]
        kk = jnp.clip(tk, 1, V) - 1
        # --- width-1 lanes: the single-token batched schedule --------
        g0 = jnp.argmax(lg[0]).astype(jnp.int32)
        if sampling:
            ks2 = jax.random.split(key)
            x0 = lg[0].astype(jnp.float32) / jnp.where(t > 0, t, 1.0)
            kth0 = jnp.sort(x0)[::-1][kk]
            x0 = jnp.where((tk > 0) & (x0 < kth0), -1e30, x0)
            s0 = jax.random.categorical(ks2[1], x0[None, :], axis=-1)[0]
            tok1 = jnp.where(t > 0, s0.astype(jnp.int32), g0)
        else:
            tok1 = g0
        # --- speculative lanes: one K-wide verify ---------------------
        acc_g, bonus_g = spec_accept_greedy(lg[None], draft[None])
        acc, bonus = acc_g[0], bonus_g[0]
        new_key = key
        if sampling:
            ks3 = jax.random.split(key, 3)
            # _process_logits row-wise over the whole chunk: divide by
            # the row's temperature, kth-largest threshold per position
            x = lg.astype(jnp.float32) / jnp.where(t > 0, t, 1.0)
            kth = jnp.sort(x, axis=-1)[:, ::-1][:, kk]  # [W]
            x = jnp.where((tk > 0) & (x < kth[:, None]), -1e30, x)
            acc_s, bonus_s = spec_accept_sampled(
                x[None], draft[None], ks3[1], ks3[2])
            acc = jnp.where(t > 0, acc_s[0], acc)
            bonus = jnp.where(t > 0, bonus_s[0], bonus)
            new_key = jnp.where(is_spec, ks3[0], ks2[0])
        dp = jnp.concatenate([draft, jnp.zeros((1,), jnp.int32)])
        emit_spec = jnp.where(jnp.arange(W) < acc, dp, bonus)
        emit_one = jnp.zeros((W,), jnp.int32).at[0].set(tok1)
        emit = jnp.where(is_spec, emit_spec, emit_one)
        n = jnp.where(is_spec, acc + 1, 1).astype(jnp.int32)
        return new_key, emit, n

    return jax.vmap(row)(keys, logits, chunk, temperature, top_k, widths)


def check_speculative_capacity(config: TransformerConfig, prompt_len: int,
                               max_new_tokens: int, draft_k: int) -> None:
    """The full-cache headroom bound for speculative decoding: the final
    verify writes draft positions up to prompt_len + max_new_tokens +
    draft_k - 3, which must stay within the cache — the one definition
    shared by the exclusive lane's trace-time guard and the engine's
    batched-lane admission check."""
    if config.window_size is None and \
            prompt_len + max_new_tokens - 2 + draft_k > config.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) + "
            f"draft_k ({draft_k}) headroom exceeds max_seq_len "
            f"({config.max_seq_len})")


def _check_cache_capacity(config: TransformerConfig, prompt_len: int,
                          max_new_tokens: int) -> None:
    """Shared full-cache bound for greedy and beam decoding: the LAST
    sampled token is returned, never fed back, so the highest position
    written/attended is prompt_len + max_new_tokens - 2."""
    if config.window_size is None and \
            prompt_len + max_new_tokens - 1 > config.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds max_seq_len ({config.max_seq_len}) and no "
            "window_size is set (the full KV cache is max_seq_len "
            "long; sliding-window configs decode indefinitely)")


def prefill_buckets_for(config: TransformerConfig) -> tuple[int, ...]:
    """The default prefill chunk-size bucket set for a serving engine:
    powers of two up to ``max_seq_len`` (capped at ``prefill_chunk`` for
    sliding-window configs, whose ring cache only has window +
    prefill_chunk - 1 slots per chunk write).  Any prompt length
    decomposes into bucket-sized chunks (1 is always a bucket), so the
    engine compiles at most ``len(buckets)`` prefill programs instead of
    one per distinct prompt length."""
    cap = config.max_seq_len
    if config.window_size:
        cap = min(cap, max(1, config.prefill_chunk))
    out, b = [], 1
    while b <= cap:
        out.append(b)
        b *= 2
    return tuple(out)


def split_prefill(length: int, buckets: tuple[int, ...]) -> list[int]:
    """Greedy largest-first decomposition of a prompt length into
    bucket-sized chunks (e.g. 13 over {1,2,4,8} -> [8, 4, 1]).  Each
    chunk is one decode-mode cache call at exact absolute positions — no
    padding, so there is no left-pad RoPE corruption to work around."""
    if length < 1:
        raise ValueError(f"length must be >= 1, got {length}")
    bs = sorted(buckets, reverse=True)
    if not bs or bs[-1] != 1:
        raise ValueError(f"buckets must include 1, got {buckets}")
    out: list[int] = []
    rem = length
    for b in bs:
        while rem >= b:
            out.append(b)
            rem -= b
    return out


def make_generate_fn(config: TransformerConfig, max_new_tokens: int,
                     temperature: float = 0.0, top_k: Optional[int] = None,
                     eos_id: Optional[int] = None, pad_id: int = 0,
                     chunked_prefill: bool = False):
    """Build ``generate(params, prompt, rng) -> [B, max_new_tokens]``.

    The returned function is jit-compiled once per (config, prompt shape):
    prefill consumes the prompt and populates the caches, then a
    ``lax.scan`` of single-token steps carries ``(cache, token, position,
    done, rng)``.  Rows that emit ``eos_id`` are frozen to ``pad_id`` for
    the remaining steps.

    ``chunked_prefill``: instead of one full-length prefill pass, stream
    the prompt through the cache in ``config.prefill_chunk``-token chunks
    (a leading remainder chunk plus a ``lax.scan`` over the full ones).
    Prefill activation memory becomes O(chunk * cache) instead of
    O(prompt^2 / blocks), and with a sliding window the cache itself is
    O(window + chunk) — rolling prefill for arbitrarily long prompts.
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if chunked_prefill and config.prefill_chunk < 1:
        raise ValueError("chunked_prefill needs config.prefill_chunk >= 1")
    model = Transformer(config)

    def _chunked_prefill(params, prompt):
        """Stream the prompt through decode-mode cache calls; returns
        (cache, last-position logits)."""
        B, Lp = prompt.shape
        C = config.prefill_chunk
        first = Lp % C or min(C, Lp)  # leading remainder (or one chunk)
        pos0 = jnp.broadcast_to(jnp.arange(first), (B, first))
        logits, varz = model.apply(
            {"params": params}, prompt[:, :first], positions=pos0,
            mode="decode", mutable=["cache"])
        cache, last = varz["cache"], logits[:, -1]
        n_full = (Lp - first) // C
        if n_full == 0:
            return cache, last
        chunks = prompt[:, first:].reshape(B, n_full, C).transpose(1, 0, 2)
        bases = first + C * jnp.arange(n_full)

        # last logits ride the CARRY, not the scan outputs: stacking every
        # chunk's [B, V] logits would grow HBM with prompt length, exactly
        # what rolling prefill exists to avoid
        def body(carry, xs):
            cache, _ = carry
            chunk, base = xs
            pos = base + jnp.broadcast_to(jnp.arange(C), (B, C))
            logits, varz = model.apply(
                {"params": params, "cache": cache}, chunk, positions=pos,
                mode="decode", mutable=["cache"])
            return (varz["cache"], logits[:, -1]), None

        (cache, last), _ = jax.lax.scan(body, (cache, last),
                                        (chunks, bases))
        return cache, last

    @jax.jit
    def generate(params, prompt, rng):
        B, Lp = prompt.shape
        _check_cache_capacity(config, Lp, max_new_tokens)
        if chunked_prefill:
            cache, last = _chunked_prefill(params, prompt)
            varz = {"cache": cache}
        else:
            logits, varz = model.apply(
                {"params": params}, prompt, mode="prefill",
                mutable=["cache"])
            last = logits[:, -1]
        rng, sub = jax.random.split(rng)
        tok = sample_logits(last, sub, temperature, top_k)
        # EOS itself is emitted; rows freeze to pad_id from the NEXT step
        done = (tok == eos_id) if eos_id is not None \
            else jnp.zeros((B,), bool)

        def step(carry, _):
            cache, tok, pos, done, rng = carry
            logits, varz = model.apply(
                {"params": params, "cache": cache},
                tok[:, None], positions=pos[:, None], mode="decode",
                mutable=["cache"])
            rng, sub = jax.random.split(rng)
            nxt = sample_logits(logits[:, -1], sub, temperature, top_k)
            nxt = jnp.where(done, pad_id, nxt)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            return (varz["cache"], nxt, pos + 1, done, rng), nxt

        pos = jnp.full((B,), Lp, jnp.int32)
        carry = (varz["cache"], tok, pos, done, rng)
        if max_new_tokens == 1:
            return tok[:, None]
        _, rest = jax.lax.scan(step, carry, None, length=max_new_tokens - 1)
        return jnp.concatenate([tok[:, None], rest.T], axis=1)

    return generate


def make_speculative_generate_fn(config: TransformerConfig,
                                 max_new_tokens: int, draft_k: int = 4,
                                 eos_id: Optional[int] = None,
                                 pad_id: int = 0,
                                 temperature: float = 0.0,
                                 top_k: Optional[int] = None,
                                 return_stats: bool = False):
    """Speculative decoding with prompt-lookup drafting:
    ``generate(params, prompt[, rng]) -> [B, max_new_tokens]`` (plus a
    per-call stats dict when ``return_stats``).

    Each iteration proposes ``draft_k - 1`` continuation tokens by copying
    what followed the most recent earlier occurrence of the current
    2-gram in the row's own context (prompt-lookup decoding — model-free
    drafting, strongest on repetitive/structured text), then VERIFIES the
    whole proposal in ONE ``draft_k``-token cached decode call: position
    ``i``'s logits depend only on the (correct) chunk prefix.

    - ``temperature == 0`` (default): the longest draft prefix matching
      the model's own argmax is accepted, plus the model's bonus token.
      Output is argmax-EXACT with vanilla greedy by construction.
    - ``temperature > 0``: REJECTION sampling (Leviathan et al.).  The
      deterministic draft is a point-mass proposal, so draft ``d`` at
      position ``i`` is accepted with probability ``p_i(d)`` (the model's
      temperature/top-k sampling distribution); on the first rejection
      the emitted token is drawn from the renormalized residual — ``p_i``
      with ``d`` masked out — and when every draft survives, a bonus
      token is drawn from ``p_{k-1}``.  Each emitted token is therefore
      distributed EXACTLY as vanilla temperature/top-k sampling; only the
      number of model calls changes.  Pass ``rng``.

    Rejected-draft cache writes need no rollback: their slots carry
    positions the causal mask hides from every later query, and the next
    chunk (which starts at the first rejected position) overwrites them
    before attending — the write-then-mask chunk contract from chunked
    prefill.  Composes with GQA, the int8 KV cache, and sliding-window
    ring caches (requiring ``config.prefill_chunk >= draft_k`` so draft
    writes never evict still-attended ring slots).
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if draft_k < 2:
        raise ValueError("draft_k must be >= 2 (k-1 drafts + 1 bonus)")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and temperature == 0.0:
        raise ValueError("top_k needs temperature > 0 (greedy ignores it)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if config.window_size is not None and config.prefill_chunk < draft_k:
        # Ring soundness: a draft chunk writes up to draft_k slots ahead,
        # evicting position p - (window + prefill_chunk - 1) when it
        # writes p.  With prefill_chunk >= draft_k the evicted position
        # is always OUTSIDE every remaining query's window (current
        # chunk's earliest query included) — smaller chunks would evict
        # keys still attended, which no rollback can restore.
        raise ValueError(
            f"speculative decoding over a sliding-window ring needs "
            f"config.prefill_chunk >= draft_k ({config.prefill_chunk} < "
            f"{draft_k}): the ring is sized window + prefill_chunk - 1")
    model = Transformer(config)
    sampling = temperature > 0.0

    def _proc(logits):
        # the SHARED processing (one definition with vanilla sampling —
        # the exactness guarantee is stated against its softmax)
        return _process_logits(logits, temperature, top_k)

    @jax.jit
    def generate(params, prompt, rng=None):
        B, Lp = prompt.shape
        if Lp < 2:
            raise ValueError("prompt-lookup drafting needs prompt_len >= 2")
        if sampling and rng is None:
            raise ValueError("temperature > 0 needs an rng key")
        # FULL caches only: the final iteration (n = max_new_tokens - 1)
        # writes draft positions up to Lp + max_new_tokens + draft_k - 3,
        # which must stay <= max_seq_len - 1 — slot = pos % S wraps at
        # max_seq_len and silently EVICTS prompt token 0's K/V before the
        # same call attends.  Windowed rings wrap BY DESIGN (eviction
        # safety is the prefill_chunk >= draft_k build-time guard) and
        # decode indefinitely.
        check_speculative_capacity(config, Lp, max_new_tokens, draft_k)
        T = Lp + max_new_tokens
        K = draft_k

        logits, varz = model.apply({"params": params}, prompt,
                                   mode="prefill", mutable=["cache"])
        if sampling:
            rng, sub = jax.random.split(rng)
            first = jax.random.categorical(
                sub, _proc(logits[:, -1]), axis=-1).astype(jnp.int32)
        else:
            rng = jax.random.PRNGKey(0) if rng is None else rng
            first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate(
            [prompt.astype(jnp.int32),
             jnp.full((B, max_new_tokens), pad_id, jnp.int32)], axis=1)
        seq = seq.at[:, Lp].set(first)
        n = jnp.ones((B,), jnp.int32)
        done = (first == eos_id) if eos_id is not None \
            else jnp.zeros((B,), bool)
        iters = jnp.zeros((), jnp.int32)

        def lookup_draft(seq, length, last):
            """[B, K-1] proposed continuations of each row's last 2-gram
            (latest earlier occurrence wins; fallback: repeat last)."""
            a = jnp.take_along_axis(seq, (length - 2)[:, None], 1)[:, 0]
            idx = jnp.arange(T - 1)
            hit = (seq[:, :-1] == a[:, None]) & (seq[:, 1:] == last[:, None]) \
                & (idx[None, :] < (length - 2)[:, None])
            j = jnp.where(hit, idx[None, :], -1).max(axis=1)  # [B]
            offs = (j + 2)[:, None] + jnp.arange(K - 1)[None, :]
            valid = (j >= 0)[:, None] & (offs < length[:, None])
            toks = jnp.take_along_axis(seq, jnp.clip(offs, 0, T - 1), 1)
            return jnp.where(valid, toks, last[:, None])

        def cond(carry):
            seq, n, last, done, cache, iters, rng = carry
            return jnp.any(~done & (n < max_new_tokens))

        def draft_padded(draft):
            # draft is [B, K-1]; pad one column so `where` shapes line up
            return _spec_draft_padded(draft, pad_id)

        def body(carry):
            seq, n, last, done, cache, iters, rng = carry
            length = Lp + n                      # next write index per row
            draft = lookup_draft(seq, length, last)          # [B, K-1]
            chunk = jnp.concatenate([last[:, None], draft], axis=1)
            positions = (length - 1)[:, None] + jnp.arange(K)[None, :]
            logits, varz = model.apply(
                {"params": params, "cache": cache}, chunk,
                positions=positions, mode="decode", mutable=["cache"])
            if sampling:
                # rejection sampling against the point-mass draft
                # proposal — spec_accept_sampled, the one definition
                # shared with the engine's batched spec lane (which must
                # match this token-for-token at a fixed seed)
                rng, ku, kc = jax.random.split(rng, 3)
                acc, bonus = spec_accept_sampled(
                    _proc(logits), draft, ku, kc, pad_id)
            else:
                acc, bonus = spec_accept_greedy(logits, draft)
            ar = jnp.arange(K)[None, :]
            emit = jnp.where(ar < acc[:, None], draft_padded(draft),
                             bonus[:, None])                 # [B, K]
            n_new = acc + 1
            if eos_id is not None:
                # truncate at the FIRST emitted EOS (inclusive)
                is_eos = (emit == eos_id) & (ar < n_new[:, None])
                any_eos = is_eos.any(axis=1)
                first_eos = jnp.where(is_eos, ar, K).min(axis=1)
                n_new = jnp.where(any_eos, first_eos + 1, n_new)
                done_next = done | any_eos
            else:
                done_next = done
            n_new = jnp.minimum(n_new, max_new_tokens - n)
            n_new = jnp.where(done | (n >= max_new_tokens), 0, n_new)
            cols = length[:, None] + ar
            write = (ar < n_new[:, None])
            seq = seq.at[jnp.arange(B)[:, None],
                         jnp.where(write, cols, T)].set(
                jnp.where(write, emit, pad_id), mode="drop")
            last_new = jnp.take_along_axis(
                emit, jnp.maximum(n_new - 1, 0)[:, None], 1)[:, 0]
            last = jnp.where(n_new > 0, last_new, last)
            return (seq, n + n_new, last, done_next, varz["cache"],
                    iters + 1, rng)

        carry = (seq, n, first, done, varz["cache"], iters, rng)
        seq, n, _, _, _, iters, _ = jax.lax.while_loop(cond, body, carry)
        out = seq[:, Lp:]
        if return_stats:
            return out, {
                "model_calls": iters + 1,  # +1 for the prefill call
                # mean tokens landed per batched verify call per row
                # (1.0 = vanilla decode pace; up to draft_k when drafts hit)
                "tokens_per_call": (n - 1).sum()
                / (jnp.maximum(iters, 1) * B),
            }
        return out

    return generate


@functools.lru_cache(maxsize=32)
def cached_speculative_fn(config: TransformerConfig, max_new_tokens: int,
                          draft_k: int = 4, eos_id: Optional[int] = None,
                          pad_id: int = 0, temperature: float = 0.0,
                          top_k: Optional[int] = None):
    """Program-cached :func:`make_speculative_generate_fn` (config is a
    frozen dataclass, so the whole generation config is hashable) — a
    resident server's repeated shapes reuse the executable instead of
    re-tracing per request."""
    return make_speculative_generate_fn(config, max_new_tokens,
                                        draft_k=draft_k, eos_id=eos_id,
                                        pad_id=pad_id,
                                        temperature=temperature,
                                        top_k=top_k)


def make_beam_generate_fn(config: TransformerConfig, max_new_tokens: int,
                          beam_size: int, eos_id: Optional[int] = None,
                          pad_id: int = 0, length_penalty: float = 0.0):
    """Beam search over the KV cache: ``beam(params, prompt) ->
    (tokens [B, max_new_tokens], scores [B])``.

    One jit program, like greedy generate: prefill once per batch row,
    repeat every cache leaf to B*beam rows, then a ``lax.scan`` whose
    carry holds (cache, running scores, per-beam token history).  Each
    step expands [B, K, V] candidates, takes the global top-K, and
    REORDERS the cache by gathering leaves with the parent-beam indices —
    XLA turns the gather into an on-device shuffle, no host round trips.
    Beams that emit ``eos_id`` freeze: their only continuation is
    ``pad_id`` at log-prob 0, so their score stops accumulating.

    ``length_penalty`` is GNMT-style alpha: final scores divide by
    ((5 + len) / 6) ** alpha where len counts tokens through EOS
    (0.0 = pure log-prob).  Returned scores are the penalized ones the
    winner was chosen by.
    """
    if beam_size < 1:
        raise ValueError("beam_size must be >= 1")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    model = Transformer(config)
    K, T = beam_size, max_new_tokens

    def penalize(scores, lengths):
        if length_penalty == 0.0:
            return scores
        return scores / (((5.0 + lengths) / 6.0) ** length_penalty)

    @jax.jit
    def beam(params, prompt):
        B, Lp = prompt.shape
        _check_cache_capacity(config, Lp, T)
        logits, varz = model.apply(
            {"params": params}, prompt, mode="prefill", mutable=["cache"])
        logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
        V = logp0.shape[-1]
        kk = min(K, V)
        scores, tok = jax.lax.top_k(logp0, kk)  # [B, kk]
        if kk < K:  # beam wider than vocab: pad with dead beams
            scores = jnp.pad(scores, ((0, 0), (0, K - kk)),
                             constant_values=-1e30)
            tok = jnp.pad(tok, ((0, 0), (0, K - kk)))
        # beam row layout: flat index b*K + k
        cache = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, K, axis=0), varz["cache"])
        finished = (tok == eos_id) if eos_id is not None \
            else jnp.zeros((B, K), bool)
        lengths = jnp.ones((B, K), jnp.int32)
        seqs = jnp.full((B, K, T), pad_id, jnp.int32)
        seqs = seqs.at[:, :, 0].set(tok)
        # a frozen beam may only continue with pad_id, at zero cost
        pad_only = jnp.full((V,), -1e30, jnp.float32).at[pad_id].set(0.0)

        def step(carry, t):
            cache, scores, finished, lengths, seqs, tok = carry
            logits, varz = model.apply(
                {"params": params, "cache": cache},
                tok.reshape(B * K, 1),
                positions=jnp.full((B * K, 1), Lp + t - 1, jnp.int32),
                mode="decode", mutable=["cache"])
            logp = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32)).reshape(B, K, V)
            logp = jnp.where(finished[..., None], pad_only, logp)
            cand = (scores[..., None] + logp).reshape(B, K * V)
            scores, idx = jax.lax.top_k(cand, K)  # [B, K]
            parent, tok = idx // V, (idx % V).astype(jnp.int32)
            flat_parent = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
            cache = jax.tree_util.tree_map(
                lambda x: x[flat_parent], varz["cache"])
            gather = lambda a: jnp.take_along_axis(a, parent, axis=1)  # noqa: E731
            finished = gather(finished)
            lengths = gather(lengths) + (~finished).astype(jnp.int32)
            seqs = jnp.take_along_axis(
                seqs, parent[..., None], axis=1).at[:, :, t].set(tok)
            if eos_id is not None:
                finished = finished | (tok == eos_id)
            return (cache, scores, finished, lengths, seqs, tok), None

        carry = (cache, scores, finished, lengths, seqs, tok)
        if T > 1:
            carry, _ = jax.lax.scan(step, carry, jnp.arange(1, T))
        _, scores, finished, lengths, seqs, _ = carry
        final = penalize(scores, lengths.astype(jnp.float32))
        best = jnp.argmax(final, axis=1)  # [B]
        out = jnp.take_along_axis(
            seqs, best[:, None, None], axis=1)[:, 0]
        return out, jnp.take_along_axis(final, best[:, None], axis=1)[:, 0]

    return beam


@functools.lru_cache(maxsize=8)
def _cached_generate_fn(config, max_new_tokens, temperature, top_k, eos_id,
                        pad_id):
    return make_generate_fn(config, max_new_tokens, temperature=temperature,
                            top_k=top_k, eos_id=eos_id, pad_id=pad_id)


def generate(config: TransformerConfig, params, prompt, max_new_tokens: int,
             rng=None, temperature: float = 0.0, top_k: Optional[int] = None,
             eos_id: Optional[int] = None, pad_id: int = 0):
    """One-shot convenience wrapper around :func:`make_generate_fn`.

    Caches the compiled function per sampling config (TransformerConfig is
    a frozen dataclass, so it is hashable) — repeated calls with the same
    shapes reuse the executable.

    NOTE: ``rng`` defaults to ``PRNGKey(0)``, so temperature-sampling
    calls that omit it are deterministic across invocations by design
    (reproducibility-first); pass a fresh key per call for fresh samples.
    """
    fn = _cached_generate_fn(config, max_new_tokens, temperature, top_k,
                             eos_id, pad_id)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return fn(params, jnp.asarray(prompt, jnp.int32), rng)

"""Autoregressive inference driver: jit-able prefill + KV-cached token loop.

The reference repo has no serving story at all (it is a training operator);
this is the framework's inference surface for the Transformer family, built
TPU-first:

- the whole generation — prefill, every decode step, and sampling — is one
  jit program: the token loop is a ``lax.scan`` with a static step count
  (no data-dependent Python control flow, one compile, static shapes);
- K/V caches live in the flax ``cache`` collection threaded through the
  scan carry as ordinary pytree state (transformer.Attention._decode_step);
- sliding-window configs decode from an O(window) ring-buffer cache, so
  long-context inference memory is bounded by the window, not the sequence;
- EOS is handled with a done-mask (finished rows emit ``pad_id`` and stop
  advancing), keeping the scan shape-static instead of early-exiting.

Reference parity note: the closest upstream artifact is the smoke
workload's inference-free matmul graph (tf_smoke); decode exists because a
complete LM framework needs it, not because the operator did.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from k8s_tpu.models.transformer import Transformer, TransformerConfig


def sample_logits(logits, rng, temperature: float = 0.0,
                  top_k: Optional[int] = None):
    """Sample next tokens from [B, V] logits.

    temperature == 0 is greedy argmax (rng unused); otherwise softmax
    sampling at the given temperature, optionally truncated to the top_k
    highest-probability tokens (mask, not gather — XLA-friendly and
    shape-static).
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        # clamp the large side: top_k >= vocab is a no-op filter, not a
        # trace-time shape error (serve_lm lets arbitrary --top_k through)
        kk = min(top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, kk)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _check_cache_capacity(config: TransformerConfig, prompt_len: int,
                          max_new_tokens: int) -> None:
    """Shared full-cache bound for greedy and beam decoding: the LAST
    sampled token is returned, never fed back, so the highest position
    written/attended is prompt_len + max_new_tokens - 2."""
    if config.window_size is None and \
            prompt_len + max_new_tokens - 1 > config.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds max_seq_len ({config.max_seq_len}) and no "
            "window_size is set (the full KV cache is max_seq_len "
            "long; sliding-window configs decode indefinitely)")


def make_generate_fn(config: TransformerConfig, max_new_tokens: int,
                     temperature: float = 0.0, top_k: Optional[int] = None,
                     eos_id: Optional[int] = None, pad_id: int = 0,
                     chunked_prefill: bool = False):
    """Build ``generate(params, prompt, rng) -> [B, max_new_tokens]``.

    The returned function is jit-compiled once per (config, prompt shape):
    prefill consumes the prompt and populates the caches, then a
    ``lax.scan`` of single-token steps carries ``(cache, token, position,
    done, rng)``.  Rows that emit ``eos_id`` are frozen to ``pad_id`` for
    the remaining steps.

    ``chunked_prefill``: instead of one full-length prefill pass, stream
    the prompt through the cache in ``config.prefill_chunk``-token chunks
    (a leading remainder chunk plus a ``lax.scan`` over the full ones).
    Prefill activation memory becomes O(chunk * cache) instead of
    O(prompt^2 / blocks), and with a sliding window the cache itself is
    O(window + chunk) — rolling prefill for arbitrarily long prompts.
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if chunked_prefill and config.prefill_chunk < 1:
        raise ValueError("chunked_prefill needs config.prefill_chunk >= 1")
    model = Transformer(config)

    def _chunked_prefill(params, prompt):
        """Stream the prompt through decode-mode cache calls; returns
        (cache, last-position logits)."""
        B, Lp = prompt.shape
        C = config.prefill_chunk
        first = Lp % C or min(C, Lp)  # leading remainder (or one chunk)
        pos0 = jnp.broadcast_to(jnp.arange(first), (B, first))
        logits, varz = model.apply(
            {"params": params}, prompt[:, :first], positions=pos0,
            mode="decode", mutable=["cache"])
        cache, last = varz["cache"], logits[:, -1]
        n_full = (Lp - first) // C
        if n_full == 0:
            return cache, last
        chunks = prompt[:, first:].reshape(B, n_full, C).transpose(1, 0, 2)
        bases = first + C * jnp.arange(n_full)

        # last logits ride the CARRY, not the scan outputs: stacking every
        # chunk's [B, V] logits would grow HBM with prompt length, exactly
        # what rolling prefill exists to avoid
        def body(carry, xs):
            cache, _ = carry
            chunk, base = xs
            pos = base + jnp.broadcast_to(jnp.arange(C), (B, C))
            logits, varz = model.apply(
                {"params": params, "cache": cache}, chunk, positions=pos,
                mode="decode", mutable=["cache"])
            return (varz["cache"], logits[:, -1]), None

        (cache, last), _ = jax.lax.scan(body, (cache, last),
                                        (chunks, bases))
        return cache, last

    @jax.jit
    def generate(params, prompt, rng):
        B, Lp = prompt.shape
        _check_cache_capacity(config, Lp, max_new_tokens)
        if chunked_prefill:
            cache, last = _chunked_prefill(params, prompt)
            varz = {"cache": cache}
        else:
            logits, varz = model.apply(
                {"params": params}, prompt, mode="prefill",
                mutable=["cache"])
            last = logits[:, -1]
        rng, sub = jax.random.split(rng)
        tok = sample_logits(last, sub, temperature, top_k)
        # EOS itself is emitted; rows freeze to pad_id from the NEXT step
        done = (tok == eos_id) if eos_id is not None \
            else jnp.zeros((B,), bool)

        def step(carry, _):
            cache, tok, pos, done, rng = carry
            logits, varz = model.apply(
                {"params": params, "cache": cache},
                tok[:, None], positions=pos[:, None], mode="decode",
                mutable=["cache"])
            rng, sub = jax.random.split(rng)
            nxt = sample_logits(logits[:, -1], sub, temperature, top_k)
            nxt = jnp.where(done, pad_id, nxt)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            return (varz["cache"], nxt, pos + 1, done, rng), nxt

        pos = jnp.full((B,), Lp, jnp.int32)
        carry = (varz["cache"], tok, pos, done, rng)
        if max_new_tokens == 1:
            return tok[:, None]
        _, rest = jax.lax.scan(step, carry, None, length=max_new_tokens - 1)
        return jnp.concatenate([tok[:, None], rest.T], axis=1)

    return generate


def make_beam_generate_fn(config: TransformerConfig, max_new_tokens: int,
                          beam_size: int, eos_id: Optional[int] = None,
                          pad_id: int = 0, length_penalty: float = 0.0):
    """Beam search over the KV cache: ``beam(params, prompt) ->
    (tokens [B, max_new_tokens], scores [B])``.

    One jit program, like greedy generate: prefill once per batch row,
    repeat every cache leaf to B*beam rows, then a ``lax.scan`` whose
    carry holds (cache, running scores, per-beam token history).  Each
    step expands [B, K, V] candidates, takes the global top-K, and
    REORDERS the cache by gathering leaves with the parent-beam indices —
    XLA turns the gather into an on-device shuffle, no host round trips.
    Beams that emit ``eos_id`` freeze: their only continuation is
    ``pad_id`` at log-prob 0, so their score stops accumulating.

    ``length_penalty`` is GNMT-style alpha: final scores divide by
    ((5 + len) / 6) ** alpha where len counts tokens through EOS
    (0.0 = pure log-prob).  Returned scores are the penalized ones the
    winner was chosen by.
    """
    if beam_size < 1:
        raise ValueError("beam_size must be >= 1")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    model = Transformer(config)
    K, T = beam_size, max_new_tokens

    def penalize(scores, lengths):
        if length_penalty == 0.0:
            return scores
        return scores / (((5.0 + lengths) / 6.0) ** length_penalty)

    @jax.jit
    def beam(params, prompt):
        B, Lp = prompt.shape
        _check_cache_capacity(config, Lp, T)
        logits, varz = model.apply(
            {"params": params}, prompt, mode="prefill", mutable=["cache"])
        logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
        V = logp0.shape[-1]
        kk = min(K, V)
        scores, tok = jax.lax.top_k(logp0, kk)  # [B, kk]
        if kk < K:  # beam wider than vocab: pad with dead beams
            scores = jnp.pad(scores, ((0, 0), (0, K - kk)),
                             constant_values=-1e30)
            tok = jnp.pad(tok, ((0, 0), (0, K - kk)))
        # beam row layout: flat index b*K + k
        cache = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, K, axis=0), varz["cache"])
        finished = (tok == eos_id) if eos_id is not None \
            else jnp.zeros((B, K), bool)
        lengths = jnp.ones((B, K), jnp.int32)
        seqs = jnp.full((B, K, T), pad_id, jnp.int32)
        seqs = seqs.at[:, :, 0].set(tok)
        # a frozen beam may only continue with pad_id, at zero cost
        pad_only = jnp.full((V,), -1e30, jnp.float32).at[pad_id].set(0.0)

        def step(carry, t):
            cache, scores, finished, lengths, seqs, tok = carry
            logits, varz = model.apply(
                {"params": params, "cache": cache},
                tok.reshape(B * K, 1),
                positions=jnp.full((B * K, 1), Lp + t - 1, jnp.int32),
                mode="decode", mutable=["cache"])
            logp = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32)).reshape(B, K, V)
            logp = jnp.where(finished[..., None], pad_only, logp)
            cand = (scores[..., None] + logp).reshape(B, K * V)
            scores, idx = jax.lax.top_k(cand, K)  # [B, K]
            parent, tok = idx // V, (idx % V).astype(jnp.int32)
            flat_parent = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
            cache = jax.tree_util.tree_map(
                lambda x: x[flat_parent], varz["cache"])
            gather = lambda a: jnp.take_along_axis(a, parent, axis=1)  # noqa: E731
            finished = gather(finished)
            lengths = gather(lengths) + (~finished).astype(jnp.int32)
            seqs = jnp.take_along_axis(
                seqs, parent[..., None], axis=1).at[:, :, t].set(tok)
            if eos_id is not None:
                finished = finished | (tok == eos_id)
            return (cache, scores, finished, lengths, seqs, tok), None

        carry = (cache, scores, finished, lengths, seqs, tok)
        if T > 1:
            carry, _ = jax.lax.scan(step, carry, jnp.arange(1, T))
        _, scores, finished, lengths, seqs, _ = carry
        final = penalize(scores, lengths.astype(jnp.float32))
        best = jnp.argmax(final, axis=1)  # [B]
        out = jnp.take_along_axis(
            seqs, best[:, None, None], axis=1)[:, 0]
        return out, jnp.take_along_axis(final, best[:, None], axis=1)[:, 0]

    return beam


@functools.lru_cache(maxsize=8)
def _cached_generate_fn(config, max_new_tokens, temperature, top_k, eos_id,
                        pad_id):
    return make_generate_fn(config, max_new_tokens, temperature=temperature,
                            top_k=top_k, eos_id=eos_id, pad_id=pad_id)


def generate(config: TransformerConfig, params, prompt, max_new_tokens: int,
             rng=None, temperature: float = 0.0, top_k: Optional[int] = None,
             eos_id: Optional[int] = None, pad_id: int = 0):
    """One-shot convenience wrapper around :func:`make_generate_fn`.

    Caches the compiled function per sampling config (TransformerConfig is
    a frozen dataclass, so it is hashable) — repeated calls with the same
    shapes reuse the executable.

    NOTE: ``rng`` defaults to ``PRNGKey(0)``, so temperature-sampling
    calls that omit it are deterministic across invocations by design
    (reproducibility-first); pass a fresh key per call for fresh samples.
    """
    fn = _cached_generate_fn(config, max_new_tokens, temperature, top_k,
                             eos_id, pad_id)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return fn(params, jnp.asarray(prompt, jnp.int32), rng)

"""HTTP inference server over a train_lm serving artifact.

The CLI loop (examples/train_lm/serve_lm.py) pays artifact load + jit
compile per invocation; a resident server pays them once and serves every
request from the warm jit cache — the practical half of the train→serve
story (`examples/tf_job_serve_http.yaml` runs this as the serving
TFJob's long-lived process; `tf_job_serve.yaml` is the one-shot batch
variant).

    python -m k8s_tpu.models.server --train_dir DIR --port 8000

Endpoints (JSON over HTTP/1.1, stdlib-only like the rest of the repo):

- ``GET /healthz`` → ``{"status": "ok", "model": {...}, "serving":
  {...}}`` — readiness for kubelet probes, including queue depth / slot
  occupancy.  Stays 200 while the admission queue is shedding: readiness
  is "can answer HTTP", not "not busy".
- ``GET /metrics`` → Prometheus text exposition (serve_requests_total,
  serve_queue_depth, serve_batch_occupancy, serve_tokens_total,
  serve_rejected_total, serve_request_duration_seconds).
- ``GET /debug/traces`` → recent prefill/decode_step span trees (the
  operator's responder, k8s_tpu.trace; 404 with an explicit body when
  K8S_TPU_TRACE_SAMPLE is 0).
- ``GET /debug/requests`` / ``GET /debug/engine`` → per-request serving
  timelines with dominant-phase attribution and the engine step ledger
  (ISSUE 12; the shared k8s_tpu.models.requestlog responders, 404 with
  an explicit body until ``K8S_TPU_REQUEST_LOG=1`` activates the
  recorder), plus ``GET /debug`` — the shared endpoint index.  An
  inbound W3C ``traceparent`` on POST /v1/generate parents the server
  and engine spans, joining caller → ingress → engine into one trace.
- ``POST /v1/generate`` with ``{"text": str | "tokens": [int], ...}`` →
  ``{"text": str | "tokens": [int]}``.  Optional fields:
  ``max_new_tokens`` (default from --max_new_tokens), ``temperature``,
  ``top_k``, ``eos``, ``seed``, ``speculative`` (draft_k, greedy-only).
  Bad input answers 400 with ``{"error": ..., "field": ...}`` naming the
  offending field; a full admission queue answers 503 with a
  ``Retry-After`` header.

Device work goes through the continuous-batching engine
(k8s_tpu.models.engine): greedy, sampled (``temperature > 0``, optional
``top_k``) AND speculative requests share one batched decode step over
K8S_TPU_SERVE_SLOTS slots with iteration-level join/retire, per-slot
RNG keys, and per-slot step widths (a speculative slot verifies its
draft chunk in the same call that advances its 1-token neighbors), so a
long generation no longer serializes short ones and the production
sampling/spec mix gets the batching speedup too — fixed-seed output is
token-identical across lanes for every request type.  The engine's
paged KV cache reuses shared prompt prefixes across requests (radix
tree, refcounted blocks, copy-on-write at the divergence block;
K8S_TPU_SERVE_PREFIX_BLOCKS sizes the retained pool, 0 disables reuse).
``K8S_TPU_SERVE_BATCH_SAMPLING=0`` (or ``--batch-sampling 0``) restores
the exclusive-lane routing for sampled requests;
``K8S_TPU_SERVE_BATCH_SPEC=0`` (or ``--batch-spec 0``) does the same
for speculative requests (they also ride the exclusive lane on
sliding-window configs, whose dense cache rows have no write-maskable
block pool).  ``--slots 0`` disables the engine entirely and restores
the original one-lock single-flight path (the bench_serve baseline).
Prompt-length compiles are bounded by the engine's bucket set instead
of unbounded per-prompt-length.

Multi-host serving (ISSUE 14, docs/serving.md "Multi-host serving"):
with ``K8S_TPU_SERVE_MESH=N`` every pod of an N-replica serving gang
runs THIS binary — the launcher env contract brings up
``jax.distributed``, replica 0 serves HTTP as the chief over a
``MeshPlacement`` (params tensor-sharded over ``K8S_TPU_SERVE_TP``,
KV block pool head-sharded per host), and the other replicas replay
the chief's per-step batch plan (models/mesh_serve.follower_loop),
exiting nonzero if the chief dies so the gang restarts whole.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import threading
from k8s_tpu.analysis import checkedlock
from k8s_tpu.analysis import compileledger
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

log = logging.getLogger(__name__)


class RequestError(ValueError):
    """400-class input error carrying the offending field name."""

    def __init__(self, field: str, msg: str):
        super().__init__(msg)
        self.field = field


@dataclasses.dataclass
class ParsedRequest:
    """A fully validated /v1/generate request — everything the device
    path needs, produced on the HTTP handler thread so no request
    parsing, tokenization, or validation ever runs inside the engine."""

    ids: "object"                      # np.ndarray [Lp] int32
    echo_text: Optional[str]           # original text, or None for tokens
    max_new_tokens: int
    temperature: float
    top_k: Optional[int]
    eos: Optional[int]
    seed: int
    speculative: int
    # disaggregated serving (ISSUE 15): the decode pod's kv-transfer
    # address (host:port) the router injected for a phase-split request;
    # None = serve locally (the collapsed path)
    kv_dest: Optional[str] = None
    # fleet prefix fetch-on-miss (ISSUE 17): the kv-transfer address of
    # a peer pod the fleet index says already caches this prompt's
    # prefix chain — this pod fetches the blocks from there instead of
    # re-prefilling them; best-effort (any failure falls back to a
    # normal prefill), None = no known holder
    kv_src: Optional[str] = None


def parse_request(config, req: dict, default_max_new_tokens: int
                  ) -> ParsedRequest:
    """Validate one request dict against the model config; raises
    :class:`RequestError` naming the offending field."""
    import numpy as np

    from k8s_tpu.models.dataset import encode_bytes

    has_text = isinstance(req.get("text"), str)
    has_tokens = isinstance(req.get("tokens"), list)
    if has_text == has_tokens:
        raise RequestError("text", 'give exactly one of "text" or "tokens"')
    field = "text" if has_text else "tokens"
    if has_text:
        ids = encode_bytes(req["text"]).astype(np.int32)
    else:
        try:
            ids = np.asarray([int(t) for t in req["tokens"]], np.int32)
        except (TypeError, ValueError):
            raise RequestError("tokens", '"tokens" must be a list of ints')
    if ids.size < 1:
        raise RequestError(field, "empty prompt")
    if ids.min(initial=0) < 0 or ids.max(initial=0) >= config.vocab_size:
        raise RequestError(
            field, f"token ids outside [0, {config.vocab_size})")

    def opt(key, default, cast):
        # JSON null means "not set" (use the default), like an absent
        # key; a non-castable value is the CLIENT's error -> 400
        val = req.get(key)
        if val is None:
            return default
        try:
            return cast(val)
        except (TypeError, ValueError):
            raise RequestError(key, f"bad {key!r}: {val!r}")

    max_new = opt("max_new_tokens", default_max_new_tokens, int)
    if not 1 <= max_new <= config.max_seq_len:
        raise RequestError(
            "max_new_tokens",
            f"max_new_tokens must be in [1, {config.max_seq_len}]")
    from k8s_tpu.models.decode import _check_cache_capacity

    try:
        # the ONE definition of the cache-capacity bound, surfaced here
        # as a client error before any device work
        _check_cache_capacity(config, int(ids.size), max_new)
    except ValueError as e:
        raise RequestError("max_new_tokens", str(e))
    temperature = opt("temperature", 0.0, float)
    if temperature < 0.0:
        raise RequestError("temperature", "temperature must be >= 0")
    top_k = opt("top_k", 0, int) or None
    if top_k is not None and top_k < 1:
        raise RequestError("top_k",
                           "top_k must be >= 1 (omit or 0 disables)")
    eos: Optional[int] = opt("eos", None, int)
    seed = opt("seed", 0, int)
    spec = opt("speculative", 0, int)
    if spec != 0 and spec < 2:
        raise RequestError("speculative",
                           "speculative must be >= 2 (0 disables)")
    def kv_addr(key):
        val = req.get(key)
        if val is None:
            return None
        from k8s_tpu.models import kvxfer

        if not isinstance(val, str):
            raise RequestError(key, f'"{key}" must be a string')
        try:
            kvxfer.parse_dest(val)
        except ValueError as e:
            raise RequestError(key, str(e))
        return val

    return ParsedRequest(
        ids=ids, echo_text=req["text"] if has_text else None,
        max_new_tokens=max_new, temperature=temperature, top_k=top_k,
        eos=eos, seed=seed, speculative=spec, kv_dest=kv_addr("kv_dest"),
        kv_src=kv_addr("kv_src"))


def _emitted(toks, eos) -> int:
    """Tokens actually emitted by a shape-static generation row: through
    the first EOS inclusive, excluding the frozen pad tail — the same
    definition the engine counts at retirement, so serve_tokens_total
    means one thing across lanes."""
    toks = list(toks)
    if eos is not None and eos in toks:
        return toks.index(eos) + 1
    return len(toks)


class LmServer:
    """Loads a serving artifact (or takes config+params directly) once;
    thread-safe generate() through the continuous-batching engine."""

    def __init__(self, train_dir: Optional[str] = None,
                 kv_cache: str = "model", param_dtype: str = "model",
                 default_max_new_tokens: int = 64, *,
                 config=None, params=None, slots: Optional[int] = None,
                 queue_limit: Optional[int] = None,
                 prefix_blocks: Optional[int] = None,
                 batch_sampling: Optional[bool] = None,
                 batch_spec: Optional[bool] = None, registry=None,
                 placement=None, role: Optional[str] = None,
                 kvxfer_port: Optional[int] = None,
                 kvxfer_int8: Optional[bool] = None,
                 spill_mb: Optional[int] = None,
                 kvxfer_dedup: Optional[bool] = None):
        from k8s_tpu.models import engine as engine_lib
        from k8s_tpu.models import kvxfer as kvxfer_lib
        from k8s_tpu.util import metrics as metrics_mod

        if train_dir is not None:
            from k8s_tpu.models import serving

            config, params = serving.load_for_serving(
                train_dir, kv_cache=kv_cache, param_dtype=param_dtype)
        elif config is None or params is None:
            raise ValueError("need train_dir or config+params")
        self.config = config
        self.params = params
        self.default_max_new_tokens = default_max_new_tokens
        self.registry = registry or metrics_mod.REGISTRY
        self.metrics = metrics_mod.serving_metrics(self.registry)
        # registry.register() returns the EXISTING metric on a name
        # collision, so rebind the gauge callable to THIS server (latest
        # wins) instead of baking it in at registration — a second
        # LmServer on the shared default registry must not report a
        # closed predecessor's queue forever (nor pin it against GC;
        # close() releases the binding)
        self.metrics["queue_depth"]._fn = self.queue_depth
        if slots is None:
            slots = engine_lib.env_slots()
        if batch_sampling is None:
            batch_sampling = engine_lib.env_batch_sampling()
        self.batch_sampling = bool(batch_sampling)
        if batch_spec is None:
            batch_spec = engine_lib.env_batch_spec()
        self.batch_spec = bool(batch_spec)
        if slots > 0:
            # placement seam (ISSUE 14): None = single-host LocalPlacement;
            # a MeshPlacement makes THIS server the chief of a
            # tensor-parallel serving gang (workers run
            # mesh_serve.follower_loop — python -m k8s_tpu.models.server
            # routes them there when K8S_TPU_SERVE_MESH is set)
            self.engine: Optional[engine_lib.Engine] = engine_lib.Engine(
                config, params, slots=slots, queue_limit=queue_limit,
                prefix_blocks=prefix_blocks, metrics=self.metrics,
                placement=placement, spill_mb=spill_mb)
        else:
            # legacy single-flight path: one lock around all device work
            # (kept as the bench_serve baseline and an escape hatch)
            self.engine = None
        self._lock = checkedlock.make_lock("server.singleflight")
        # disaggregated serving tier membership (ISSUE 15): a prefill
        # pod never seats migrated requests; a decode pod always runs a
        # kv-transfer receiver (ephemeral port when the env leaves it
        # unset — tests/benches read it back from serving_info)
        self.role = kvxfer_lib.env_role() if role is None else role
        if self.role not in ("", kvxfer_lib.ROLE_PREFILL,
                             kvxfer_lib.ROLE_DECODE):
            raise ValueError(f"role must be prefill/decode/'' "
                             f"(got {self.role!r})")
        self.kvxfer_int8 = kvxfer_lib.env_kvxfer_int8() \
            if kvxfer_int8 is None else bool(kvxfer_int8)
        # migration dedup (ISSUE 17): default on — a dedup-off peer
        # interoperates through the legacy-fallback handshake either way
        self.kvxfer_dedup = kvxfer_lib.env_kvxfer_dedup() \
            if kvxfer_dedup is None else bool(kvxfer_dedup)
        if kvxfer_port is None:
            kvxfer_port = kvxfer_lib.env_kvxfer_port()
        self._kv_receiver = None
        self._kv_sender = None
        if self.engine is not None and self.engine.disagg_capable:
            if self.role != kvxfer_lib.ROLE_PREFILL and (
                    kvxfer_port is not None
                    or self.role == kvxfer_lib.ROLE_DECODE):
                self._kv_receiver = kvxfer_lib.KvReceiver(
                    self._seat_migrated, host="0.0.0.0",
                    port=kvxfer_port or 0,
                    index_fn=(self.engine.dedup_have
                              if self.kvxfer_dedup else None),
                    fetch_fn=self._serve_fetch)
            if self.role != kvxfer_lib.ROLE_DECODE:
                self._kv_sender = kvxfer_lib.KvSender()
        # fleet prefix cache index (ISSUE 17): advertise resident chain
        # fingerprints (tree + spill) as a labeled gauge family the
        # fleet plane already scrapes/aggregates — the router's index
        # lookup reads them back per pod.  Same rebind-don't-rebake
        # contract as queue_depth: the registry dedupes by name, so the
        # proxy's sample_fn is rebound to THIS server (latest wins) and
        # close() releases the binding.
        proxy = self.registry.register(metrics_mod.ProxyMetric(
            "serve_kv_prefix_cached",
            "Chain fingerprints this pod can serve by reference or "
            "re-promote (radix tree + host spill tier), one labeled "
            "sample per fingerprint.", "gauge", None))
        proxy._sample_fn = self._sample_prefix_index
        self._prefix_index_proxy = proxy
        # compile ledger (ISSUE 11): the exclusive lane's whole-generation
        # programs are the server's own compile surface — one program per
        # (generation config, prompt length), bounded by the decode-module
        # lru tables.  The engine declares its own seams at construction.
        self._ledger = compileledger.maybe_active()
        self._seam_whole_gen = None
        if self._ledger is not None:
            try:
                from jax import monitoring as _monitoring
            except Exception:  # noqa: BLE001 - older jax: wrap fallback covers it
                _monitoring = None
            compileledger.ensure_listener(_monitoring)
            from k8s_tpu.models import decode as decode_lib

            bound = ((decode_lib._cached_generate_fn.cache_info().maxsize
                      or 8)
                     + (decode_lib.cached_speculative_fn.cache_info()
                        .maxsize or 32))
            self._seam_whole_gen = self._ledger.declare(
                "server.whole_gen", bound,
                note="exclusive-lane whole-generation programs, bounded "
                "by the decode-module lru tables "
                "(_cached_generate_fn + cached_speculative_fn)")

    def _sample_prefix_index(self, name: str):
        """Exposition lines for the fleet prefix cache index family
        (ProxyMetric sample_fn): one ``{fp="…"} 1`` gauge sample per
        advertised chain fingerprint; nothing with no paged engine."""
        if self.engine is None or not self.engine.paged:
            return
        for fp in self.engine.prefix_index():
            yield f'{name}{{fp="{fp}"}} 1'

    def close(self) -> None:
        if self.metrics["queue_depth"]._fn == self.queue_depth:
            self.metrics["queue_depth"]._fn = None
        if getattr(self._prefix_index_proxy, "_sample_fn", None) \
                == self._sample_prefix_index:
            self._prefix_index_proxy._sample_fn = None
        if self._kv_receiver is not None:
            self._kv_receiver.stop()
        if self._kv_sender is not None:
            self._kv_sender.close()
        if self.engine is not None:
            self.engine.shutdown()

    def queue_depth(self) -> int:
        return self.engine.queue_depth() if self.engine is not None else 0

    def compile_seams(self) -> list:
        """Every seam this server answers for: the engine's program
        inventory plus the exclusive lane's whole-generation table."""
        seams = list(self.engine.compile_seams()) \
            if self.engine is not None else []
        if self._seam_whole_gen is not None:
            seams.append(self._seam_whole_gen)
        return seams

    def compile_audit(self) -> Optional[dict]:
        """Per-seam compile-budget audit for this server (None when the
        ledger is off) — the bench phases' assertion payload."""
        if self._ledger is None:
            return None
        return self._ledger.seam_audit(self.compile_seams())

    def model_info(self) -> dict:
        c = self.config
        return {"layers": c.layers, "hidden": c.hidden,
                "vocab_size": c.vocab_size, "max_seq_len": c.max_seq_len,
                "kv_cache_dtype": c.kv_cache_dtype}

    def serving_info(self) -> dict:
        """Engine occupancy for /healthz (shedding is NOT unreadiness)."""
        if self.engine is None:
            return {"engine": "single-flight", "slots": 0,
                    "queue_depth": 0, "role": self.role}
        s = self.engine.stats()
        return {"engine": "continuous-batching", "slots": s["slots"],
                # mesh identity (ISSUE 14): the fleet plane and
                # /debug/engine can tell a tensor-sharded multi-process
                # pod from a single-host one
                "placement": s["placement"],
                "num_processes": s["num_processes"],
                "mesh_shape": s["mesh_shape"],
                "tp_degree": s["tp_degree"],
                "active": s["active"], "queue_depth": s["queue_depth"],
                "queue_limit": s["queue_limit"],
                "batch_sampling": self.batch_sampling,
                "batch_spec": self.batch_spec,
                "paged": s["paged"], "block_size": s["block_size"],
                "pool_blocks": s["pool_blocks"],
                "blocks_in_use": s["blocks_in_use"],
                "prefix_hits": s["prefix_hits"],
                "prefix_tokens_saved": s["prefix_tokens_saved"],
                # speculative drafting efficiency (ISSUE 9): proposed /
                # accepted draft tokens and the mean accepted per verify
                # step, so the fleet plane can rate acceptance per job
                "spec_proposed": s["spec_proposed"],
                "spec_accepted": s["spec_accepted"],
                "spec_mean_accepted": s["spec_mean_accepted"],
                # per-request recorder binding (ISSUE 12)
                "request_log": s["request_log"],
                # disaggregated tier surface (ISSUE 15): role, the
                # kv-transfer listener (decode pods; tests/benches read
                # the ephemeral port back from here), and the migration
                # counters the bench rates blocks/s from
                "role": self.role,
                "kvxfer_port": self._kv_receiver.port
                if self._kv_receiver is not None else None,
                "kvxfer_int8": self.kvxfer_int8,
                "kv_exports": s["kv_exports"],
                "kv_imports": s["kv_imports"],
                "kv_blocks_out": s["kv_blocks_out"],
                "kv_blocks_in": s["kv_blocks_in"],
                # tiered KV hierarchy (ISSUE 17): host spill tier
                # occupancy, dedup savings, and fleet fetch imports
                "kvxfer_dedup": self.kvxfer_dedup,
                "spill_enabled": s["spill_enabled"],
                "spill_blocks": s["spill_blocks"],
                "spill_bytes": s["spill_bytes"],
                "spill_demotions": s["spill_demotions"],
                "spill_promotions": s["spill_promotions"],
                "spill_evictions": s["spill_evictions"],
                "kv_blocks_deduped": s["kv_blocks_deduped"],
                "kv_prefix_fetched": s["kv_prefix_fetched"]}

    # -- disaggregated prefill/decode (ISSUE 15) -----------------------

    def _wire_blocks(self, export: dict) -> tuple[dict, bool]:
        """The export manifest's block arrays as wire arrays: int8 pools
        ship their native leaves bit-exact; fp pools optionally
        quantize k/v content for transit through THE quantize_kv
        definition (``K8S_TPU_KVXFER_INT8`` — lossy, 4x less wire)."""
        import numpy as np

        blocks = export["blocks"]
        if not self.kvxfer_int8:
            return ({f"blk/{p}": a for p, a in blocks.items()}, False)
        from k8s_tpu.models.paged import quantize_kv

        out: dict = {}
        quantized = False
        for path, arr in blocks.items():
            leaf = path.rsplit("/", 1)[-1]
            if leaf in ("k", "v") and np.issubdtype(arr.dtype,
                                                    np.floating):
                q, scale = quantize_kv(arr)
                out[f"blk/{path}"] = np.asarray(q)
                out[f"blkscale/{path}"] = np.asarray(scale)
                quantized = True
            else:
                out[f"blk/{path}"] = arr
        return out, quantized

    @staticmethod
    def _unwire_blocks(arrays: dict, wire_int8: bool) -> dict:
        """Receiver-side inverse of :meth:`_wire_blocks`: dequantize
        wire-int8 content back to f32 (the engine's graft casts to the
        pool dtype); bit-exact passthrough otherwise."""
        import numpy as np

        out: dict = {}
        for name, arr in arrays.items():
            if not name.startswith("blk/"):
                continue
            path = name[len("blk/"):]
            scale = arrays.get(f"blkscale/{path}")
            if wire_int8 and scale is not None:
                out[path] = (arr.astype(np.float32)
                             * scale[..., None].astype(np.float32))
            else:
                out[path] = arr
        return out

    def _serve_fetch(self, statics: dict, arrays: dict
                     ) -> Optional[tuple[dict, dict]]:
        """The kv-receiver's fetch seam (ISSUE 17): serve a peer's
        fetch-on-miss request from this pod's cached prefix chain
        (tree blocks + spill payloads), wire-encoded exactly like a
        migration export; None = nothing cached (the peer re-prefills)."""
        import numpy as np

        from k8s_tpu.models import kvxfer as kvxfer_lib

        if self.engine is None or not self.engine.paged:
            return None
        manifest = self.engine.fetch_prefix(
            np.asarray(arrays["ids"], np.int32))
        if manifest is None or not manifest["n_blocks"]:
            return None
        wire, quantized = self._wire_blocks(manifest)
        return ({"v": kvxfer_lib.PROTOCOL_VERSION,
                 "wire_int8": quantized,
                 "n_blocks": manifest["n_blocks"],
                 "block_size": manifest["block_size"]}, wire)

    def _fetch_on_miss(self, parsed: ParsedRequest,
                       trace_ctx: Optional[tuple]) -> int:
        """Requester side of fleet fetch-on-miss (ISSUE 17): pull the
        prompt's cached prefix chain from ``parsed.kv_src`` (the holder
        the router's index lookup named) and graft it locally, so the
        submit right after attaches it as an ordinary tree hit.
        Best-effort end to end: any shortfall or transport failure
        returns 0 and the request simply re-prefills."""
        import numpy as np

        from k8s_tpu import trace
        from k8s_tpu.models import kvtier
        from k8s_tpu.models import kvxfer as kvxfer_lib

        engine = self.engine
        bs = engine.block_size
        fps = kvtier.chain_fingerprints(
            parsed.ids, bs, max_blocks=(int(parsed.ids.size) - 1) // bs)
        if not fps or engine.dedup_have(fps) >= len(fps):
            return 0  # nothing fetchable, or already cached locally
        try:
            with trace.span_under(trace_ctx, "kv_fetch",
                                  src=parsed.kv_src):
                statics, arrays = self._kv_sender.fetch(
                    parsed.kv_src, {"v": kvxfer_lib.PROTOCOL_VERSION},
                    {"ids": np.asarray(parsed.ids, np.int32)})
            n = int(statics.get("n_blocks") or 0)
            if n <= 0:
                return 0
            blocks = self._unwire_blocks(arrays,
                                         bool(statics.get("wire_int8")))
            return engine.import_prefix(parsed.ids, blocks, n)
        except Exception as e:  # noqa: BLE001 - fetch is an optimization, never an error
            log.debug("kv fetch-on-miss from %s failed: %s",
                      parsed.kv_src, e)
            return 0

    def _seat_migrated(self, statics: dict, arrays: dict,
                       on_seated) -> list[int]:
        """The kv-receiver's seam onto the engine: rebuild the flat
        block manifest from the wire and seat the request; typed
        refusals (PoolExhausted / QueueFull / ValueError / DedupStale)
        travel back to the sender as error frames."""
        import numpy as np

        req = statics.get("req") or {}
        blocks = self._unwire_blocks(arrays,
                                     bool(statics.get("wire_int8")))
        return self.engine.submit_prefilled(
            np.asarray(arrays["ids"], np.int32), blocks,
            skip=int(statics.get("skip") or 0),
            first_token=int(req["first"]),
            key=np.asarray(arrays["key"], np.uint32),
            max_new_tokens=int(req["max_new_tokens"]),
            eos_id=req.get("eos"),
            temperature=float(req.get("temperature") or 0.0),
            top_k=req.get("top_k"),
            speculative=int(req.get("speculative") or 0),
            block_size=req.get("block_size"),
            trace_id=statics.get("trace_id"),
            seated=on_seated)

    def _generate_disagg(self, parsed: ParsedRequest,
                         trace_ctx: Optional[tuple]) -> "object":
        """The phase-split path: prefill-only locally (no decode slot
        held), stream the block chain to ``parsed.kv_dest``, and return
        the decode pod's token list.  The transfer span joins the
        caller trace; the request timeline closes with the ``migrate``
        phase billed."""
        from k8s_tpu import trace
        from k8s_tpu.models import kvxfer as kvxfer_lib
        from k8s_tpu.models import requestlog

        export = self.engine.prefill_export(
            parsed.ids, parsed.max_new_tokens, eos_id=parsed.eos,
            temperature=parsed.temperature, top_k=parsed.top_k,
            seed=parsed.seed, speculative=parsed.speculative,
            trace_ctx=trace_ctx)
        if export["done"]:
            return export["tokens"]
        rid = export.get("rid")
        rlog = requestlog.active()
        try:
            wire, quantized = self._wire_blocks(export)
            wire["ids"] = export["ids"]
            wire["key"] = export["key"]
            statics = {
                "v": kvxfer_lib.PROTOCOL_VERSION,
                "wire_int8": quantized,
                "trace_id": trace_ctx[0] if trace_ctx else None,
                "req": {
                    "first": export["first"],
                    "max_new_tokens": parsed.max_new_tokens,
                    "eos": parsed.eos,
                    "temperature": parsed.temperature,
                    "top_k": parsed.top_k,
                    "speculative": parsed.speculative,
                    "block_size": export["block_size"],
                },
            }
            # migration dedup (ISSUE 17): offer the chain's cumulative
            # block fingerprints so the receiver can claim blocks its
            # tree/spill already holds and the wire ships only the rest
            fps = None
            info: dict = {}
            if self.kvxfer_dedup:
                from k8s_tpu.models import kvtier

                ids = export["ids"]
                # offer only blocks the receiver may legally skip: the
                # last prompt token's block is never tree-shareable
                fps = kvtier.chain_fingerprints(
                    ids, export["block_size"],
                    max_blocks=(len(ids) - 1) // export["block_size"])
            with trace.span_under(trace_ctx, "kv_migrate",
                                  dest=parsed.kv_dest,
                                  blocks=export["n_blocks"],
                                  wire_int8=quantized):
                tokens, seated_s = self._kv_sender.migrate(
                    parsed.kv_dest, statics, wire, fingerprints=fps,
                    info=info)
            skipped = int(info.get("skipped_blocks") or 0)
            if skipped:
                ded = self.metrics.get("kvxfer_dedup_skipped")
                if ded is not None:
                    ded.inc(skipped)
            h = self.metrics.get("kv_migrate")
            if h is not None:
                h.observe(seated_s)
            if rlog is not None:
                rlog.migrate_send(rid, export["n_blocks"] - skipped,
                                  seated_s, dest=parsed.kv_dest)
                rlog.retire(rid, "migrated", tokens=len(tokens))
            return tokens
        except BaseException:
            # the export timeline must not leak live on a failed hop
            if rlog is not None:
                rlog.retire(rid, "error")
            raise

    def generate(self, parsed: ParsedRequest,
                 trace_ctx: Optional[tuple] = None) -> dict:
        """One validated generation request (parse_request ran on the
        handler thread).  May raise engine.QueueFull under backpressure.

        ``trace_ctx`` is the ``(trace_id, span_id, sampled)`` context the
        HTTP ingress extracted from the inbound W3C ``traceparent`` (or
        minted for its own server span): the engine parents its
        prefill/exclusive spans under it across the thread hop, so one
        trace spans caller -> ingress -> engine (ISSUE 12)."""
        import numpy as np

        from k8s_tpu.models.dataset import decode_bytes
        from k8s_tpu.models.serving import strip_after_eos

        # lane routing: sampled requests ride the batch unless the
        # batch_sampling knob routes them exclusively; speculative
        # requests ride the batch unless batch_spec routes them
        # exclusively OR the engine has no paged pool to write-mask
        # (windowed configs) OR the prompt is too short to draft from
        # (the exclusive lane rejects that at trace time — same 400,
        # one lane).  Either routing emits identical tokens at a fixed
        # seed; only throughput differs.
        spec_batched = (parsed.speculative > 0 and self.batch_spec
                        and self.engine is not None and self.engine.paged
                        and parsed.ids.size >= 2)
        use_batched = (parsed.speculative == 0 or spec_batched) and (
            parsed.temperature == 0.0 or self.batch_sampling)
        if parsed.kv_src and not parsed.kv_dest \
                and self._kv_sender is not None \
                and self.engine is not None and self.engine.paged:
            # fleet fetch-on-miss (ISSUE 17): the router's index lookup
            # named a peer that caches this prompt's prefix chain —
            # pull it over the kvxfer plane and graft it locally before
            # submitting, so the prefill attaches it as a tree hit.
            # Best-effort: any failure just re-prefills.
            self._fetch_on_miss(parsed, trace_ctx)
        if parsed.kv_dest and self._kv_sender is not None \
                and self.engine is not None and self.engine.paged \
                and use_batched:
            # disaggregated phase split (ISSUE 15): prefill here, decode
            # on the kv_dest peer.  A kv_dest landing on a pod that
            # cannot send (decode role, windowed engine) — or a request
            # the lane-routing knobs route EXCLUSIVELY (batch_sampling /
            # batch_spec off: migration only seats batched lanes, and
            # the operator's routing policy outranks the router's phase
            # split) — falls through and serves locally, never a 500.
            toks = np.asarray(self._generate_disagg(parsed, trace_ctx))
        elif self.engine is not None and use_batched:
            toks = self.engine.submit(parsed.ids, parsed.max_new_tokens,
                                      eos_id=parsed.eos,
                                      temperature=parsed.temperature,
                                      top_k=parsed.top_k,
                                      seed=parsed.seed,
                                      speculative=parsed.speculative,
                                      trace_ctx=trace_ctx)
        elif self.engine is not None:
            toks = np.asarray(self.engine.submit_exclusive(
                lambda: self._generate_exclusive(parsed),
                trace_ctx=trace_ctx))
            self.metrics["tokens"].inc(_emitted(toks, parsed.eos))
        else:
            # jit dispatch is async: a dispatch-only lock would pipeline
            # the device queue and this baseline would stop measuring
            # single-flight at all
            with self._lock:
                # sync-ok: the legacy single-flight BASELINE deliberately
                # syncs under its lock — serialized device work is its definition
                toks = np.asarray(self._generate_exclusive(parsed))
            self.metrics["tokens"].inc(_emitted(toks, parsed.eos))
        toks = strip_after_eos(np.asarray(toks), parsed.eos)
        if parsed.echo_text is not None:
            return {"text": parsed.echo_text
                    + decode_bytes(np.asarray(toks))}
        return {"tokens": [int(t) for t in toks]}

    def _whole_gen_programs(self) -> int:
        """Whole-generation builder constructions so far in the decode
        module's lru tables (the exclusive lane's program inventory).
        ``misses`` rather than ``currsize``: once a process-global table
        fills to maxsize a fresh config EVICTS instead of growing, and
        an evicted-then-reused config really does rebuild (and retrace)
        its program — both are compiles the ledger must see."""
        from k8s_tpu.models import decode as decode_lib

        return (decode_lib._cached_generate_fn.cache_info().misses
                + decode_lib.cached_speculative_fn.cache_info().misses)

    def _generate_exclusive(self, parsed: ParsedRequest):
        """The pre-engine device path (sampling / speculative / legacy
        single-flight): one whole-generation program per shape.

        Returns the DEVICE row so the caller chooses where to pay the
        host transfer: the engine's exclusive lane materializes OUTSIDE
        the lane (holding it across the transfer would stall every
        batched slot for nothing), while the legacy single-flight path
        deliberately syncs under its lock — that serialization is the
        baseline's definition."""
        import jax
        import jax.numpy as jnp

        from k8s_tpu.models import decode as decode_lib

        ledger, seam = self._ledger, self._seam_whole_gen
        before = self._whole_gen_programs() if ledger is not None else 0
        t0 = time.perf_counter()
        prompt = jnp.asarray(parsed.ids)[None, :]
        if parsed.speculative > 0:
            # temperature/top_k compose via rejection sampling: the
            # emitted tokens are distributed exactly as vanilla
            # temperature/top-k sampling
            fn = decode_lib.cached_speculative_fn(
                self.config, parsed.max_new_tokens,
                draft_k=parsed.speculative, eos_id=parsed.eos,
                temperature=parsed.temperature,
                top_k=parsed.top_k if parsed.temperature > 0 else None)
            out = fn(self.params, prompt, jax.random.PRNGKey(parsed.seed))
        else:
            out = decode_lib.generate(
                self.config, self.params, prompt, parsed.max_new_tokens,
                rng=jax.random.PRNGKey(parsed.seed),
                temperature=parsed.temperature, top_k=parsed.top_k,
                eos_id=parsed.eos)
        if ledger is not None and self._whole_gen_programs() > before:
            # a fresh whole-generation builder was constructed for this
            # request's generation config: one distinct program, keyed
            # by everything that selects it (prompt shape included)
            ledger.record(seam, compileledger.fingerprint(
                "whole_gen", (), {
                    "prompt_len": int(parsed.ids.size),
                    "max_new": parsed.max_new_tokens,
                    "draft_k": parsed.speculative,
                    "temperature": parsed.temperature,
                    "top_k": parsed.top_k, "eos": parsed.eos},
                static_argnames=("prompt_len", "max_new", "draft_k",
                                 "temperature", "top_k", "eos")),
                time.perf_counter() - t0,
                compileledger.caller_stack())
        return out[0]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "k8s-tpu-lm"
    # one TCP segment per response: fully buffer writes (flushed once per
    # request by handle_one_request) and disable Nagle.  With the default
    # unbuffered wfile, the header write and the body write leave as two
    # small segments; Nagle holds the second until the first is ACKed and
    # the client's delayed ACK waits on more data — a 40-200ms stall per
    # response on every keep-alive connection.
    wbufsize = -1
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        log.debug("server: " + fmt, *args)

    def _send(self, code: int, obj: dict, headers: Optional[dict] = None
              ) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            lm = self.server.lm
            # busy (shedding) is still ready; a CRASHED engine is not —
            # 503 here makes the kubelet recycle the pod instead of
            # routing to a process that 500s every generate
            dead = lm.engine is not None and not lm.engine.healthy
            return self._send(503 if dead else 200,
                              {"status": "engine crashed" if dead
                               else "ok",
                               "model": lm.model_info(),
                               "serving": lm.serving_info()})
        if path == "/metrics":
            try:
                body = self.server.lm.registry.expose()
            except Exception as e:  # noqa: BLE001 - broken collector
                return self._send_text(500, f"scrape failed: {e}\n",
                                       "text/plain")
            return self._send_text(
                200, body, "text/plain; version=0.0.4; charset=utf-8")
        if path == "/debug/traces":
            from k8s_tpu import trace

            code, body, ctype = trace.debug_traces_response(
                trace.TRACER, query)
            return self._send_text(code, body, ctype)
        if path == "/debug/compiles":
            # XLA compile ledger: per-seam budgets + fingerprints (the
            # SAME shared responder the metrics server and dashboard
            # route to; 404 with an explicit body while the ledger is
            # off — /debug/traces parity)
            code, body, ctype = compileledger.debug_compiles_response(
                query)
            return self._send_text(code, body, ctype)
        if path == "/debug/requests":
            # request lifecycle recorder (ISSUE 12): per-request serving
            # timelines with dominant-phase attribution (?id=/?slow=/
            # ?phase=/?n=; 404 with an explicit body until
            # K8S_TPU_REQUEST_LOG activates a recorder)
            from k8s_tpu.models import requestlog

            code, body, ctype = requestlog.debug_requests_response(query)
            return self._send_text(code, body, ctype)
        if path == "/debug/engine":
            # engine step ledger: per-iteration occupancy/width/tokens/
            # wall-time records + windowed rollups (same 404 contract)
            from k8s_tpu.models import requestlog

            code, body, ctype = requestlog.debug_engine_response(query)
            return self._send_text(code, body, ctype)
        if path in ("/debug", "/debug/"):
            # the shared debug index (what is servable right now)
            from k8s_tpu.util.debug_index import debug_index_response

            code, body, ctype = debug_index_response(query)
            return self._send_text(code, body, ctype)
        return self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        # ALWAYS drain the declared body first: replying on a keep-alive
        # connection with unread bytes leaves them to be parsed as the
        # next request line, 400-ing every later request on the socket
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self.close_connection = True  # unknown body size: can't drain
            return self._send(400, {"error": "bad Content-Length"})
        raw = self.rfile.read(length) if length > 0 else b""
        if self.path != "/v1/generate":
            return self._send(404, {"error": f"unknown path {self.path}"})
        lm = self.server.lm
        m = lm.metrics
        try:
            req = json.loads(raw or b"{}")
            if not isinstance(req, dict):
                raise ValueError("request body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            m["requests"].labels("bad_request").inc()
            return self._send(400, {"error": f"bad request body: {e}"})
        # parse/validate ENTIRELY on the handler thread: the engine only
        # ever sees token arrays and validated knobs
        try:
            parsed = parse_request(lm.config, req,
                                   lm.default_max_new_tokens)
        except RequestError as e:
            m["requests"].labels("bad_request").inc()
            return self._send(400, {"error": str(e), "field": e.field})
        from k8s_tpu import trace
        from k8s_tpu.models.engine import QueueFull
        from k8s_tpu.models.kvxfer import KvTransferError

        # end-to-end trace join (ISSUE 12): the inbound W3C traceparent
        # (the operator-side propagation machinery emits it) parents this
        # request's server span, and the engine's prefill/exclusive spans
        # parent under THAT across the thread hop — one trace per request
        # across processes.  With tracing off the recorder still keeps
        # the inbound trace id on the timeline, so the join survives.
        inbound = trace.parse_traceparent(self.headers.get("traceparent"))
        start = time.monotonic()
        try:
            with trace.span_under(inbound, "serve_request",
                                  prompt_len=int(parsed.ids.size),
                                  max_new=parsed.max_new_tokens) as sspan:
                ctx = trace.span_context(sspan) or inbound
                out = lm.generate(parsed, trace_ctx=ctx)
        except QueueFull as e:
            # backpressure: shed with an explicit retry hint; /healthz
            # stays 200 (the serve_rejected_total counter is incremented
            # by the engine at the rejection site)
            m["requests"].labels("rejected").inc()
            return self._send(
                503, {"error": str(e)},
                headers={"Retry-After":
                         str(max(1, int(round(e.retry_after_s))))})
        except KvTransferError as e:
            # receive-side backpressure (pool exhausted / queue full on
            # the decode peer) is a shed, not an error — the router's
            # retry walk re-places the request; anything else (dead
            # peer, protocol) is a 502-class failure the router also
            # walks past
            if e.kind in ("pool_exhausted", "queue_full"):
                m["requests"].labels("rejected").inc()
                return self._send(503, {"error": str(e)},
                                  headers={"Retry-After": "1"})
            log.warning("kv migration failed: %s", e)
            m["requests"].labels("error").inc()
            return self._send(502, {"error": f"kv migration: {e}"})
        except ValueError as e:
            m["requests"].labels("bad_request").inc()
            return self._send(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 - surface, don't kill the worker
            log.exception("generate failed")
            m["requests"].labels("error").inc()
            return self._send(500, {"error": f"{type(e).__name__}: {e}"})
        m["requests"].labels("ok").inc()
        m["duration"].observe(time.monotonic() - start)
        return self._send(200, out)


def serve(lm: LmServer, host: str = "127.0.0.1", port: int = 0):
    """Returns a started ThreadingHTTPServer (caller owns shutdown())."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.lm = lm  # type: ignore[attr-defined]
    t = threading.Thread(target=httpd.serve_forever,
                         kwargs={"poll_interval": 0.1}, daemon=True,
                         name="lm-server")
    t.start()
    return httpd


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train_dir", required=True)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default loopback; set 0.0.0.0 "
                   "explicitly for pod exposure)")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--max_new_tokens", type=int, default=64,
                   help="per-request default")
    p.add_argument("--kv_cache", choices=["model", "int8"], default="model")
    p.add_argument("--param_dtype", choices=["model", "bfloat16"],
                   default="model")
    p.add_argument("--slots", type=int, default=None,
                   help="continuous-batching decode slots (default "
                   "K8S_TPU_SERVE_SLOTS or 4; 0 = legacy single-flight)")
    p.add_argument("--queue", type=int, default=None,
                   help="admission queue bound before 503 shedding "
                   "(default K8S_TPU_SERVE_QUEUE or 64)")
    p.add_argument("--prefix-blocks", type=int, default=None,
                   help="KV pool blocks retained for shared-prefix reuse "
                   "beyond the per-slot floor (default "
                   "K8S_TPU_SERVE_PREFIX_BLOCKS or auto; 0 disables "
                   "prefix reuse)")
    p.add_argument("--batch-sampling", type=int, choices=(0, 1),
                   default=None,
                   help="route temperature>0 requests onto the batched "
                   "slot lanes (default K8S_TPU_SERVE_BATCH_SAMPLING or "
                   "1; 0 = exclusive-lane sampling, the legacy routing)")
    p.add_argument("--batch-spec", type=int, choices=(0, 1),
                   default=None,
                   help="route speculative requests onto the batched "
                   "slot lanes (variable-width verify chunks; default "
                   "K8S_TPU_SERVE_BATCH_SPEC or 1; 0 = exclusive-lane "
                   "speculation, the legacy routing)")
    p.add_argument("--role", choices=("prefill", "decode"), default=None,
                   help="disaggregated tier membership (default "
                   "K8S_TPU_SERVE_ROLE; unset = collapsed single-role "
                   "pod serving both phases)")
    p.add_argument("--kvxfer-port", type=int, default=None,
                   help="KV block-transfer listener port on decode-"
                   "capable pods (default K8S_TPU_KVXFER_PORT; 0 = "
                   "ephemeral; decode-role pods always listen)")
    p.add_argument("--kvxfer-int8", type=int, choices=(0, 1),
                   default=None,
                   help="quantize fp-pool KV content to int8 for "
                   "transit (default K8S_TPU_KVXFER_INT8 or 0; lossy "
                   "on fp pools, no-op on int8 pools)")
    p.add_argument("--spill-mb", type=int, default=None,
                   help="host-RAM KV spill tier budget in MiB: evicted "
                   "prefix-tree leaves demote to quantized host buffers "
                   "and re-promote on the next hit instead of "
                   "re-prefilling (default K8S_TPU_SERVE_SPILL_MB or 0 "
                   "= off)")
    p.add_argument("--kvxfer-dedup", type=int, choices=(0, 1),
                   default=None,
                   help="fingerprint-dedup the kv migration wire: skip "
                   "blocks the receiver already holds in-tree or "
                   "in-spill (default K8S_TPU_KVXFER_DEDUP or 1; "
                   "legacy peers interoperate either way)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    from k8s_tpu.models import placement as placement_lib

    placement = None
    mesh_kw: dict = {"train_dir": args.train_dir}
    if placement_lib.env_mesh() > 0:
        # multi-host serving gang (ISSUE 14): every pod of the serving
        # TFJob runs THIS binary; the launcher env contract brings up
        # jax.distributed, workers replay the chief's batch plan, and
        # the chief serves HTTP over the mesh placement.  Every process
        # loads the same artifact, so no parameter broadcast is needed.
        from k8s_tpu.launcher import bootstrap
        from k8s_tpu.models import mesh_serve
        from k8s_tpu.models import serving as serving_lib

        lcfg = bootstrap.initialize_distributed()
        config, params = serving_lib.load_for_serving(
            args.train_dir, kv_cache=args.kv_cache,
            param_dtype=args.param_dtype)
        if lcfg.num_processes > 1 and lcfg.process_id != 0:
            host = lcfg.coordinator_address.rsplit(":", 1)[0] \
                if lcfg.coordinator_address else "127.0.0.1"
            return mesh_serve.follower_loop(config, params,
                                            chief_host=host)
        placement = mesh_serve.MeshPlacement.from_env(config)
        mesh_kw = {"config": config, "params": params}
    lm = LmServer(kv_cache=args.kv_cache,
                  param_dtype=args.param_dtype,
                  default_max_new_tokens=args.max_new_tokens,
                  slots=args.slots, queue_limit=args.queue,
                  prefix_blocks=args.prefix_blocks,
                  batch_sampling=None if args.batch_sampling is None
                  else bool(args.batch_sampling),
                  batch_spec=None if args.batch_spec is None
                  else bool(args.batch_spec),
                  role=args.role, kvxfer_port=args.kvxfer_port,
                  kvxfer_int8=None if args.kvxfer_int8 is None
                  else bool(args.kvxfer_int8),
                  spill_mb=args.spill_mb,
                  kvxfer_dedup=None if args.kvxfer_dedup is None
                  else bool(args.kvxfer_dedup),
                  placement=placement, **mesh_kw)
    httpd = serve(lm, args.host, args.port)
    host, port = httpd.server_address[:2]
    log.info("serving %s on http://%s:%d (POST /v1/generate)",
             args.train_dir, host, port)
    print(f"READY http://{host}:{port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        lm.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

"""HTTP inference server over a train_lm serving artifact.

The CLI loop (examples/train_lm/serve_lm.py) pays artifact load + jit
compile per invocation; a resident server pays them once and serves every
request from the warm jit cache — the practical half of the train→serve
story (`examples/tf_job_serve_http.yaml` runs this as the serving
TFJob's long-lived process; `tf_job_serve.yaml` is the one-shot batch
variant).

    python -m k8s_tpu.models.server --train_dir DIR --port 8000

Endpoints (JSON over HTTP/1.1, stdlib-only like the rest of the repo):

- ``GET /healthz`` → ``{"status": "ok", "model": {...}}`` — readiness for
  kubelet probes.
- ``POST /v1/generate`` with ``{"text": str | "tokens": [int], ...}`` →
  ``{"text": str | "tokens": [int]}``.  Optional fields:
  ``max_new_tokens`` (default from --max_new_tokens), ``temperature``,
  ``top_k``, ``eos``, ``seed``, ``speculative`` (draft_k, greedy-only).

Device work is single-flight (one lock): decode programs are compiled per
(prompt-length, generation-config) shape and cached by jit, so repeated
shapes are served at device speed; a NEW prompt length pays one compile
(documented, not hidden — there is no silent left-pad bucketing, which
would corrupt RoPE positions).
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

log = logging.getLogger(__name__)


class LmServer:
    """Loads a serving artifact once; thread-safe generate()."""

    def __init__(self, train_dir: str, kv_cache: str = "model",
                 param_dtype: str = "model",
                 default_max_new_tokens: int = 64):
        from k8s_tpu.models import serving

        self.config, self.params = serving.load_for_serving(
            train_dir, kv_cache=kv_cache, param_dtype=param_dtype)
        self.default_max_new_tokens = default_max_new_tokens
        self._lock = threading.Lock()  # single-flight device work

    def model_info(self) -> dict:
        c = self.config
        return {"layers": c.layers, "hidden": c.hidden,
                "vocab_size": c.vocab_size, "max_seq_len": c.max_seq_len,
                "kv_cache_dtype": c.kv_cache_dtype}

    def generate(self, req: dict) -> dict:
        """One generation request; raises ValueError on bad input."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from k8s_tpu.models import decode as decode_lib
        from k8s_tpu.models.dataset import decode_bytes, encode_bytes

        has_text = isinstance(req.get("text"), str)
        has_tokens = isinstance(req.get("tokens"), list)
        if has_text == has_tokens:
            raise ValueError('give exactly one of "text" or "tokens"')
        if has_text:
            ids = encode_bytes(req["text"]).astype(np.int32)
        else:
            try:
                ids = np.asarray([int(t) for t in req["tokens"]], np.int32)
            except (TypeError, ValueError):
                raise ValueError('"tokens" must be a list of ints')
        if ids.size < 1:
            raise ValueError("empty prompt")
        if ids.min(initial=0) < 0 or \
                ids.max(initial=0) >= self.config.vocab_size:
            raise ValueError(
                f"token ids outside [0, {self.config.vocab_size})")

        def opt(key, default, cast):
            # JSON null means "not set" (use the default), like an absent
            # key; a non-castable value is the CLIENT's error -> 400
            val = req.get(key)
            if val is None:
                return default
            try:
                return cast(val)
            except (TypeError, ValueError):
                raise ValueError(f"bad {key!r}: {val!r}")

        max_new = opt("max_new_tokens", self.default_max_new_tokens, int)
        if not 1 <= max_new <= self.config.max_seq_len:
            raise ValueError(f"max_new_tokens must be in "
                             f"[1, {self.config.max_seq_len}]")
        temperature = opt("temperature", 0.0, float)
        top_k = opt("top_k", 0, int) or None
        if top_k is not None and top_k < 1:
            raise ValueError("top_k must be >= 1 (omit or 0 disables)")
        eos: Optional[int] = opt("eos", None, int)
        seed = opt("seed", 0, int)
        spec = opt("speculative", 0, int)
        if spec != 0 and spec < 2:
            raise ValueError("speculative must be >= 2 (0 disables)")

        prompt = jnp.asarray(ids)[None, :]
        with self._lock:
            if spec > 0:
                # temperature/top_k compose via rejection sampling: the
                # emitted tokens are distributed exactly as vanilla
                # temperature/top-k sampling
                fn = decode_lib.cached_speculative_fn(
                    self.config, max_new, draft_k=spec, eos_id=eos,
                    temperature=temperature,
                    top_k=top_k if temperature > 0 else None)
                out = fn(self.params, prompt, jax.random.PRNGKey(seed))
            else:
                out = decode_lib.generate(
                    self.config, self.params, prompt, max_new,
                    rng=jax.random.PRNGKey(seed), temperature=temperature,
                    top_k=top_k, eos_id=eos)
        from k8s_tpu.models.serving import strip_after_eos

        toks = strip_after_eos(np.asarray(out)[0], eos)
        if has_text:
            return {"text": req["text"] + decode_bytes(np.asarray(toks))}
        return {"tokens": [int(t) for t in toks]}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "k8s-tpu-lm"

    def log_message(self, fmt, *args):
        log.debug("server: " + fmt, *args)

    def _send(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            return self._send(200, {"status": "ok",
                                    "model": self.server.lm.model_info()})
        return self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        # ALWAYS drain the declared body first: replying on a keep-alive
        # connection with unread bytes leaves them to be parsed as the
        # next request line, 400-ing every later request on the socket
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self.close_connection = True  # unknown body size: can't drain
            return self._send(400, {"error": "bad Content-Length"})
        raw = self.rfile.read(length) if length > 0 else b""
        if self.path != "/v1/generate":
            return self._send(404, {"error": f"unknown path {self.path}"})
        try:
            req = json.loads(raw or b"{}")
            if not isinstance(req, dict):
                raise ValueError("request body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            return self._send(400, {"error": f"bad request body: {e}"})
        try:
            return self._send(200, self.server.lm.generate(req))
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 - surface, don't kill the worker
            log.exception("generate failed")
            return self._send(500, {"error": f"{type(e).__name__}: {e}"})


def serve(lm: LmServer, host: str = "127.0.0.1", port: int = 0):
    """Returns a started ThreadingHTTPServer (caller owns shutdown())."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.lm = lm  # type: ignore[attr-defined]
    t = threading.Thread(target=httpd.serve_forever,
                         kwargs={"poll_interval": 0.1}, daemon=True,
                         name="lm-server")
    t.start()
    return httpd


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train_dir", required=True)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default loopback; set 0.0.0.0 "
                   "explicitly for pod exposure)")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--max_new_tokens", type=int, default=64,
                   help="per-request default")
    p.add_argument("--kv_cache", choices=["model", "int8"], default="model")
    p.add_argument("--param_dtype", choices=["model", "bfloat16"],
                   default="model")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    lm = LmServer(args.train_dir, kv_cache=args.kv_cache,
                  param_dtype=args.param_dtype,
                  default_max_new_tokens=args.max_new_tokens)
    httpd = serve(lm, args.host, args.port)
    host, port = httpd.server_address[:2]
    log.info("serving %s on http://%s:%d (POST /v1/generate)",
             args.train_dir, host, port)
    print(f"READY http://{host}:{port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

"""Mixture-of-Experts FFN with expert parallelism over the ``ep`` mesh axis.

Absent from the reference (SURVEY.md §2.4 — no expert parallelism exists in
the TF1 PS world); built GShard-style for the TPU-native stack:

- router: dense [d → E] in f32, top-k gating with normalized weights;
- capacity-bounded dispatch: each expert processes at most
  ``C = ceil(tokens / E * capacity_factor)`` tokens; overflow tokens fall
  through the residual connection (standard GShard/Switch behavior);
- dispatch/combine are einsums against a [tokens, E, C] one-hot tensor; the
  expert dimension of the [E, C, d] activations and the stacked expert
  params are sharded over ``ep`` via sharding constraints, so XLA inserts
  the all-to-alls — no hand-written collectives, the pjit recipe;
- auxiliary load-balance loss (Switch-style: E * Σ_e f_e · p_e) returned
  for the trainer to add.

Integrated into the transformer family via TransformerConfig.num_experts
(k8s_tpu.models.transformer.Block swaps its MLP for MoeMLP).
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _constrain(x, mesh, spec):
    if mesh is None:
        return x
    try:
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))
    except (ValueError, TypeError):  # e.g. tracing without a mesh context
        return x


class MoeMLP(nn.Module):
    """Drop-in MLP replacement: top-k routed experts, each a SwiGLU MLP.

    Input/output: [B, L, d].  Also stores the auxiliary load-balance loss
    in a "losses" collection (sow) under "moe_aux_loss".
    """

    num_experts: int
    ffn_hidden: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    mesh: Any = None  # static module attr, same pattern as Attention.mesh

    @nn.compact
    def __call__(self, x):
        B, L, d = x.shape
        E = self.num_experts
        k = min(self.top_k, E)
        T = B * L
        tokens = x.reshape(T, d)

        # -- router (f32 for a stable softmax) ---------------------------
        router_w = self.param(
            "router", nn.initializers.normal(0.02), (d, E), jnp.float32)
        logits = tokens.astype(jnp.float32) @ router_w  # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k gates, renormalized over the chosen experts
        gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [T, k]
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

        # -- capacity-bounded dispatch tensor ----------------------------
        # GShard scales capacity by k: k*T (token,choice) pairs must fit in
        # E*C slots, so C = ceil(k*T/E * cf); without the k factor, default
        # top_k=2 would drop most secondary assignments at perfect balance
        C = max(1, math.ceil(k * T / E * self.capacity_factor))
        # position of each (token, choice) in its expert's buffer
        expert_onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # [T,k,E]
        # cumulative position per expert across (token, choice) pairs in
        # priority order: primary choices first, then secondaries
        flat = expert_onehot.transpose(1, 0, 2).reshape(k * T, E)
        pos_flat = jnp.cumsum(flat, axis=0) - flat  # [k*T, E]
        pos = pos_flat.reshape(k, T, E).transpose(1, 0, 2)  # [T, k, E]
        slot = jnp.sum(pos * expert_onehot, axis=-1)  # [T, k]
        keep = slot < C  # overflow tokens dropped (residual carries them)

        # dispatch [T, E, C] one-hot; combine adds the gate weight
        slot_onehot = jax.nn.one_hot(slot, C, dtype=jnp.float32) * (
            keep[..., None].astype(jnp.float32))  # [T, k, C]
        dispatch = jnp.einsum("tke,tkc->tec", expert_onehot.astype(jnp.float32),
                              slot_onehot)  # [T, E, C]
        combine = jnp.einsum("tk,tke,tkc->tec", gate_vals,
                             expert_onehot.astype(jnp.float32), slot_onehot)

        # -- expert computation, ep-sharded ------------------------------
        expert_in = jnp.einsum("tec,td->ecd", dispatch,
                               tokens.astype(jnp.float32)).astype(self.dtype)
        expert_in = _constrain(expert_in, self.mesh, P("ep", None, None))

        def init_e(rng, shape):
            return nn.initializers.normal(0.02)(rng, shape, jnp.float32)

        w_gate = self.param("w_gate", init_e, (E, d, self.ffn_hidden))
        w_up = self.param("w_up", init_e, (E, d, self.ffn_hidden))
        w_down = self.param("w_down", init_e, (E, self.ffn_hidden, d))

        h = jnp.einsum("ecd,edf->ecf", expert_in, w_gate.astype(self.dtype))
        u = jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(self.dtype))
        out = jnp.einsum("ecf,efd->ecd", nn.silu(h) * u,
                         w_down.astype(self.dtype))
        out = _constrain(out, self.mesh, P("ep", None, None))

        # -- combine back to tokens --------------------------------------
        y = jnp.einsum("tec,ecd->td", combine, out.astype(jnp.float32))

        # -- Switch aux loss: E * sum_e (fraction routed) * (mean prob) --
        primary = expert_onehot[:, 0, :].astype(jnp.float32)  # [T, E]
        f = jnp.mean(primary, axis=0)
        p = jnp.mean(probs, axis=0)
        aux = E * jnp.sum(f * p)
        # overwrite-reduce: robust to framework re-traces, and per-layer
        # values stay addressable by module path
        self.sow("losses", "moe_aux_loss", aux,
                 init_fn=lambda: jnp.zeros(()), reduce_fn=lambda a, b: b)

        return y.reshape(B, L, d).astype(x.dtype)


def expert_sharding_rule(path: tuple, mesh) -> Optional[P]:
    """Param-path sharding rule: stacked expert weights shard their leading
    expert dim over ``ep`` (composes with the fsdp rules in
    k8s_tpu.parallel.sharding)."""
    names = [getattr(p, "key", str(p)) for p in path]
    if any(n in ("w_gate", "w_up", "w_down") for n in names):
        return P("ep")
    return None

"""Chief→worker batch-plan bus for multi-host serving (ISSUE 14).

The multi-host engine keeps ALL scheduling host-side on one chief
process (slot admission, block tables, batch-plan ints); worker
processes only run device programs.  In a JAX multi-process world every
process must dispatch the SAME jitted computation with the SAME global
arrays each step — so before the chief dispatches, it broadcasts the
per-step plan (opcode + static args + the host numpy arrays) here, and
each worker replays it verbatim.  Per-step traffic is a few hundred
bytes of ints (slot/table/position/token ids); the model, the KV pool,
and every activation stay on device.

Stdlib only (socket + struct + json), same discipline as the router and
fleet planes.  Wire format per message::

    [4-byte big-endian header length][header json][raw array bytes...]

where the header is ``{"op": str, "statics": {...}, "arrays":
[[name, dtype, shape], ...]}`` and the array payloads follow in header
order, C-contiguous.  The stream is strictly ordered; workers execute
in receive order, so chief and workers always dispatch the same program
sequence (the device layer then enforces lockstep through its own
collectives).

Failure semantics are the gang's: a chief crash closes the TCP stream,
every worker's ``recv()`` raises :class:`PlanBusClosed`, and the worker
exits NONZERO — the operator's whole-gang restart policy takes it from
there (a half-dead serving gang, like a half-dead SPMD training gang,
can only hang).  A deliberate shutdown sends the ``bye`` op first so
workers exit 0.
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from k8s_tpu.analysis import checkedlock

log = logging.getLogger(__name__)

OP_BYE = "bye"
_HDR = struct.Struct(">I")
# plan messages are tiny; anything past this is a protocol bug, not a
# big batch (guards a worker against interpreting a garbage/misaligned
# stream as a multi-GB allocation)
MAX_HEADER = 1 << 20
MAX_ARRAY_BYTES = 1 << 28


class PlanBusClosed(ConnectionError):
    """The plan stream ended: deliberate ``bye`` or a dead chief."""

    def __init__(self, msg: str, *, clean: bool):
        super().__init__(msg)
        self.clean = clean


def _encode(op: str, statics: dict, arrays: dict[str, np.ndarray]
            ) -> bytes:
    metas = []
    payloads = []
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        if arr.nbytes > MAX_ARRAY_BYTES:
            raise ValueError(f"plan array {name} too large: {arr.nbytes}")
        metas.append([name, str(arr.dtype), list(arr.shape)])
        payloads.append(arr.tobytes())
    header = json.dumps({"op": op, "statics": statics,
                         "arrays": metas}).encode()
    if len(header) > MAX_HEADER:
        raise ValueError(f"plan header too large: {len(header)}")
    return _HDR.pack(len(header)) + header + b"".join(payloads)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise PlanBusClosed(
                "plan bus stream ended mid-message (chief gone)",
                clean=False)
        buf.extend(chunk)
    return bytes(buf)


def _decode(sock: socket.socket) -> tuple[str, dict, dict]:
    (hlen,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if hlen > MAX_HEADER:
        raise PlanBusClosed(f"bad plan header length {hlen}", clean=False)
    header = json.loads(_recv_exact(sock, hlen))
    arrays: dict[str, np.ndarray] = {}
    for name, dtype, shape in header["arrays"]:
        n = int(np.dtype(dtype).itemsize * int(np.prod(shape or [1])))
        if n > MAX_ARRAY_BYTES:
            raise PlanBusClosed(f"bad plan array size {n}", clean=False)
        raw = _recv_exact(sock, n) if n else b""
        arrays[name] = np.frombuffer(raw, dtype=dtype).reshape(shape)
    return header["op"], header.get("statics") or {}, arrays


def mp_closed_during_accept() -> PlanBusClosed:
    return PlanBusClosed("plan bus closed during worker accept",
                         clean=True)


class PlanBus:
    """Chief side: accept one connection per worker, then broadcast
    plan messages in step order.

    ``pipelined=False`` (default): sends happen inline on the engine
    thread — broadcast returns after every worker's socket took the
    frame.  ``pipelined=True`` (ISSUE 15 satellite — chunked-prefill
    plan pipelining): broadcast ENQUEUES the encoded frame and returns
    immediately; a dedicated sender thread drains the queue in FIFO
    order, so the chief's next dispatch overlaps the socket I/O of the
    current plan instead of serializing behind it — a multi-chunk
    prefill stops paying one bus round per chunk.  Ordering is
    preserved (one queue, one sender), the frame is encoded at enqueue
    time (the engine may reuse its host buffers afterwards), and a
    sender-side socket failure surfaces as :class:`PlanBusClosed` on
    the NEXT broadcast — the same gang-fatal semantics as the inline
    path, one step later.  ``stats()`` reports enqueue-wait vs actual
    send seconds so the bench can assert the overlap is real.

    ``close()`` (any thread) drains the queue, sends ``bye`` once and
    tears down."""

    def __init__(self, num_workers: int, host: str = "127.0.0.1",
                 port: int = 0, accept_timeout: float = 120.0,
                 pipelined: bool = False):
        """``host`` is the BIND address: loopback for same-host gangs
        (tests, the local driver); the serving chief binds all
        interfaces (``""``) so workers on other pods can dial the
        chief pod's hostname — MeshPlacement.from_env does that."""
        self._lock = checkedlock.make_lock("mp.planbus")
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        self.num_workers = num_workers
        self._conns: list[socket.socket] = []
        self._closed = False
        self._accept_timeout = accept_timeout
        self.pipelined = bool(pipelined)
        # pipelined state, all under _send_cond (its own leaf lock so
        # the sender never holds the conns lock across a syscall)
        self._send_cond = checkedlock.make_condition("mp.planbus.sendq")
        self._sendq: "deque[bytes]" = deque()
        self._send_error: Optional[str] = None
        self._sender_stop = False
        self._stat_broadcasts = 0
        self._stat_enqueue_s = 0.0
        self._stat_send_s = 0.0
        self._stat_bytes = 0
        self._stat_max_depth = 0
        self._sender: Optional[threading.Thread] = None
        if self.pipelined:
            self._sender = threading.Thread(
                target=self._sender_loop, daemon=True,
                name="planbus-sender")
            self._sender.start()

    def stats(self) -> dict:
        """Pipelining telemetry: enqueue-wait vs send seconds is the
        measured overlap (enqueue ≪ send means the engine thread really
        stopped paying the socket I/O)."""
        with self._send_cond:
            return {
                "pipelined": self.pipelined,
                "broadcasts": self._stat_broadcasts,
                "enqueue_wait_s": round(self._stat_enqueue_s, 6),
                "send_s": round(self._stat_send_s, 6),
                "bytes": self._stat_bytes,
                "max_queue_depth": self._stat_max_depth,
                "send_error": self._send_error,
            }

    def _sender_loop(self) -> None:
        while True:
            with self._send_cond:
                while not self._sendq and not self._sender_stop:
                    self._send_cond.wait()
                if not self._sendq:
                    return  # stopped and drained
                data = self._sendq.popleft()
                self._send_cond.notify_all()  # close() waits for drain
            with self._lock:
                conns = list(self._conns)
            t0 = time.monotonic()
            try:
                for conn in conns:
                    conn.sendall(data)
            except OSError as e:
                # a dead worker is gang-fatal: surface on the next
                # broadcast (PlanBusClosed) instead of hanging the queue
                with self._send_cond:
                    self._send_error = f"{type(e).__name__}: {e}"
                    self._sendq.clear()
                    self._send_cond.notify_all()
                return
            with self._send_cond:
                self._stat_send_s += time.monotonic() - t0

    def accept_workers(self) -> None:
        """Block until every worker has dialed in (workers connect right
        after ``jax.distributed`` init, so this bounds gang bring-up,
        not steady state).  The accept socket is only ever touched here;
        the shared connection list is mutated under the bus lock."""
        self._listener.settimeout(self._accept_timeout)
        accepted = 0
        try:
            while accepted < self.num_workers:
                conn, addr = self._listener.accept()
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                accepted += 1
                log.info("plan bus: worker %d/%d connected from %s",
                         accepted, self.num_workers, addr)
                with self._lock:
                    if self._closed:
                        conn.close()
                        raise mp_closed_during_accept()
                    self._conns.append(conn)
        except socket.timeout:
            raise TimeoutError(
                f"plan bus: only {accepted}/{self.num_workers} "
                "workers connected before the accept timeout") from None

    def broadcast(self, op: str, statics: Optional[dict] = None,
                  arrays: Optional[dict] = None) -> None:
        data = _encode(op, statics or {}, arrays or {})
        if not self.pipelined:
            with self._lock:
                if self._closed:
                    raise PlanBusClosed("plan bus closed", clean=True)
                for conn in self._conns:
                    conn.sendall(data)
            return
        t0 = time.monotonic()
        with self._send_cond:
            if self._sender_stop:
                raise PlanBusClosed("plan bus closed", clean=True)
            if self._send_error is not None:
                raise PlanBusClosed(
                    f"plan bus sender died: {self._send_error}",
                    clean=False)
            self._sendq.append(data)
            self._stat_broadcasts += 1
            self._stat_bytes += len(data)
            self._stat_max_depth = max(self._stat_max_depth,
                                       len(self._sendq))
            self._send_cond.notify()
            self._stat_enqueue_s += time.monotonic() - t0

    def _drain_sender(self, timeout: float = 10.0) -> None:
        """Flush queued frames, then stop the sender thread (``bye``
        below must be the LAST frame on every worker's stream)."""
        deadline = time.monotonic() + timeout
        with self._send_cond:
            while self._sendq and self._send_error is None \
                    and time.monotonic() < deadline:
                self._send_cond.wait(0.1)
            self._sender_stop = True
            self._send_cond.notify_all()
        if self._sender is not None:
            self._sender.join(timeout=5)

    def close(self) -> None:
        if self.pipelined:
            self._drain_sender()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for conn in self._conns:
                try:
                    conn.sendall(_encode(OP_BYE, {}, {}))
                except OSError:
                    pass  # worker already gone; the gang policy covers it
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns = []
            try:
                self._listener.close()
            except OSError:
                pass


class PlanFollower:
    """Worker side: one blocking connection to the chief's bus.

    ``recv()`` returns ``(op, statics, arrays)`` in stream order;
    raises :class:`PlanBusClosed` with ``clean=True`` on ``bye`` and
    ``clean=False`` when the stream dies (chief crash) — the worker
    main converts the latter into a NONZERO exit so the gang supervisor
    restarts the whole serving gang instead of leaving orphans parked
    inside a collective."""

    def __init__(self, host: str, port: int, connect_timeout: float = 120.0,
                 retry_interval: float = 0.1):
        import time as _time

        deadline = _time.monotonic() + connect_timeout
        last: Optional[Exception] = None
        self._sock: Optional[socket.socket] = None
        while _time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection(
                    (host, port), timeout=connect_timeout)
                break
            except OSError as e:  # chief still binding: retry
                last = e
                _time.sleep(retry_interval)
        if self._sock is None:
            raise ConnectionError(
                f"plan bus: could not reach chief at {host}:{port}: {last}")
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)  # steady state blocks on the stream

    def recv(self) -> tuple[str, dict, dict]:
        op, statics, arrays = _decode(self._sock)
        if op == OP_BYE:
            raise PlanBusClosed("chief said bye", clean=True)
        return op, statics, arrays

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

"""Workload models (reference counterparts: examples/tf_sample tf_smoke,
test/e2e/dist-mnist; plus the BASELINE.json configs: ResNet-50/ImageNet,
BERT-base fine-tune, Llama-style FSDP)."""

"""Block-table paged attention: the decode-step seam between the serving
engine's pooled KV cache and the attention math.

The engine (models/engine.py) keeps decode KV state in one shared
block-granular pool per cache leaf (``[num_blocks, block_size, kv_heads,
head_dim]``) addressed through per-request **block tables**.  Until round
9 the batched step materialized a per-row gathered *view* of the pool
(``leaf[tables].reshape(B, S, ...)`` for every leaf, every fused window),
ran the dense cache path over it, and scattered the written positions
back — a full extra copy of every row's KV per window, the ~15% decode
tax docs/performance.md tracks.  Now the transformer's decode step writes
new K/V straight into the pool at ``(table[pos // block], pos % block)``
and attends through this one function:

    paged_attention(q, pool_k, pool_v, tables, lengths, positions)

Everything the attention needs to address the pool goes through this
seam, so a real TPU kernel — a Pallas grid over (batch row, block) that
streams table-addressed blocks HBM→VMEM with no gathered copy at all,
flash-style running softmax per row — can replace the body without
touching the engine or the transformer.  The reference implementation
below is plain XLA: it gathers K/V blocks in table order (numerically
identical to the old view, so batched output stays token-identical to
the dense oracle) and feeds them directly into the attention einsum; the
gather is the only materialization left, and it is fused into the
operand feed where XLA can manage it.

Conventions (shared with the Pallas slot-in):

- ``tables`` is ``[B, max_blocks]`` int32; entry 0 is the engine's
  reserved **null block** — table padding points there and nothing valid
  ever reads it.  Write-masked lanes do NOT write the null block: their
  destination index is forced out of bounds (block ``N``) and the
  scatter drops it, so masked rows never store anywhere (see
  :func:`paged_kv_write`).
- ``lengths`` is ``[B]``: the row's written length BEFORE this chunk.
  View index ``p`` is absolute position ``p`` (block ``p // bs``, offset
  ``p % bs``), so validity is purely length-based: positions below
  ``lengths`` are the row's own (or shared, by the table invariant)
  content; everything above — recycled-block garbage, a rejected-draft
  tail, copy-on-write residue — is masked without any scrubbing pass.
- ``positions`` is ``[B, Lc]`` absolute query positions.  **-1 marks a
  write-masked slot**: an inactive row, or the padding lanes of a
  shorter row in a variable-width (speculative) chunk.  Masked queries
  attend nothing and their K/V writes are dropped before they reach the
  pool, so a mixed-width batch can never scribble past a short row's
  block capacity.
- int8 KV pools carry ``k_scale`` / ``v_scale`` leaves ``[N, bs,
  kv_heads]``; dequantization happens after the block load, exactly as
  in the dense path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MASK_VALUE = -1e30


def quantize_kv(x):
    """Symmetric per-vector absmax int8 quantization for KV storage:
    ``x`` is ``[..., D]`` vectors; returns ``(q int8 [..., D], scale f32
    [...])``.  The ONE definition shared by the dense cache write
    (transformer.Attention._kv_cache_write) and the pool write below, so
    the int8 round trip is bitwise identical across paths."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]),
        -127, 127).astype(jnp.int8)
    return q, scale


def paged_kv_write(leaf, tables, positions, x, *, scale_leaf=None,
                   quantize: bool = False):
    """Scatter chunk K/V straight into the pool: ``x`` is ``[B, Lc, H,
    D]`` vectors for absolute ``positions`` ``[B, Lc]``; each lands at
    ``(tables[b, p // bs], p % bs)``.  Write-masked slots (position -1)
    target block index N — out of bounds — and are dropped, never
    clipped into a live block.  Returns the updated leaf (and scale leaf
    when quantizing int8)."""
    N, bs = leaf.shape[0], leaf.shape[1]
    S = tables.shape[1] * bs
    pos_c = jnp.clip(positions, 0, S - 1)
    dstb = jnp.take_along_axis(tables, pos_c // bs, axis=1)  # [B, Lc]
    dstb = jnp.where(positions >= 0, dstb, N)  # masked -> dropped
    off = pos_c % bs
    if quantize:
        q, scale = quantize_kv(x)
        leaf = leaf.at[dstb, off].set(q, mode="drop")
        scale_leaf = scale_leaf.at[dstb, off].set(scale, mode="drop")
        return leaf, scale_leaf
    leaf = leaf.at[dstb, off].set(x.astype(leaf.dtype), mode="drop")
    return leaf, scale_leaf


def paged_attention(q, pool_k, pool_v, tables, lengths, positions, *,
                    k_scale=None, v_scale=None, dtype=None,
                    mask_value: float = MASK_VALUE):
    """Attention for one batched decode chunk over the block pool.

    ``q`` is ``[B, Lc, H, D]`` post-rotary queries; ``pool_k`` /
    ``pool_v`` are ``[N, bs, Hkv, D]`` pool leaves that ALREADY contain
    this chunk's own K/V (write-then-attend, the dense path's order —
    int8 pools therefore see the same quantize/dequantize round trip on
    the chunk's own vectors).  Returns ``[B, Lc, H, D]``.

    Reference XLA implementation of the seam: block-table gather in
    table order feeding the grouped-query einsum — element-for-element
    the computation the dense cache path performs on a gathered view, so
    swapping the paths can never change a sampled token.  A Pallas
    kernel replacing this body must preserve the masking contract
    (validity from ``lengths`` plus this chunk's own positions,
    causality from ``positions``) but is free to never materialize the
    gather.
    """
    B, Lc, H, D = q.shape
    bs = pool_k.shape[1]
    kv_heads = pool_k.shape[2]
    S = tables.shape[1] * bs

    def gather(pool, scale):
        g = pool[tables]  # [B, MAXB, bs, Hkv, D] — table-order blocks
        if scale is not None:
            gs = scale[tables]
            # dequantize in f32, cast the product once (the dense path's
            # _kv_cache_read contract — see transformer.py)
            g = (g.astype(jnp.float32) * gs[..., None]).astype(dtype)
        return g.reshape(B, S, kv_heads, D)

    keys = gather(pool_k, k_scale)
    values = gather(pool_v, v_scale)
    # synthesized slot positions: index p IS position p below the row's
    # written length; the chunk's own (unmasked) positions become valid
    # for later in-chunk queries, exactly like the dense pos scatter
    idx = jnp.arange(S, dtype=jnp.int32)
    kpos = jnp.where(idx[None, :] < lengths[:, None], idx[None, :], -1)
    b = jnp.arange(B)[:, None]
    slot = jnp.where(positions >= 0, positions, S)  # masked -> dropped
    kpos = kpos.at[b, slot].set(positions, mode="drop")
    # grouped-query einsum + masked f32 softmax: one definition with the
    # dense path (transformer.Attention._decode_step) so the two are
    # bitwise interchangeable in exactness tests
    rep = H // kv_heads
    qg = q.reshape(B, Lc, kv_heads, rep, D)
    scores = jnp.einsum("bqhrd,bkhd->bhrqk", qg, keys).astype(jnp.float32)
    scores = scores * (D ** -0.5)
    mask = (kpos >= 0)[:, None, :] & \
        (kpos[:, None, :] <= positions[:, :, None])  # [B, Lc, S]
    scores = jnp.where(mask[:, None, None], scores, mask_value)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhrqk,bkhd->bqhrd", probs.astype(values.dtype),
                     values)
    return out.reshape(B, Lc, H, D)


# ------------------------------------------------- tensor-parallel islands
#
# The multi-host serving placement (ISSUE 14) shards the KV pool along
# the kv-head axis over the mesh's ``tp`` axis: each host holds ITS head
# slice of every block, addressed by the SAME block tables the chief's
# scheduler maintains.  Attention is embarrassingly parallel over heads
# (per-head softmax, no cross-head reduction), so both the pool write
# and the attention read are shard_map'd with ZERO collectives — the
# islands exist to PIN the sharding: left to GSPMD's solver, the
# table-order block gather on a replicated-table / sharded-pool operand
# is exactly the kind of op that can lower to an all-gather of the pool,
# which would silently re-materialize per-host the one tensor this
# placement exists to split.  The bodies are the single-device reference
# functions above, called per shard — numerics are identical per head,
# so a tp mesh can never change a sampled token through attention.
# (The surrounding o_proj/down_proj partial-sum psums are GSPMD's job,
# outside these islands.)

def _head_spec(P):
    return P(None, None, "tp", None)


def paged_kv_write_tp(mesh, leaf, tables, positions, x, *,
                      scale_leaf=None, quantize: bool = False):
    """:func:`paged_kv_write` over a kv-head-sharded pool: the scatter
    indexes only (block, offset) — never the head axis — so each shard
    writes its own head slice locally (no collectives)."""
    from functools import partial

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    hs = _head_spec(P)
    rep = P()
    if quantize:
        body = partial(shard_map,
                       mesh=mesh,
                       in_specs=(hs, rep, rep, hs, P(None, None, "tp")),
                       out_specs=(hs, P(None, None, "tp")))(
            lambda lf, tb, ps, xx, sc: paged_kv_write(
                lf, tb, ps, xx, scale_leaf=sc, quantize=True))
        return body(leaf, tables, positions, x, scale_leaf)
    body = partial(shard_map,
                   mesh=mesh,
                   in_specs=(hs, rep, rep, hs),
                   out_specs=hs)(
        lambda lf, tb, ps, xx: paged_kv_write(lf, tb, ps, xx)[0])
    return body(leaf, tables, positions, x), None


def paged_attention_tp(mesh, q, pool_k, pool_v, tables, lengths,
                       positions, *, k_scale=None, v_scale=None,
                       dtype=None, mask_value: float = MASK_VALUE):
    """:func:`paged_attention` sharded over the ``tp`` mesh axis: query
    heads and pool kv-heads split together (grouped-query ratios are
    preserved per shard), tables/lengths/positions replicated, output
    head-sharded for the row-sharded o_proj that follows.  Per-head math
    is the reference body verbatim — no collective runs inside."""
    from functools import partial

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    hs = _head_spec(P)
    rep = P()
    if k_scale is not None:
        body = partial(
            shard_map, mesh=mesh,
            in_specs=(hs, hs, hs, rep, rep, rep,
                      P(None, None, "tp"), P(None, None, "tp")),
            out_specs=hs)(
            lambda qq, pk, pv, tb, ln, ps, ks, vs: paged_attention(
                qq, pk, pv, tb, ln, ps, k_scale=ks, v_scale=vs,
                dtype=dtype, mask_value=mask_value))
        return body(q, pool_k, pool_v, tables, lengths, positions,
                    k_scale, v_scale)
    body = partial(
        shard_map, mesh=mesh,
        in_specs=(hs, hs, hs, rep, rep, rep),
        out_specs=hs)(
        lambda qq, pk, pv, tb, ln, ps: paged_attention(
            qq, pk, pv, tb, ln, ps, dtype=dtype,
            mask_value=mask_value))
    return body(q, pool_k, pool_v, tables, lengths, positions)

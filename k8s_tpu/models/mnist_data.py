"""MNIST-format (IDX) dataset loading for dist_mnist.

The reference's dist-mnist trains on the real dataset via
``input_data.read_data_sets`` (test/e2e/dist-mnist/dist_mnist.py:120-138),
which reads the gzipped IDX files of the MNIST distribution.  This module
is the TPU rebuild's equivalent: a standalone IDX parser (magic 0x803
images / 0x801 labels, big-endian dims, raw uint8 payload) over a local
``--data_dir`` — no network, no TF.

This image has no cached MNIST bytes and zero egress, so the repo packages
a checksummed fixture built from the UCI handwritten-digits images (real
scanned digits from the same NIST lineage, via sklearn), upscaled to MNIST
geometry and written in genuine IDX+gzip format — the loader cannot tell it
from the real distribution, and any user pointing --data_dir at actual
MNIST files gets them byte-for-byte.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

IMAGES_MAGIC = 0x00000803
LABELS_MAGIC = 0x00000801

TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
TRAIN_LABELS = "train-labels-idx1-ubyte.gz"


def _open(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def _read_header(f, fmt: str, path: str) -> tuple:
    size = struct.calcsize(fmt)
    head = f.read(size)
    if len(head) != size:
        raise ValueError(f"{path}: truncated header ({len(head)} bytes)")
    return struct.unpack(fmt, head)


def read_idx_images(path: str) -> np.ndarray:
    """Parse an IDX3 image file -> [N, rows, cols] uint8."""
    with _open(path) as f:
        magic, n, rows, cols = _read_header(f, ">IIII", path)
        if magic != IMAGES_MAGIC:
            raise ValueError(
                f"{path}: bad magic {magic:#x}, want {IMAGES_MAGIC:#x} "
                f"(IDX3 images)")
        buf = f.read(n * rows * cols)
    if len(buf) != n * rows * cols:
        raise ValueError(f"{path}: truncated — {len(buf)} bytes for "
                         f"{n}x{rows}x{cols}")
    return np.frombuffer(buf, dtype=np.uint8).reshape(n, rows, cols)


def read_idx_labels(path: str) -> np.ndarray:
    """Parse an IDX1 label file -> [N] uint8."""
    with _open(path) as f:
        magic, n = _read_header(f, ">II", path)
        if magic != LABELS_MAGIC:
            raise ValueError(
                f"{path}: bad magic {magic:#x}, want {LABELS_MAGIC:#x} "
                f"(IDX1 labels)")
        buf = f.read(n)
    if len(buf) != n:
        raise ValueError(f"{path}: truncated — {len(buf)} bytes for {n}")
    return np.frombuffer(buf, dtype=np.uint8)


def write_idx_images(path: str, images: np.ndarray) -> None:
    images = np.asarray(images, dtype=np.uint8)
    n, rows, cols = images.shape
    payload = struct.pack(">IIII", IMAGES_MAGIC, n, rows, cols) + \
        images.tobytes()
    # mtime=0 keeps the gzip bytes reproducible across fixture rebuilds
    with gzip.GzipFile(path, "wb", mtime=0) as f:
        f.write(payload)


def write_idx_labels(path: str, labels: np.ndarray) -> None:
    labels = np.asarray(labels, dtype=np.uint8)
    payload = struct.pack(">II", LABELS_MAGIC, len(labels)) + labels.tobytes()
    with gzip.GzipFile(path, "wb", mtime=0) as f:
        f.write(payload)


def load_dataset(data_dir: str) -> tuple[np.ndarray, np.ndarray]:
    """Load the training split from an MNIST-layout directory.

    Returns (x [N, 28, 28, 1] float32 in [0, 1], y [N] int32) — the shapes
    models.mnist.MnistCNN and cross_entropy_loss consume.
    """
    images = read_idx_images(os.path.join(data_dir, TRAIN_IMAGES))
    labels = read_idx_labels(os.path.join(data_dir, TRAIN_LABELS))
    if len(images) != len(labels):
        raise ValueError(
            f"{data_dir}: {len(images)} images vs {len(labels)} labels")
    x = (images.astype(np.float32) / 255.0)[..., None]
    return x, labels.astype(np.int32)


def build_digits_fixture(out_dir: str) -> tuple[np.ndarray, np.ndarray]:
    """Write the packaged real-digits fixture: UCI handwritten digits
    (8x8 grayscale scans) nearest-upscaled to 28x28 and emitted as genuine
    IDX+gzip MNIST-layout files.  Deterministic bytes (gzip mtime=0)."""
    from sklearn.datasets import load_digits

    X, y = load_digits(return_X_y=True)
    imgs8 = (X.reshape(-1, 8, 8) / 16.0 * 255.0).astype(np.uint8)
    # nearest-neighbour 8->24 (x3), then pad 2 px each side to 28
    imgs24 = np.repeat(np.repeat(imgs8, 3, axis=1), 3, axis=2)
    imgs28 = np.pad(imgs24, ((0, 0), (2, 2), (2, 2)))
    os.makedirs(out_dir, exist_ok=True)
    write_idx_images(os.path.join(out_dir, TRAIN_IMAGES), imgs28)
    write_idx_labels(os.path.join(out_dir, TRAIN_LABELS), y)
    return imgs28, y.astype(np.int32)

"""Serving artifacts: the bridge from a training run to inference.

``export_serving`` writes ``<train_dir>/serving/`` — ``model_config.json``
(the TransformerConfig, dtype serialized by name) plus a params-only orbax
checkpoint — so an inference process can reconstruct the model WITHOUT the
training flags that produced it.  ``load_serving`` is the inverse; the
pair closes the train → checkpoint → serve loop that the reference left
entirely to user containers (its pods just mounted volumes;
checkpoint/serving formats were user business — SURVEY.md §5
checkpoint/resume).

The params checkpoint is separate from the training checkpoints on
purpose: training state embeds the optimizer pytree, whose STRUCTURE
depends on the exact optimizer chain (schedule, clipping, accumulation),
so restoring it requires reproducing those flags — exactly the coupling a
serving artifact must not have.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax.numpy as jnp

from k8s_tpu.models.transformer import TransformerConfig

CONFIG_FILE = "model_config.json"
_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}


def export_serving(train_dir: str, config: TransformerConfig,
                   variables: Any) -> str:
    """Write the serving artifact; returns the serving directory path.

    ``variables`` is the model's variables dict ({"params": ...}) — the
    same object train_lm passes to model.apply.
    """
    from k8s_tpu.models.checkpoint import Checkpointer

    if not config.causal:
        raise ValueError(
            "serving artifacts are for causal LMs: decode-mode attention "
            "is causal by construction, so a bidirectional (causal=False) "
            "model would serve silently wrong")
    d = os.path.join(train_dir, "serving")
    os.makedirs(d, exist_ok=True)
    # strip training-scale composition: the sp ring is rejected by decode
    # modes, and params are identical with or without it
    config = dataclasses.replace(config, use_ring_attention=False)
    cfg = dataclasses.asdict(config)
    dtype_name = jnp.dtype(config.dtype).name
    if dtype_name not in _DTYPES:
        raise ValueError(f"unserializable dtype {dtype_name!r}")
    cfg["dtype"] = dtype_name
    tmp = os.path.join(d, CONFIG_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(cfg, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, os.path.join(d, CONFIG_FILE))

    # a resumed/re-run training writes a FRESH artifact: orbax refuses to
    # overwrite an existing step in place, so replace the old step dir
    import shutil

    old = os.path.join(d, "0")
    if os.path.isdir(old):
        shutil.rmtree(old)
    ckpt = Checkpointer(d, max_to_keep=1)
    ckpt.save(0, {"params": variables}, force=True)
    ckpt.wait()
    ckpt.close()
    return d


def load_serving(train_dir: str) -> tuple[TransformerConfig, Any]:
    """Reconstruct (config, variables) from a serving artifact.

    The params template comes from a throwaway model.init at tiny
    sequence length — shapes depend only on the config, not on the
    sequence the training run used.
    """
    import jax

    from k8s_tpu.models.checkpoint import Checkpointer
    from k8s_tpu.models.transformer import Transformer

    d = os.path.join(train_dir, "serving")
    path = os.path.join(d, CONFIG_FILE)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no serving artifact at {d} (train with --train_dir; the "
            "exporter runs on successful completion)")
    with open(path) as f:
        cfg_dict = json.load(f)
    cfg_dict["dtype"] = _DTYPES[cfg_dict["dtype"]]
    config = TransformerConfig(**cfg_dict)

    model = Transformer(config)
    seq = min(8, config.max_seq_len)
    template = model.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, seq), jnp.int32))
    ckpt = Checkpointer(d, max_to_keep=1)
    restored = ckpt.restore(0, {"params": template})
    ckpt.close()
    return config, restored["params"]


def load_for_serving(train_dir: str, kv_cache: str = "model",
                     param_dtype: str = "model"):
    """Artifact load + the serving-efficiency overrides, shared by the CLI
    (examples/train_lm/serve_lm.py) and the resident HTTP server
    (models/server.py) so the two never drift: returns (config, params)
    with ``kv_cache="int8"`` / ``param_dtype="bfloat16"`` applied."""
    config, variables = load_serving(train_dir)
    if kv_cache == "int8":
        config = dataclasses.replace(config, kv_cache_dtype="int8")
    elif kv_cache != "model":
        raise ValueError(
            f"kv_cache must be 'model' or 'int8', got {kv_cache!r}")
    params = variables["params"]
    if param_dtype == "bfloat16":
        params = cast_params_for_serving(params)
    elif param_dtype != "model":
        raise ValueError(
            f"param_dtype must be 'model' or 'bfloat16', got {param_dtype!r}")
    return config, params


def strip_after_eos(toks, eos_id):
    """Rendered output: drop the EOS token and the pad tail after it
    (rows freeze to pad once EOS is emitted)."""
    toks = list(toks)
    if eos_id is not None and eos_id in toks:
        toks = toks[:toks.index(eos_id)]
    return toks


def cast_params_for_serving(params):
    """f32 -> bf16 param cast for inference (decode re-reads every param
    per token, so at f32 they are the dominant HBM term).  Non-f32 leaves
    (already-bf16, integer tables) pass through untouched.  The single
    definition keeps the benchmarked configuration (bench.py decode) and
    the served one (serve_lm --param_dtype bfloat16) identical."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16)
        if x.dtype == jnp.float32 else x, params)

"""Cluster TPU capacity model: total chips, live reservations, node math.

The capacity unit is the **chip** — the one number that is conserved
across topologies (a v5litepod-256 is 64 hosts x 4 chips whether it is
one slice or four).  A reservation is all-or-nothing by construction:
the scheduler either records the whole job's chip demand or nothing, so
a half-scheduled gang can never hold chips (the deadlock gang admission
exists to prevent — see "Exploring the limits of Concurrency in ML
Training on Google TPUs", PAPERS.md).

Stdlib-only by policy (``harness/py_checks.py`` gates this package like
``k8s_tpu/trace/``): the controller hands us plain ints and dicts; all
TFJob/topology knowledge stays in ``controller_v2.tpu_config``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# Mirrors api.v1alpha2.constants.TPU_RESOURCE_PREFIX; duplicated by value
# because this package may not import the rest of the repo (stdlib-only
# gate).  harness/py_checks would flag the import; tests pin the two equal.
TPU_RESOURCE_PREFIX = "cloud-tpus.google.com/"


def chips_from_nodes(nodes: list[dict],
                     resource_prefix: str = TPU_RESOURCE_PREFIX) -> int:
    """Total allocatable TPU chips across ``nodes`` (plain Node dicts):
    the node-listing half of the capacity knob.  Unparseable quantities
    count as 0 — a garbage label must not inflate the cluster."""
    total = 0
    for node in nodes or []:
        alloc = ((node.get("status") or {}).get("allocatable")) or {}
        for key, value in alloc.items():
            if not key.startswith(resource_prefix):
                continue
            try:
                total += int(value)
            except (TypeError, ValueError):
                continue
    return total


@dataclass
class Reservation:
    """One admitted gang's whole-slice chip hold."""

    key: str                       # namespace/name of the TFJob
    chips: int
    priority: int = 0
    queue: str = "default"
    granted_at: float = 0.0        # POSIX seconds
    adopted: bool = False          # re-reserved for an already-running gang

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "chips": self.chips,
            "priority": self.priority,
            "queue": self.queue,
            "granted_at": self.granted_at,
            "adopted": self.adopted,
        }


@dataclass
class ClusterCapacity:
    """Chip ledger.  ``total_chips is None`` means **unlimited** — the
    compatibility default that disables gang admission entirely (the
    operator behaves exactly as before the scheduler existed).

    Not thread-safe on its own: the owning GangScheduler serializes all
    access under its lock.
    """

    total_chips: Optional[int] = None
    reservations: dict[str, Reservation] = field(default_factory=dict)

    @property
    def unlimited(self) -> bool:
        return self.total_chips is None

    def in_use(self) -> int:
        return sum(r.chips for r in self.reservations.values())

    def available(self) -> int:
        """Chips not currently reserved.  Adoption (reality-wins
        re-reservation after a controller restart) may legally drive this
        negative; admission always checks ``fits`` before reserving, so
        the ledger converges back as adopted jobs finish."""
        if self.total_chips is None:
            raise RuntimeError("available() is undefined on unlimited capacity")
        return self.total_chips - self.in_use()

    def fits(self, chips: int) -> bool:
        return self.unlimited or chips <= self.available()

    def reserve(self, key: str, chips: int, priority: int, queue: str,
                now: float, adopted: bool = False) -> Reservation:
        """Record the whole gang's hold.  Idempotent per key: re-reserving
        an existing key keeps the original grant (a double-admit must not
        double-count chips)."""
        existing = self.reservations.get(key)
        if existing is not None:
            return existing
        r = Reservation(key=key, chips=chips, priority=priority, queue=queue,
                        granted_at=now, adopted=adopted)
        self.reservations[key] = r
        return r

    def release(self, key: str) -> int:
        """Free a reservation; returns the chips freed, 0 when absent.
        Idempotent — a gang mid-teardown whose job is preempted AND
        cleaned up terminally releases exactly once, never double-counting
        its chips back into the pool."""
        r = self.reservations.pop(key, None)
        return r.chips if r is not None else 0

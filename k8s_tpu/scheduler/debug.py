"""/debug/scheduler responder (mirror of trace.debug_traces_response).

Serves the active GangScheduler's state as JSON for the metrics server
and the dashboard backend; 404 with an explicit body when no scheduler
is active in this process (same contract as /debug/traces while tracing
is off).  Stdlib-only like the rest of the package.
"""

from __future__ import annotations

import json
from urllib.parse import parse_qs


def debug_scheduler_response(scheduler, query: str = "") -> tuple[int, str, str]:
    """(status_code, body, content_type) for GET /debug/scheduler.

    ``?queue=<name>`` filters reservations and queue entries to one
    logical queue; ``?events=0`` drops the event ring from the payload.
    """
    if scheduler is None:
        return (404,
                "no scheduler active (the controller registers one on "
                "startup)\n",
                "text/plain")
    params = parse_qs(query or "")
    state = scheduler.debug_state()
    queue_name = (params.get("queue") or [None])[0]
    if queue_name:
        state["reservations"] = [
            r for r in state["reservations"] if r.get("queue") == queue_name
        ]
        state["queue"] = [
            e for e in state["queue"] if e.get("queue") == queue_name
        ]
    if (params.get("events") or ["1"])[0] in ("0", "false"):
        state.pop("events", None)
    return 200, json.dumps(state, indent=2, sort_keys=True) + "\n", "application/json"

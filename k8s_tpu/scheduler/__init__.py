"""Gang admission & TPU capacity scheduler (ISSUE 4).

The operator's arbitration layer for finite TPU capacity: a chip ledger
(:mod:`capacity`), a priority queue with FIFO-within-priority and
starvation-resistant aging (:mod:`queue`), and the all-or-nothing
admission + priority-preemption engine (:mod:`scheduler`) the v2
controller consults before creating any pod.

Process-global active-scheduler registry (mirror of ``trace.TRACER``):
the controller registers its scheduler on construction so the metrics
server and dashboard can serve ``/debug/scheduler`` without holding a
controller reference.

This package is stdlib-only by policy (``harness/py_checks.py`` gates it
like ``k8s_tpu/trace/``): it holds cross-job state consulted from every
sync, and all TFJob/topology knowledge stays with its callers.
"""

from __future__ import annotations

from typing import Optional

from k8s_tpu.scheduler.capacity import (  # noqa: F401 (public surface)
    ClusterCapacity,
    Reservation,
    chips_from_nodes,
)
from k8s_tpu.scheduler.debug import debug_scheduler_response  # noqa: F401
from k8s_tpu.scheduler.queue import AdmissionQueue, QueueEntry  # noqa: F401
from k8s_tpu.scheduler.scheduler import (  # noqa: F401
    Decision,
    GangScheduler,
)

# The process's active scheduler (last controller constructed wins — one
# operator process runs one controller; embedded/test layouts overwrite).
_ACTIVE: Optional[GangScheduler] = None


def set_active(scheduler: Optional[GangScheduler]) -> None:
    global _ACTIVE
    _ACTIVE = scheduler


def active() -> Optional[GangScheduler]:
    return _ACTIVE


def debug_response(query: str = "") -> tuple[int, str, str]:
    """The /debug/scheduler endpoint body for the active scheduler."""
    return debug_scheduler_response(_ACTIVE, query)

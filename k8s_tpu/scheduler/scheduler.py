"""Gang admission & capacity scheduler with priority preemption.

One ``GangScheduler`` arbitrates every TFJob's whole-slice chip demand
against finite cluster capacity (ISSUE 4).  The controller calls it once
per sync, *before* any pod exists:

- ``sync_admit`` — the all-or-nothing decision: either the whole gang's
  chips are reserved (reconcile proceeds) or the job stays parked with
  zero pods.  A decision may instead name preemption victims.
- ``preempt`` — atomically evict the victims (release + requeue at their
  base priority) and reserve the preemptor.
- ``release``/``forget`` — free chips on completion/deletion; idempotent,
  so a gang mid-teardown can never be double-counted.

Admission policy: walk the queue in effective-priority order (aging
included, queue.py) and seat jobs until the first one that does not fit
— a job is admitted iff it is in that strict prefix.  No backfill past a
waiting head: small jobs can never starve a parked giant by recycling
the chips it is waiting for; the price (idle chips while the head
waits) is bounded by aging and preemption.

Preemption policy: base priorities only (aging never evicts), victims
chosen lowest-priority-first and newest-grant-first within a priority,
taking the minimal prefix that frees enough chips.  No victims are named
unless the preemptor then actually fits.

Thread-safe: controller workers sync different jobs concurrently and all
cross-job state (ledger + queue) lives here, under one lock.

Stdlib-only by policy (harness/py_checks.py gates this package like
``k8s_tpu/trace/``); all TFJob knowledge stays with the caller.
"""

from __future__ import annotations

import collections
import os
from k8s_tpu.analysis import checkedlock
import time
from dataclasses import dataclass, field
from typing import Optional

from k8s_tpu.scheduler.capacity import ClusterCapacity
from k8s_tpu.scheduler.queue import AdmissionQueue

# Aging knob (seconds of waiting per effective-priority step); the
# constructor arg wins, then the environment, then the default.
ENV_AGING_INTERVAL = "K8S_TPU_SCHED_AGING_S"
DEFAULT_AGING_INTERVAL_S = 300.0

_EVENT_RING = 128  # /debug/scheduler recent-events window


def _aging_from_env() -> float:
    try:
        v = float(os.environ.get(ENV_AGING_INTERVAL, ""))
    except ValueError:
        return DEFAULT_AGING_INTERVAL_S
    return v if v > 0 else DEFAULT_AGING_INTERVAL_S


@dataclass
class Decision:
    """Outcome of one sync's admission question."""

    admitted: bool
    reason: str = ""
    # queued=True: the job holds no reservation and must create no pods.
    queued: bool = False
    # victims: admission is possible NOW by evicting these keys (all
    # strictly lower base priority); caller tears them down then calls
    # ``preempt``.
    victims: list[str] = field(default_factory=list)
    # seconds between first enqueue and this admission (0 when admitted
    # without ever waiting) — feeds tfjob_admission_wait_seconds.
    wait_s: float = 0.0
    # True when this decision granted a NEW reservation (vs. one that
    # already existed) — feeds tfjob_admitted_total.
    newly_admitted: bool = False


class GangScheduler:
    def __init__(self, total_chips: Optional[int] = None,
                 aging_interval_s: Optional[float] = None,
                 max_aging_boost: int = 5):
        self._lock = checkedlock.make_rlock("scheduler.ledger")
        self.capacity = ClusterCapacity(total_chips=total_chips)
        self.queue = AdmissionQueue(
            aging_interval_s=(aging_interval_s if aging_interval_s is not None
                              else _aging_from_env()),
            max_boost=max_aging_boost,
        )
        # victim key -> preemptor key, held until the victim is re-admitted
        # (or forgotten); lets the victim's own sync explain WHY it parked
        # and suppresses its reality-wins re-adoption.
        self._preempted_by: dict[str, str] = {}
        self.preemptions_total = 0
        self.admitted_total = 0
        self._events: collections.deque = collections.deque(maxlen=_EVENT_RING)

    # -- configuration --------------------------------------------------------

    @property
    def unlimited(self) -> bool:
        return self.capacity.unlimited

    @property
    def total_chips(self) -> Optional[int]:
        return self.capacity.total_chips

    def set_total(self, chips: Optional[int]) -> None:
        """(Re)pin total capacity — the node-derived path updates this as
        nodes come and go.  Shrinking below current use does not evict
        anyone; running gangs drain naturally and nothing new is admitted
        until the ledger fits again."""
        with self._lock:
            if chips == self.capacity.total_chips:
                return
            self.capacity.total_chips = chips
            self._event("set_total", key="", chips=chips or 0)

    # -- admission ------------------------------------------------------------

    def sync_admit(self, key: str, chips: int, priority: int = 0,
                   queue: str = "default", running: bool = False,
                   now: Optional[float] = None) -> Decision:
        """The per-sync admission question for one job.

        ``running=True`` asserts the gang's pods already run (controller
        restart): the reservation is re-adopted unconditionally — reality
        wins over the ledger — unless the job was deliberately preempted,
        in which case the eviction stands.
        """
        now = time.time() if now is None else now
        with self._lock:
            if self.unlimited:
                return Decision(admitted=True, reason="unlimited")
            if key in self.capacity.reservations:
                return Decision(admitted=True, reason="reserved")
            if chips <= 0:
                # No TPU demand (CPU-only replicas): nothing to arbitrate.
                return Decision(admitted=True, reason="no-tpu-demand")
            if running and key not in self._preempted_by:
                self.capacity.reserve(key, chips, priority, queue, now,
                                      adopted=True)
                self.queue.remove(key)
                self.admitted_total += 1
                self._event("adopt", key=key, chips=chips)
                return Decision(admitted=True, reason="adopted",
                                newly_admitted=True)

            newly_queued = self.queue.get(key) is None
            entry = self.queue.add(key, chips, priority, queue, now)
            if key in self._admissible_prefix(now):
                self.queue.remove(key)
                self.capacity.reserve(key, chips, priority, queue, now)
                self._preempted_by.pop(key, None)
                self.admitted_total += 1
                wait = max(now - entry.enqueued_at, 0.0)
                self._event("admit", key=key, chips=chips)
                return Decision(admitted=True, reason="fit", wait_s=wait,
                                newly_admitted=True)

            if chips > (self.capacity.total_chips or 0):
                # Infeasible: no amount of draining or preemption can ever
                # seat this job.  It stays parked with a reason that says
                # so, and the prefix walk skips it, so it cannot starve
                # feasible work behind it.
                if newly_queued:
                    self._event("queue", key=key, chips=chips)
                return Decision(admitted=False, queued=True,
                                reason="infeasible-demand-exceeds-cluster")
            victims = self._select_victims(chips, priority)
            if victims:
                return Decision(admitted=False, queued=True, victims=victims,
                                reason="preemptible")
            if newly_queued:
                # first parking only: a resyncing parked job must not flood
                # the event ring and evict the admit/preempt history
                self._event("queue", key=key, chips=chips)
            return Decision(admitted=False, queued=True,
                            reason="insufficient-capacity")

    def _admissible_prefix(self, now: float) -> set[str]:
        """Keys the priority-ordered walk can seat in the available chips,
        stopping at the FIRST entry that does not fit: the waiting head
        holds every free chip for itself (strict head-of-line order), so a
        stream of small lower-priority jobs can never backfill a parked
        giant into starvation — the queue drains in effective-priority
        order, period.  The cost is idle chips while the head waits; aging
        plus preemption keep that wait bounded."""
        avail = self.capacity.available()
        total = self.capacity.total_chips or 0
        seated: set[str] = set()
        for e in self.queue.ordered(now):
            if e.chips > total:
                continue  # infeasible forever: must not block feasible work
            if e.chips > avail:
                break
            seated.add(e.key)
            avail -= e.chips
        return seated

    # -- preemption -----------------------------------------------------------

    def _select_victims(self, chips_needed: int, priority: int) -> list[str]:
        """Minimal victim set freeing >= the shortfall: strictly lower BASE
        priority only, lowest priority first, newest grant first within a
        priority (the job that ran least loses least).  Empty when even
        evicting every lower-priority gang would not fit."""
        avail = self.capacity.available()
        candidates = sorted(
            (r for r in self.capacity.reservations.values()
             if r.priority < priority),
            key=lambda r: (r.priority, -r.granted_at),
        )
        chosen: list[str] = []
        for r in candidates:
            if avail >= chips_needed:
                break
            chosen.append(r.key)
            avail += r.chips
        return chosen if avail >= chips_needed else []

    def preempt(self, preemptor: str, chips: int, priority: int,
                queue: str, victims: Optional[list[str]] = None,
                now: Optional[float] = None) -> Decision:
        """Atomically select victims, evict them, and seat ``preemptor`` —
        all under one lock acquisition.  The caller's ``victims`` hint (from
        a prior sync_admit decision) is ADVISORY only: the ledger may have
        moved between that decision and this call (another worker admitted
        into the free chips, a victim finished), and evicting a stale set
        would tear down innocent gangs without seating anyone.  Each actual
        victim's reservation is released exactly once and the victim
        re-enters the queue at its ORIGINAL base priority with a fresh
        waiting clock.  If nothing can seat the preemptor any more, nothing
        is evicted and the preemptor stays queued."""
        del victims  # advisory hint; re-selected fresh under the lock
        now = time.time() if now is None else now
        with self._lock:
            if preemptor in self.capacity.reservations:
                return Decision(admitted=True, reason="reserved")
            evicted: list[str] = []
            if not self.capacity.fits(chips):
                fresh = self._select_victims(chips, priority)
                if not fresh:
                    # the window closed: stay queued, the next sync re-decides
                    self.queue.add(preemptor, chips, priority, queue, now)
                    return Decision(admitted=False, queued=True,
                                    reason="preempt-insufficient")
                for v in fresh:
                    r = self.capacity.reservations.get(v)
                    self.capacity.release(v)
                    self.queue.add(v, r.chips, r.priority, r.queue, now)
                    self._preempted_by[v] = preemptor
                    self.preemptions_total += 1
                    evicted.append(v)
                    self._event("preempt", key=v, chips=r.chips, by=preemptor)
            entry = self.queue.remove(preemptor)
            self.capacity.reserve(preemptor, chips, priority, queue, now)
            self._preempted_by.pop(preemptor, None)
            self.admitted_total += 1
            wait = (max(now - entry.enqueued_at, 0.0)
                    if entry is not None else 0.0)
            self._event("admit", key=preemptor, chips=chips)
            return Decision(admitted=True, reason="preempted",
                            victims=evicted, wait_s=wait, newly_admitted=True)

    def preempted_by(self, key: str) -> Optional[str]:
        with self._lock:
            return self._preempted_by.get(key)

    def is_reserved(self, key: str) -> bool:
        """Cheap steady-state fast path: lets callers skip computing a
        job's chip demand entirely when its reservation already exists
        (every sync of a running gang)."""
        with self._lock:
            return key in self.capacity.reservations

    def reserved_chips(self, key: str) -> Optional[int]:
        """The job's current chip hold, or None when it holds nothing —
        the drift check the controller runs when a replica-count patch
        (autoscale, ISSUE 13) changes a reserved gang's demand."""
        with self._lock:
            r = self.capacity.reservations.get(key)
            return None if r is None else r.chips

    def resize(self, key: str, chips: int,
               now: Optional[float] = None) -> Decision:
        """Atomically resize an EXISTING reservation to ``chips`` — the
        gang-atomic scale path (ISSUE 13).  A shrink always succeeds and
        frees the delta back to the pool; a grow succeeds iff the whole
        delta fits in the available chips RIGHT NOW, else nothing
        changes and the caller parks the expansion (never a partial
        placement).  Unreserved keys are refused: first admission goes
        through :meth:`sync_admit`, where queue order and preemption
        apply."""
        now = time.time() if now is None else now
        with self._lock:
            if self.unlimited:
                return Decision(admitted=True, reason="unlimited")
            r = self.capacity.reservations.get(key)
            if r is None:
                return Decision(admitted=False,
                                reason="not-reserved (admit first)")
            if chips <= 0:
                return Decision(admitted=False,
                                reason="resize to <= 0 chips is a release")
            delta = chips - r.chips
            if delta == 0:
                return Decision(admitted=True, reason="unchanged")
            if delta < 0:
                r.chips = chips
                self._event("shrink", key=key, chips=-delta)
                return Decision(admitted=True, reason="shrunk",
                                newly_admitted=False)
            if delta > self.capacity.available():
                self._event("resize-denied", key=key, chips=delta)
                return Decision(
                    admitted=False, reason="insufficient-capacity")
            r.chips = chips
            self._event("grow", key=key, chips=delta)
            return Decision(admitted=True, reason="grown",
                            newly_admitted=False)

    # -- release --------------------------------------------------------------

    def release(self, key: str) -> int:
        """Free the job's reservation (terminal cleanup); returns chips
        freed (0 when it held none — idempotent)."""
        with self._lock:
            freed = self.capacity.release(key)
            if freed:
                self._event("release", key=key, chips=freed)
            return freed

    def forget(self, key: str) -> int:
        """Job deleted: release its chips AND drop any queue entry or
        preemption marker; returns chips freed."""
        with self._lock:
            freed = self.capacity.release(key)
            self.queue.remove(key)
            self._preempted_by.pop(key, None)
            if freed:
                self._event("release", key=key, chips=freed)
            return freed

    # -- introspection --------------------------------------------------------

    def queue_depth(self) -> int:
        with self._lock:
            return self.queue.depth()

    def waiting_keys(self) -> list[str]:
        """Parked jobs in admission order — the wake list after a release."""
        with self._lock:
            return [e.key for e in self.queue.ordered(time.time())]

    def _event(self, etype: str, key: str, chips: int = 0, **extra) -> None:
        evt = {"ts": time.time(), "type": etype, "key": key, "chips": chips}
        evt.update(extra)
        self._events.append(evt)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def debug_state(self, now: Optional[float] = None) -> dict:
        """The /debug/scheduler document: capacity ledger, queue with
        effective priorities and waits, recent events."""
        now = time.time() if now is None else now
        with self._lock:
            unlimited = self.unlimited
            state = {
                "total_chips": self.capacity.total_chips,
                "unlimited": unlimited,
                "in_use_chips": self.capacity.in_use(),
                "available_chips": (None if unlimited
                                    else self.capacity.available()),
                "admitted_total": self.admitted_total,
                "preemptions_total": self.preemptions_total,
                "reservations": sorted(
                    (r.to_dict() for r in self.capacity.reservations.values()),
                    key=lambda d: d["granted_at"],
                ),
                "queue": [
                    {
                        "key": e.key,
                        "chips": e.chips,
                        "priority": e.priority,
                        "effective_priority":
                            self.queue.effective_priority(e, now),
                        "queue": e.queue,
                        "wait_s": round(max(now - e.enqueued_at, 0.0), 3),
                        "preempted_by": self._preempted_by.get(e.key),
                    }
                    for e in self.queue.ordered(now)
                ],
                "events": list(self._events),
            }
        return state

"""Admission queue: priority order, FIFO within a priority, aging.

Ordering contract (the one ``tests/test_scheduler.py`` pins):

- higher **effective** priority first;
- FIFO within equal effective priority (a monotonic enqueue sequence
  breaks ties — arrival order, never dict order);
- **aging**: a parked job gains one effective-priority step per
  ``aging_interval_s`` of waiting, capped at ``max_boost`` steps, so a
  steady stream of higher-priority arrivals cannot starve a low-priority
  job forever.  Aging affects *queue order only* — preemption compares
  **base** priorities (scheduler.py), so an aged job never evicts a
  genuinely more important running gang; it just stops being overtaken.

Stdlib-only by policy (harness/py_checks.py gates this package).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class QueueEntry:
    key: str                  # namespace/name of the TFJob
    chips: int
    priority: int             # base priority from the spec
    queue: str = "default"    # logical queue label (grouping/reporting)
    enqueued_at: float = 0.0  # POSIX seconds of FIRST enqueue
    seq: int = 0              # arrival order tiebreaker


class AdmissionQueue:
    """Not thread-safe on its own: the owning GangScheduler serializes
    access under its lock."""

    def __init__(self, aging_interval_s: float = 300.0, max_boost: int = 5):
        self.aging_interval_s = max(aging_interval_s, 1e-9)
        self.max_boost = max(max_boost, 0)
        self._entries: dict[str, QueueEntry] = {}
        self._seq = 0

    def add(self, key: str, chips: int, priority: int, queue: str,
            now: float) -> QueueEntry:
        """Enqueue, or refresh an existing entry's demand/priority from the
        latest spec.  ``enqueued_at``/``seq`` survive the refresh: waiting
        time (and with it aging and the FIFO position) is measured from the
        first time the job asked, not the latest resync."""
        entry = self._entries.get(key)
        if entry is None:
            entry = QueueEntry(key=key, chips=chips, priority=priority,
                               queue=queue, enqueued_at=now, seq=self._seq)
            self._seq += 1
            self._entries[key] = entry
        else:
            entry.chips = chips
            entry.priority = priority
            entry.queue = queue
        return entry

    def get(self, key: str) -> QueueEntry | None:
        return self._entries.get(key)

    def remove(self, key: str) -> QueueEntry | None:
        return self._entries.pop(key, None)

    def depth(self) -> int:
        return len(self._entries)

    def keys(self) -> list[str]:
        return list(self._entries)

    def effective_priority(self, entry: QueueEntry, now: float) -> int:
        boost = int((now - entry.enqueued_at) / self.aging_interval_s)
        return entry.priority + min(max(boost, 0), self.max_boost)

    def ordered(self, now: float) -> list[QueueEntry]:
        """Entries in admission order: effective priority desc, then FIFO."""
        return sorted(
            self._entries.values(),
            key=lambda e: (-self.effective_priority(e, now), e.seq),
        )

"""Dashboard: REST API + SPA (reference: dashboard/)."""

"""Dashboard backend (reference: dashboard/backend/).

Serves the SPA at ``/tfjobs/ui/`` and the REST API under ``/tfjobs/api``
(routes from dashboard/backend/handler/api_handler.go:74-113):

    GET    /tfjobs/api/tfjob                         list across namespaces
    GET    /tfjobs/api/tfjob/{namespace}             list in a namespace
    GET    /tfjobs/api/tfjob/{namespace}/{name}      get one
    POST   /tfjobs/api/tfjob                         deploy (creates ns if absent)
    DELETE /tfjobs/api/tfjob/{namespace}/{name}      delete
    GET    /tfjobs/api/logs/{namespace}/{pod}        pod logs
    GET    /tfjobs/api/namespaces                    list namespaces

Implemented on http.server (stdlib-only like the rest of the control plane).
"""

from __future__ import annotations

import json
import logging
import re
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from k8s_tpu.client import errors
from k8s_tpu.client.clientset import Clientset

log = logging.getLogger(__name__)

FRONTEND_DIR = Path(__file__).parent / "frontend"


class ClientManager:
    """dashboard/backend/client/manager.go:13-45."""

    def __init__(self, clientset: Clientset):
        self.clientset = clientset


def _make_handler(manager: ClientManager):
    cs = manager.clientset

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            log.debug(fmt, *args)

        # -- helpers ---------------------------------------------------------

        def _send_json(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str, content_type="text/plain") -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _error(self, e: Exception) -> None:
            if isinstance(e, errors.ApiError):
                code = e.code
            elif isinstance(e, (json.JSONDecodeError, ValueError)):
                code = 400
            else:
                code = 500
            self._send_json(code, {"error": str(e)})

        def _read_body(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            return json.loads(self.rfile.read(length)) if length else {}

        # -- routes ----------------------------------------------------------

        def do_GET(self):  # noqa: N802
            raw_path, _, query = self.path.partition("?")
            path = raw_path.rstrip("/")
            try:
                if path == "/metrics":
                    # Prometheus text exposition (filling SURVEY.md §5's
                    # observability gap; Go operators serve this from
                    # controller-runtime — here the dashboard process does).
                    from k8s_tpu.util.metrics import REGISTRY

                    self._send_text(
                        200, REGISTRY.expose(), "text/plain; version=0.0.4"
                    )
                elif path == "/debug/traces":
                    # Same contract as the operator's metrics endpoint:
                    # recent span trees slowest-first, ?job= filter, 404
                    # with an explicit body while tracing is off.  Like
                    # /metrics above, this reads THIS process's state —
                    # it shows operator spans only when the dashboard is
                    # embedded with the controller (the LocalCluster /
                    # single-binary layout); a separately deployed
                    # dashboard should scrape the operator's
                    # --metrics-port endpoint instead.
                    from k8s_tpu import trace

                    code, body, ctype = trace.debug_traces_response(
                        trace.TRACER, query)
                    self._send_text(code, body, ctype)
                elif path == "/debug/scheduler":
                    # Gang-admission queue/capacity state — same per-process
                    # scope caveat as /debug/traces above: meaningful when
                    # the dashboard embeds the controller (LocalCluster /
                    # single-binary layout); a separately deployed dashboard
                    # should scrape the operator's --metrics-port endpoint.
                    from k8s_tpu import scheduler as scheduler_mod

                    code, body, ctype = scheduler_mod.debug_response(query)
                    self._send_text(code, body, ctype)
                elif path == "/debug/timeline":
                    # Flight-recorder lifecycle journal — the SAME shared
                    # responder the metrics server uses (one contract, one
                    # implementation: flight.debug_timeline_response), with
                    # the same per-process scope caveat as above.
                    from k8s_tpu import flight

                    code, body, ctype = flight.timeline_response(query)
                    self._send_text(code, body, ctype)
                elif path == "/debug/fleet":
                    # Fleet telemetry plane — shared responder with the
                    # metrics server (fleet.debug_fleet_response), same
                    # per-process scope caveat as the other /debug routes.
                    from k8s_tpu import fleet

                    code, body, ctype = fleet.debug_response(query)
                    self._send_text(code, body, ctype)
                elif path == "/debug/router":
                    # serving front-door router (ISSUE 13) — shared
                    # responder with the metrics server and the router's
                    # own listener, same per-process scope caveat as the
                    # other /debug routes.
                    from k8s_tpu import router as router_mod

                    code, body, ctype = router_mod.debug_response(query)
                    self._send_text(code, body, ctype)
                elif path == "/debug/compiles":
                    # XLA compile ledger — shared responder with the
                    # metrics server and the serving pod, same
                    # per-process scope caveat as the other /debug routes.
                    from k8s_tpu.analysis import compileledger

                    code, body, ctype = \
                        compileledger.debug_compiles_response(query)
                    self._send_text(code, body, ctype)
                elif path == "/debug/requests":
                    # per-request serving timelines (ISSUE 12) — shared
                    # responder with the metrics server and the serving
                    # pod, same per-process scope caveat (meaningful
                    # when this process hosts the engine; a separately
                    # deployed dashboard hits the serving pod directly).
                    from k8s_tpu.models import requestlog

                    code, body, ctype = \
                        requestlog.debug_requests_response(query)
                    self._send_text(code, body, ctype)
                elif path == "/debug/engine":
                    # engine step ledger (ISSUE 12) — shared responder,
                    # same scope caveat as /debug/requests above.
                    from k8s_tpu.models import requestlog

                    code, body, ctype = \
                        requestlog.debug_engine_response(query)
                    self._send_text(code, body, ctype)
                elif path == "/debug":
                    # index of the debug endpoints with active state
                    # (path is rstrip("/")-normalized above, so this
                    # covers /debug/ too)
                    from k8s_tpu.util.debug_index import debug_index_response

                    code, body, ctype = debug_index_response(query)
                    self._send_text(code, body, ctype)
                elif path in ("", "/tfjobs/ui", "/tfjobs"):
                    self._serve_ui("index.html")
                elif path.startswith("/tfjobs/ui/"):
                    self._serve_ui(path[len("/tfjobs/ui/"):] or "index.html")
                elif path == "/tfjobs/api/tfjob":
                    jobs = []
                    for ns in self._namespaces():
                        jobs += cs.tfjobs_unstructured(ns).list()
                    self._send_json(200, {"items": jobs})
                elif m := re.fullmatch(r"/tfjobs/api/tfjob/([^/]+)", path):
                    self._send_json(
                        200, {"items": cs.tfjobs_unstructured(m.group(1)).list()}
                    )
                elif m := re.fullmatch(r"/tfjobs/api/tfjob/([^/]+)/([^/]+)", path):
                    ns, name = m.groups()
                    job = cs.tfjobs_unstructured(ns).get(name)
                    pods = cs.pods(ns).list(label_selector={"tf_job_name": name})
                    if not pods:
                        pods = [
                            p
                            for p in cs.pods(ns).list()
                            if any(
                                r.get("name") == name
                                for r in (p.get("metadata", {}).get("ownerReferences") or [])
                            )
                        ]
                    self._send_json(200, {"tfJob": job, "pods": pods})
                elif m := re.fullmatch(r"/tfjobs/api/logs/([^/]+)/([^/]+)", path):
                    ns, pod = m.groups()
                    # Log retrieval needs a kubelet; the fake backend stores
                    # them under status.log for tests.  404s if missing.
                    obj = cs.pods(ns).get(pod)
                    self._send_json(
                        200, {"logs": (obj.get("status") or {}).get("log", "")}
                    )
                elif path == "/tfjobs/api/namespaces":
                    self._send_json(200, {"namespaces": self._namespaces()})
                else:
                    self._send_json(404, {"error": f"no route {path}"})
            except Exception as e:  # noqa: BLE001
                self._error(e)

        def do_POST(self):  # noqa: N802
            path = self.path.split("?")[0].rstrip("/")
            try:
                if path == "/tfjobs/api/tfjob":
                    body = self._read_body()
                    ns = (body.get("metadata") or {}).get("namespace", "default")
                    # create the namespace if missing (api_handler.go deploy path)
                    try:
                        cs.namespaces().get(ns)
                    except errors.ApiError as e:
                        if errors.is_not_found(e):
                            cs.namespaces().create({"metadata": {"name": ns}})
                        else:
                            raise
                    created = cs.tfjobs_unstructured(
                        ns, body.get("apiVersion", "kubeflow.org/v1alpha2")
                    ).create(body)
                    self._send_json(201, created)
                else:
                    self._send_json(404, {"error": f"no route {path}"})
            except Exception as e:  # noqa: BLE001
                self._error(e)

        def do_DELETE(self):  # noqa: N802
            path = self.path.split("?")[0].rstrip("/")
            try:
                if m := re.fullmatch(r"/tfjobs/api/tfjob/([^/]+)/([^/]+)", path):
                    ns, name = m.groups()
                    cs.tfjobs_unstructured(ns).delete(name)
                    self._send_json(200, {"status": "deleted"})
                else:
                    self._send_json(404, {"error": f"no route {path}"})
            except Exception as e:  # noqa: BLE001
                self._error(e)

        # -- static ----------------------------------------------------------

        def _serve_ui(self, rel: str) -> None:
            target = (FRONTEND_DIR / rel).resolve()
            if not str(target).startswith(str(FRONTEND_DIR.resolve())) or not target.is_file():
                target = FRONTEND_DIR / "index.html"
            ctype = "text/html"
            if target.suffix == ".js":
                ctype = "application/javascript"
            elif target.suffix == ".css":
                ctype = "text/css"
            self._send_text(200, target.read_text(), ctype)

        def _namespaces(self) -> list[str]:
            try:
                return [
                    n["metadata"]["name"] for n in cs.namespaces().list()
                ] or ["default"]
            except errors.ApiError:
                return ["default"]

    return Handler


class DashboardServer:
    def __init__(self, clientset: Clientset, host: str = "0.0.0.0", port: int = 8080):
        self.manager = ClientManager(clientset)
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self.manager))

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def serve_forever(self) -> None:
        log.info("dashboard listening on :%d (ui at /tfjobs/ui/)", self.port)
        self.httpd.serve_forever()

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True, name="dashboard")
        t.start()
        return t

    def shutdown(self) -> None:
        self.httpd.shutdown()


def main() -> int:
    import argparse

    p = argparse.ArgumentParser("tpu-dashboard")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--kubeconfig", default="")
    opts = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    from k8s_tpu.cmd.operator import make_backend

    server = DashboardServer(Clientset(make_backend(opts.kubeconfig)), port=opts.port)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())

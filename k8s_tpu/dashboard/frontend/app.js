/* Dashboard SPA (reference counterpart: dashboard/frontend/src/components/).
 * Vanilla JS against the /tfjobs/api routes. */

const api = (p) => fetch(`/tfjobs/api${p}`).then((r) => r.json());

const TEMPLATE = {
  apiVersion: "kubeflow.org/v1alpha2",
  kind: "TFJob",
  metadata: { name: "my-tpu-job", namespace: "default" },
  spec: {
    tpu: { acceleratorType: "v5litepod-16", topology: "4x4" },
    tfReplicaSpecs: {
      TPU: {
        replicas: 4,
        restartPolicy: "ExitCode",
        template: {
          spec: {
            containers: [
              {
                name: "tensorflow",
                image: "ghcr.io/k8s-tpu/jax-tpu:latest",
                resources: { limits: { "cloud-tpus.google.com/v5e": 4 } },
              },
            ],
          },
        },
      },
    },
  },
};

function jobState(job) {
  const st = job.status || {};
  if (st.phase) return st.phase; // v1alpha1
  const conds = (st.conditions || []).filter((c) => c.status === "True");
  return conds.length ? conds[conds.length - 1].type : "Pending";
}

function replicaSummary(job) {
  const spec = job.spec || {};
  if (spec.tfReplicaSpecs)
    return Object.entries(spec.tfReplicaSpecs)
      .map(([t, s]) => `${t}:${s.replicas ?? 1}`)
      .join(" ");
  if (spec.replicaSpecs)
    return spec.replicaSpecs
      .map((s) => `${s.tfReplicaType}:${s.replicas ?? 1}`)
      .join(" ");
  return "";
}

async function refresh() {
  const data = await api("/tfjob");
  const rows = (data.items || []).map((j) => {
    const m = j.metadata || {};
    const state = jobState(j);
    return `<tr onclick="showDetail('${m.namespace}','${m.name}')">
      <td>${m.name}</td><td>${m.namespace}</td>
      <td>${replicaSummary(j)}</td>
      <td><span class="state ${state}">${state}</span></td>
      <td class="muted">${m.creationTimestamp || ""}</td>
      <td><button class="danger" onclick="event.stopPropagation();deleteJob('${m.namespace}','${m.name}')">delete</button></td>
    </tr>`;
  });
  document.getElementById("jobs").innerHTML =
    rows.join("") || `<tr><td colspan="6" class="muted">no jobs</td></tr>`;
}

async function showDetail(ns, name) {
  const data = await api(`/tfjob/${ns}/${name}`);
  document.getElementById("d-name").textContent = `${ns}/${name}`;
  document.getElementById("d-status").textContent = JSON.stringify(
    (data.tfJob || {}).status || {}, null, 2);
  document.getElementById("d-spec").textContent = JSON.stringify(
    (data.tfJob || {}).spec || {}, null, 2);
  document.getElementById("d-pods").innerHTML = (data.pods || [])
    .map((p) => {
      const phase = (p.status || {}).phase || "Pending";
      return `<tr><td>${p.metadata.name}</td>
        <td><span class="state ${phase}">${phase}</span></td>
        <td><a onclick="showLogs('${ns}','${p.metadata.name}')">logs</a></td></tr>`;
    })
    .join("") || `<tr><td colspan="3" class="muted">no pods</td></tr>`;
  document.getElementById("d-logs").style.display = "none";
  show("detail");
}

async function showLogs(ns, pod) {
  const data = await api(`/logs/${ns}/${pod}`);
  const el = document.getElementById("d-logs");
  el.textContent = data.logs || "(no logs)";
  el.style.display = "block";
}

async function deleteJob(ns, name) {
  await fetch(`/tfjobs/api/tfjob/${ns}/${name}`, { method: "DELETE" });
  refresh();
}

function showCreate() {
  document.getElementById("c-body").value = JSON.stringify(TEMPLATE, null, 2);
  document.getElementById("c-msg").textContent = "";
  show("create");
}

async function submitJob() {
  let body;
  try {
    body = JSON.parse(document.getElementById("c-body").value);
  } catch (e) {
    document.getElementById("c-msg").textContent = `invalid JSON: ${e.message}`;
    return;
  }
  const resp = await fetch("/tfjobs/api/tfjob", {
    method: "POST",
    headers: { "Content-Type": "application/json" },
    body: JSON.stringify(body),
  });
  if (resp.ok) { showList(); refresh(); }
  else {
    const err = await resp.json();
    document.getElementById("c-msg").textContent = err.error || resp.statusText;
  }
}

function show(id) {
  for (const s of ["list", "detail", "create"])
    document.getElementById(s).style.display = s === id ? "block" : "none";
}
function showList() { show("list"); refresh(); }

showList();
setInterval(() => {
  if (document.getElementById("list").style.display !== "none") refresh();
}, 5000);

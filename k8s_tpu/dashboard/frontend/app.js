/* Dashboard SPA (reference counterpart: dashboard/frontend/src/components/ —
 * JobList/JobDetail/PodList plus the CreateJob form tree:
 * CreateJob.js, CreateReplicaSpec.js, EnvVarCreator.js, VolumeCreator.js).
 * Vanilla JS against the /tfjobs/api routes; no build step. */

const api = (p) => fetch(`/tfjobs/api${p}`).then((r) => r.json());

/* HTML/attribute escaping for every user-controlled value interpolated into
 * innerHTML (names, images, commands, namespaces). */
const esc = (s) => String(s ?? "")
  .replace(/&/g, "&amp;").replace(/</g, "&lt;").replace(/>/g, "&gt;")
  .replace(/"/g, "&quot;").replace(/'/g, "&#39;");

/* ---------------- create-form state (CreateJob.js state tree) ----------- */

const newReplicaSpec = (overrides = {}) => ({
  replicaType: "TPU",
  replicas: 4,
  image: "ghcr.io/k8s-tpu/jax-tpu:latest",
  command: "",
  restartPolicy: "ExitCode",
  chipsPerHost: 4,
  ...overrides,
});

let form;
const resetForm = () => {
  form = {
    name: "my-tpu-job",
    namespace: currentNamespace() || "default",
    acceleratorType: "v5litepod-16",
    topology: "4x4",
    numSlices: 1,
    replicaSpecs: [newReplicaSpec()],
    envVars: [],      // EnvVarCreator.js rows {name, value}
    volumes: [],      // VolumeCreator.js rows {name, mountPath, hostPath}
  };
};

/* Build the TFJob manifest from the form (CreateJob.js handleDeploy).
 * Throws on duplicate replica types (object keys would silently collapse). */
function buildManifest(f) {
  const types = f.replicaSpecs.map((rs) => rs.replicaType);
  const dup = types.find((t, i) => types.indexOf(t) !== i);
  if (dup) throw new Error(`duplicate replica spec type: ${dup}`);
  const env = f.envVars.filter((e) => e.name)
    .map((e) => ({ name: e.name, value: e.value }));
  const volumes = f.volumes.filter((v) => v.name);
  const tfReplicaSpecs = {};
  for (const rs of f.replicaSpecs) {
    const container = {
      name: "tensorflow",
      image: rs.image,
    };
    if (rs.command.trim()) container.command = rs.command.trim().split(/\s+/);
    if (env.length) container.env = env;
    if (rs.replicaType === "TPU" && rs.chipsPerHost > 0)
      container.resources = { limits: { "cloud-tpus.google.com/v5e": Number(rs.chipsPerHost) } };
    if (volumes.length)
      container.volumeMounts = volumes.map((v) => ({ name: v.name, mountPath: v.mountPath }));
    const podSpec = { containers: [container] };
    if (volumes.length)
      podSpec.volumes = volumes.map((v) =>
        v.hostPath ? { name: v.name, hostPath: { path: v.hostPath } }
                   : { name: v.name, emptyDir: {} });
    tfReplicaSpecs[rs.replicaType] = {
      replicas: Number(rs.replicas),
      restartPolicy: rs.restartPolicy,
      template: { spec: podSpec },
    };
  }
  const spec = { tfReplicaSpecs };
  if (Object.keys(tfReplicaSpecs).includes("TPU")) {
    spec.tpu = { acceleratorType: f.acceleratorType, topology: f.topology };
    if (Number(f.numSlices) > 1) spec.tpu.numSlices = Number(f.numSlices);
  }
  return {
    apiVersion: "kubeflow.org/v1alpha2",
    kind: "TFJob",
    metadata: { name: f.name, namespace: f.namespace },
    spec,
  };
}

/* ---------------- list view (JobList.js / JobSummary.js) ---------------- */

function jobState(job) {
  const st = job.status || {};
  if (st.phase) return st.phase; // v1alpha1
  const conds = (st.conditions || []).filter((c) => c.status === "True");
  return conds.length ? conds[conds.length - 1].type : "Pending";
}

function replicaSummary(job) {
  const spec = job.spec || {};
  if (spec.tfReplicaSpecs)
    return Object.entries(spec.tfReplicaSpecs)
      .map(([t, s]) => `${t}:${s.replicas ?? 1}`)
      .join(" ");
  if (spec.replicaSpecs)
    return spec.replicaSpecs
      .map((s) => `${s.tfReplicaType}:${s.replicas ?? 1}`)
      .join(" ");
  return "";
}

function currentNamespace() {
  const sel = document.getElementById("ns-select");
  return sel && sel.value ? sel.value : "";
}

async function loadNamespaces() {
  const data = await api("/namespaces").catch(() => ({ namespaces: [] }));
  const names = data.namespaces || [];
  const sel = document.getElementById("ns-select");
  const current = sel.value;
  sel.innerHTML = `<option value="">all namespaces</option>` +
    names.map((n) => `<option${n === current ? " selected" : ""}>${esc(n)}</option>`).join("");
}

async function refresh() {
  const ns = currentNamespace();
  const data = await api(ns ? `/tfjob/${ns}` : "/tfjob");
  const rows = (data.items || []).map((j) => {
    const m = j.metadata || {};
    const state = jobState(j);
    return `<tr onclick="showDetail('${esc(m.namespace)}','${esc(m.name)}')">
      <td>${esc(m.name)}</td><td>${esc(m.namespace)}</td>
      <td>${esc(replicaSummary(j))}</td>
      <td><span class="state ${esc(state)}">${esc(state)}</span></td>
      <td class="muted">${esc(m.creationTimestamp || "")}</td>
      <td><button class="danger" onclick="event.stopPropagation();deleteJob('${esc(m.namespace)}','${esc(m.name)}')">delete</button></td>
    </tr>`;
  });
  document.getElementById("jobs").innerHTML =
    rows.join("") || `<tr><td colspan="6" class="muted">no jobs</td></tr>`;
}

/* ---------------- detail view (JobDetail.js / PodList.js) --------------- */

/* InfoEntry.js: one labeled row */
const infoRow = (label, value) =>
  `<tr><th style="width:220px">${esc(label)}</th><td>${value}</td></tr>`;

/* JobSummary.js/InfoEntry.js: identity + timing rows */
function renderInfo(job) {
  const m = job.metadata || {};
  const st = job.status || {};
  const tpu = (job.spec || {}).tpu;
  const rows = [
    infoRow("Name", esc(m.name)),
    infoRow("Namespace", esc(m.namespace)),
    infoRow("State", `<span class="state ${esc(jobState(job))}">${esc(jobState(job))}</span>`),
    infoRow("Created", esc(m.creationTimestamp || "—")),
    infoRow("Started", esc(st.startTime || "—")),
    infoRow("Completed", esc(st.completionTime || "—")),
    infoRow("Last reconcile", esc(st.lastReconcileTime || "—")),
  ];
  if (m.uid) rows.push(infoRow("UID", esc(m.uid)));
  if (tpu)
    rows.push(infoRow("TPU slice",
      esc(`${tpu.acceleratorType || ""} ${tpu.topology || ""}` +
          (tpu.numSlices > 1 ? ` ×${tpu.numSlices} slices` : ""))));
  return rows.join("");
}

/* JobDetail.js conditions table: the status engine's full condition list
 * (type/status/reason/message/lastTransitionTime), newest last */
function renderConditions(job) {
  const conds = ((job.status || {}).conditions || []);
  return conds.map((c) => `<tr>
      <td><span class="state ${esc(c.type)}">${esc(c.type)}</span></td>
      <td>${esc(c.status)}</td>
      <td>${esc(c.reason || "")}</td>
      <td>${esc(c.message || "")}</td>
      <td class="muted">${esc(c.lastTransitionTime || c.lastUpdateTime || "")}</td>
    </tr>`).join("")
    || `<tr><td colspan="5" class="muted">no conditions</td></tr>`;
}

/* ReplicaSpec.js drill-down: desired vs active/succeeded/failed per type */
function renderReplicaStatuses(job) {
  const spec = (job.spec || {}).tfReplicaSpecs || {};
  const statuses = (job.status || {}).tfReplicaStatuses || {};
  const types = [...new Set([...Object.keys(spec), ...Object.keys(statuses)])];
  return types.map((t) => {
    const s = statuses[t] || {};
    const rs = spec[t] || {};
    return `<tr><td>${esc(t)}</td>
      <td>${esc(rs.replicas ?? "—")}</td>
      <td>${esc(s.active || 0)}</td>
      <td class="${s.succeeded ? "" : "muted"}">${esc(s.succeeded || 0)}</td>
      <td class="${s.failed ? "" : "muted"}">${esc(s.failed || 0)}</td>
      <td class="muted">${esc(rs.restartPolicy || "")}</td></tr>`;
  }).join("") || `<tr><td colspan="6" class="muted">no replica specs</td></tr>`;
}

/* PodList.js: replica labels + container exit codes alongside phase/logs */
function podExit(p) {
  const cs = ((p.status || {}).containerStatuses || [])
    .find((c) => c.name === "tensorflow");
  const term = ((cs || {}).state || {}).terminated ||
               ((cs || {}).lastState || {}).terminated;
  return term && term.exitCode !== undefined ? String(term.exitCode) : "";
}

async function showDetail(ns, name) {
  const data = await api(`/tfjob/${ns}/${name}`);
  const job = data.tfJob || {};
  document.getElementById("d-name").textContent = `${ns}/${name}`;
  document.getElementById("d-summary").innerHTML =
    `<span class="state ${esc(jobState(job))}">${esc(jobState(job))}</span> &nbsp; ${esc(replicaSummary(job))}`;
  document.getElementById("d-info").innerHTML = renderInfo(job);
  document.getElementById("d-conditions").innerHTML = renderConditions(job);
  document.getElementById("d-replica-status").innerHTML = renderReplicaStatuses(job);
  document.getElementById("d-status").textContent =
    JSON.stringify(job.status || {}, null, 2);
  document.getElementById("d-spec").textContent =
    JSON.stringify(job.spec || {}, null, 2);
  document.getElementById("d-pods").innerHTML = (data.pods || [])
    .map((p) => {
      const phase = (p.status || {}).phase || "Pending";
      const labels = (p.metadata || {}).labels || {};
      const replica = [labels["tf-replica-type"], labels["tf-replica-index"]]
        .filter((x) => x !== undefined).join("-");
      return `<tr><td>${esc(p.metadata.name)}</td>
        <td class="muted">${esc(replica)}</td>
        <td><span class="state ${esc(phase)}">${esc(phase)}</span></td>
        <td class="muted">${esc(podExit(p))}</td>
        <td><a onclick="showLogs('${esc(ns)}','${esc(p.metadata.name)}')">logs</a></td></tr>`;
    })
    .join("") || `<tr><td colspan="5" class="muted">no pods</td></tr>`;
  document.getElementById("d-logs").style.display = "none";
  show("detail");
}

async function showLogs(ns, pod) {
  const data = await api(`/logs/${ns}/${pod}`);
  const el = document.getElementById("d-logs");
  el.textContent = data.logs || "(no logs)";
  el.style.display = "block";
}

async function deleteJob(ns, name) {
  await fetch(`/tfjobs/api/tfjob/${ns}/${name}`, { method: "DELETE" });
  refresh();
}

/* ---------------- create view ------------------------------------------- */

const REPLICA_TYPES = ["TPU", "Chief", "Worker", "PS", "Eval"];
const RESTART_POLICIES = ["ExitCode", "OnFailure", "Always", "Never"];

const opt = (vals, sel) =>
  vals.map((v) => `<option${v === sel ? " selected" : ""}>${v}</option>`).join("");

function renderForm() {
  const f = form;
  const rsRows = f.replicaSpecs.map((rs, i) => `
    <div class="row">
      <div><label>Type</label>
        <select onchange="setRS(${i},'replicaType',this.value)">${opt(REPLICA_TYPES, rs.replicaType)}</select></div>
      <div><label>Replicas</label>
        <input type="number" min="1" value="${rs.replicas}" style="width:80px"
               onchange="setRS(${i},'replicas',this.value)"></div>
      <div style="flex:1"><label>Image</label>
        <input value="${esc(rs.image)}" style="width:100%" onchange="setRS(${i},'image',this.value)"></div>
      <div><label>Command (optional)</label>
        <input value="${esc(rs.command)}" onchange="setRS(${i},'command',this.value)"></div>
      <div><label>Restart</label>
        <select onchange="setRS(${i},'restartPolicy',this.value)">${opt(RESTART_POLICIES, rs.restartPolicy)}</select></div>
      ${rs.replicaType === "TPU" ? `<div><label>Chips/host</label>
        <input type="number" min="0" value="${rs.chipsPerHost}" style="width:80px"
               onchange="setRS(${i},'chipsPerHost',this.value)"></div>` : ""}
      <div><button class="ghost" onclick="form.replicaSpecs.splice(${i},1);renderForm()">✕</button></div>
    </div>`).join("");

  const envRows = f.envVars.map((e, i) => `
    <div class="row">
      <div><label>Name</label><input value="${esc(e.name)}" onchange="form.envVars[${i}].name=this.value"></div>
      <div style="flex:1"><label>Value</label>
        <input value="${esc(e.value)}" style="width:100%" onchange="form.envVars[${i}].value=this.value"></div>
      <div><button class="ghost" onclick="form.envVars.splice(${i},1);renderForm()">✕</button></div>
    </div>`).join("");

  const volRows = f.volumes.map((v, i) => `
    <div class="row">
      <div><label>Name</label><input value="${esc(v.name)}" onchange="form.volumes[${i}].name=this.value"></div>
      <div><label>Mount path</label>
        <input value="${esc(v.mountPath)}" onchange="form.volumes[${i}].mountPath=this.value"></div>
      <div style="flex:1"><label>Host path (empty ⇒ emptyDir)</label>
        <input value="${esc(v.hostPath)}" style="width:100%" onchange="form.volumes[${i}].hostPath=this.value"></div>
      <div><button class="ghost" onclick="form.volumes.splice(${i},1);renderForm()">✕</button></div>
    </div>`).join("");

  document.getElementById("c-form").innerHTML = `
    <fieldset><legend>Job</legend>
      <div class="row">
        <div><label>Name</label><input value="${esc(f.name)}" onchange="form.name=this.value"></div>
        <div><label>Namespace</label><input value="${esc(f.namespace)}" onchange="form.namespace=this.value"></div>
      </div>
    </fieldset>
    <fieldset><legend>TPU slice</legend>
      <div class="row">
        <div><label>Accelerator type</label>
          <input value="${esc(f.acceleratorType)}" onchange="form.acceleratorType=this.value"></div>
        <div><label>Topology</label>
          <input value="${esc(f.topology)}" style="width:90px" onchange="form.topology=this.value"></div>
        <div><label>Slices</label>
          <input type="number" min="1" value="${f.numSlices}" style="width:70px"
                 onchange="form.numSlices=this.value"></div>
      </div>
    </fieldset>
    <fieldset><legend>Replica specs</legend>${rsRows}
      <button class="ghost" onclick="form.replicaSpecs.push(newReplicaSpec({replicaType:'Worker',chipsPerHost:0}));renderForm()">+ replica spec</button>
    </fieldset>
    <fieldset><legend>Environment variables</legend>${envRows}
      <button class="ghost" onclick="form.envVars.push({name:'',value:''});renderForm()">+ env var</button>
    </fieldset>
    <fieldset><legend>Volumes</legend>${volRows}
      <button class="ghost" onclick="form.volumes.push({name:'',mountPath:'',hostPath:''});renderForm()">+ volume</button>
    </fieldset>`;
}

function setRS(i, key, value) {
  form.replicaSpecs[i][key] = value;
  if (key === "replicaType") renderForm(); // chips/host visibility
}

/* Best-effort inverse of buildManifest: manifest -> form state.  Returns
 * null when the manifest contains anything the form cannot express (so
 * toggling back never silently drops JSON edits). */
function manifestToForm(man) {
  try {
    const spec = man.spec || {};
    const tpu = spec.tpu || {};
    const f = {
      name: (man.metadata || {}).name || "",
      namespace: (man.metadata || {}).namespace || "default",
      acceleratorType: tpu.acceleratorType || "v5litepod-16",
      topology: tpu.topology || "4x4",
      numSlices: tpu.numSlices || 1,
      replicaSpecs: [],
      envVars: [],
      volumes: [],
    };
    for (const [rtype, rs] of Object.entries(spec.tfReplicaSpecs || {})) {
      const podSpec = ((rs.template || {}).spec) || {};
      const c = (podSpec.containers || [])[0] || {};
      f.replicaSpecs.push(newReplicaSpec({
        replicaType: rtype,
        replicas: rs.replicas ?? 1,
        image: c.image || "",
        command: (c.command || []).join(" "),
        restartPolicy: rs.restartPolicy || "ExitCode",
        chipsPerHost: Number(((c.resources || {}).limits || {})["cloud-tpus.google.com/v5e"] || 0),
      }));
      f.envVars = (c.env || []).map((e) => ({ name: e.name, value: e.value ?? "" }));
      f.volumes = (podSpec.volumes || []).map((v) => ({
        name: v.name,
        mountPath: ((c.volumeMounts || []).find((m) => m.name === v.name) || {}).mountPath || "",
        hostPath: (v.hostPath || {}).path || "",
      }));
    }
    // round-trip check: only accept if the form reproduces the manifest
    if (JSON.stringify(buildManifest(f)) !== JSON.stringify(man)) return null;
    return f;
  } catch (e) {
    return null;
  }
}

let jsonMode = false;
function toggleJsonMode() {
  const ta = document.getElementById("c-body");
  const msg = document.getElementById("c-msg");
  if (!jsonMode) {
    try {
      ta.value = JSON.stringify(buildManifest(form), null, 2);
    } catch (e) {
      msg.textContent = e.message;
      return;
    }
  } else {
    // leaving JSON mode: sync edits back, or refuse rather than drop them
    let parsed;
    try {
      parsed = JSON.parse(ta.value);
    } catch (e) {
      msg.textContent = `invalid JSON: ${e.message} — fix it or deploy from JSON mode`;
      return;
    }
    const f = manifestToForm(parsed);
    if (!f) {
      msg.textContent =
        "this JSON uses fields the form cannot represent; staying in JSON mode";
      return;
    }
    form = f;
    renderForm();
  }
  jsonMode = !jsonMode;
  msg.textContent = "";
  ta.style.display = jsonMode ? "block" : "none";
  document.getElementById("c-form").style.display = jsonMode ? "none" : "block";
  document.getElementById("mode-btn").textContent = jsonMode ? "Edit as form" : "Edit as JSON";
}

function showCreate() {
  resetForm();
  jsonMode = false;
  document.getElementById("c-body").style.display = "none";
  document.getElementById("c-form").style.display = "block";
  document.getElementById("mode-btn").textContent = "Edit as JSON";
  document.getElementById("c-msg").textContent = "";
  renderForm();
  show("create");
}

async function submitJob() {
  let body;
  if (jsonMode) {
    try {
      body = JSON.parse(document.getElementById("c-body").value);
    } catch (e) {
      document.getElementById("c-msg").textContent = `invalid JSON: ${e.message}`;
      return;
    }
  } else {
    try {
      body = buildManifest(form);
    } catch (e) {
      document.getElementById("c-msg").textContent = e.message;
      return;
    }
  }
  const resp = await fetch("/tfjobs/api/tfjob", {
    method: "POST",
    headers: { "Content-Type": "application/json" },
    body: JSON.stringify(body),
  });
  if (resp.ok) { showList(); refresh(); }
  else {
    const err = await resp.json();
    document.getElementById("c-msg").textContent = err.error || resp.statusText;
  }
}

/* ---------------- router ------------------------------------------------ */

function show(id) {
  for (const s of ["list", "detail", "create"])
    document.getElementById(s).style.display = s === id ? "block" : "none";
}
function showList() { show("list"); refresh(); }

loadNamespaces().then(showList);
setInterval(() => {
  if (document.getElementById("list").style.display !== "none") refresh();
}, 5000);

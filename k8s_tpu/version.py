"""Version information (reference: pkg/version/version.go:22-43)."""

import subprocess

__version__ = "0.1.0-alpha"


def git_sha() -> str:
    """Best-effort git SHA of the working tree, "unknown" outside a checkout."""
    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True,
                text=True,
                timeout=5,
                check=True,
            ).stdout.strip()
            or "unknown"
        )
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def print_version(program: str) -> None:
    """Print program version + git SHA, like pkg/version/version.go:34-43."""
    print(f"{program} version {__version__} (git: {git_sha()})")

"""Controller v1 (reference: pkg/controller/controller.go).

Workqueue + informer; maps job key → stateful in-memory ``TrainingJob``
(keyed by UID so a delete+recreate with the same name builds a fresh one,
controller.go:271-288).  Same rate-limit envelope as the reference
(exp backoff 5ms→1000s, 10 qps / burst 100 — controller.go:122-126).
"""

from __future__ import annotations

import logging
import threading
from k8s_tpu.analysis import checkedlock
import time

from k8s_tpu.api import register, v1alpha1
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.client.gvr import TFJOBS_V1ALPHA1
from k8s_tpu.client.informer import SharedInformerFactory, split_meta_namespace_key
from k8s_tpu.client.record import AsyncEventRecorder, EventRecorder  # noqa: F401 (EventRecorder is part of the module's injection surface)
from k8s_tpu.controller.trainer.training import TrainingJob
from k8s_tpu.util import metrics
from k8s_tpu.util.workqueue import new_rate_limiting_queue

log = logging.getLogger(__name__)

CONTROLLER_NAME = "tpu-job-controller"


class Controller:
    def __init__(
        self,
        clientset: Clientset,
        config: v1alpha1.ControllerConfig | None = None,
        informer_factory: SharedInformerFactory | None = None,
        enable_gang_scheduling: bool = False,
        recorder=None,
    ):
        self.clientset = clientset
        self.config = config or v1alpha1.ControllerConfig()
        self.enable_gang_scheduling = enable_gang_scheduling
        self.recorder = recorder or AsyncEventRecorder(clientset, CONTROLLER_NAME)
        self.queue = new_rate_limiting_queue()
        self.metrics = metrics.controller_metrics("v1")
        self.jobs: dict[str, TrainingJob] = {}  # key -> TrainingJob
        self._jobs_lock = checkedlock.make_lock("controller_v1.jobs")

        self.factory = informer_factory or SharedInformerFactory(clientset.backend)
        self.tfjob_informer = self.factory.informer_for(TFJOBS_V1ALPHA1)
        self.tfjob_lister = self.factory.lister_for(TFJOBS_V1ALPHA1)
        self.tfjob_informer.add_event_handler(
            on_add=lambda obj: self.enqueue(obj),
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self._on_delete,
        )
        self._workers: list[threading.Thread] = []
        self._stop = threading.Event()

    @staticmethod
    def _key(obj: dict) -> str:
        meta = obj.get("metadata") or {}
        return f"{meta.get('namespace', '')}/{meta.get('name', '')}"

    def enqueue(self, obj: dict) -> None:
        self.queue.add(self._key(obj))

    def _on_delete(self, obj: dict) -> None:
        """Deletion: tear down resources via the in-memory job, then drop it
        (controller.go handles this through syncTFJob's not-found path; doing
        it here keeps teardown prompt)."""
        key = self._key(obj)
        with self._jobs_lock:
            job = self.jobs.pop(key, None)
        if job is not None:
            try:
                job.delete()
            except Exception:
                log.exception("error deleting job resources for %s", key)

    # -- run loop ------------------------------------------------------------


    def healthy(self) -> bool:
        """Liveness signal for /healthz: healthy before run() starts (a
        standby replica is alive), and, once running, while at least one
        worker thread is still processing the queue."""
        if not self._workers:
            return True
        return any(t.is_alive() for t in self._workers)

    def run(self, threadiness: int = 1, stop_event: threading.Event | None = None) -> None:
        stop = stop_event or self._stop
        self.start(threadiness)
        stop.wait()
        self.shutdown()

    def start(self, threadiness: int = 1) -> None:
        log.info("Starting %s", CONTROLLER_NAME)
        self.factory.start()
        if not self.factory.wait_for_cache_sync(30):
            raise RuntimeError("timed out waiting for caches to sync")
        for i in range(threadiness):
            t = threading.Thread(target=self._run_worker, daemon=True, name=f"v1-worker-{i}")
            t.start()
            self._workers.append(t)

    def shutdown(self) -> None:
        self._stop.set()
        self.queue.shut_down()
        self.factory.stop()
        close = getattr(self.recorder, "close", None)
        if close:  # drain + terminate the async event sink (mirrors v2) —
            # events from the final reconciles must reach the apiserver
            close(timeout=5.0)

    def _run_worker(self) -> None:
        while self._process_next_work_item():
            pass

    def _process_next_work_item(self) -> bool:
        """controller.go:201-234."""
        key, shutdown = self.queue.get()
        if shutdown:
            return False
        try:
            forget = self.sync_tfjob(key)
            if forget:
                self.queue.forget(key)
            else:
                self.metrics["queue_retries"].labels(self.metrics["generation"]).inc()
                self.queue.add_rate_limited(key)
        except Exception:
            log.exception("error syncing tfjob %s", key)
            self.metrics["queue_retries"].labels(self.metrics["generation"]).inc()
            self.queue.add_rate_limited(key)
        finally:
            self.queue.done(key)
        return True

    # -- sync ----------------------------------------------------------------

    def sync_tfjob(self, key: str) -> bool:
        """controller.go:241-310."""
        start = time.monotonic()
        result = "success"
        try:
            ns, name = split_meta_namespace_key(key)
            obj = self.tfjob_lister.get(ns, name)
            if obj is None:
                with self._jobs_lock:
                    job = self.jobs.pop(key, None)
                if job is not None:
                    job.delete()
                return True
            tfjob = register.tfjob_from_unstructured(obj)

            with self._jobs_lock:
                existing = self.jobs.get(key)
                if existing is None or existing.uid() != tfjob.metadata.uid:
                    # new job (or delete+recreate under the same name)
                    existing = TrainingJob(self.clientset, self.recorder, tfjob)
                    self.jobs[key] = existing
                else:
                    existing.job = tfjob  # Update (controller.go:284-288)

            existing.reconcile(self.config, self.enable_gang_scheduling)
            return existing.status.phase in (
                v1alpha1.PHASE_DONE,
                v1alpha1.PHASE_FAILED,
                v1alpha1.PHASE_RUNNING,
                v1alpha1.PHASE_CREATING,
            )
        except Exception:
            result = "error"
            raise
        finally:
            elapsed = time.monotonic() - start
            gen = self.metrics["generation"]
            self.metrics["sync_duration"].labels(gen).observe(elapsed)
            self.metrics["sync_total"].labels(gen, result).inc()
            log.debug("finished syncing %s (%.3fs)", key, elapsed)

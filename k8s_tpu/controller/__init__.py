"""Controller v1: stateful "trainer" reconciler (reference: pkg/controller/,
pkg/trainer/)."""

"""TrainingJob — the v1 per-job state machine (reference: pkg/trainer/training.go).

Phases: None → (setup: default+validate+accelerators+RuntimeId) → Creating →
Running → CleanUp → Done, with Failed on setup/validation errors
(training.go:214-248, 314-428).  The chief replica's state decides the job
state (training.go:154-189); in the TPU world the chief is JAX process 0, so
MASTER keeps its meaning and pure-TPU jobs chief on TPU_WORKER:0.
"""

from __future__ import annotations

import logging

from k8s_tpu.api import helpers, register, v1alpha1, validation
from k8s_tpu.client import errors
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.controller.trainer.replicas import (
    TFReplicaSet,
    V1_SPMD_TYPE_ORDER,
)
from k8s_tpu.util.util import rand_string

log = logging.getLogger(__name__)


class TrainingJob:
    def __init__(self, clientset: Clientset, recorder, job: v1alpha1.TFJob):
        self.clientset = clientset
        self.recorder = recorder
        self.job = job
        self.status = v1alpha1.TFJobStatus.from_dict(job.status.to_dict())
        self.replicas: list[TFReplicaSet] = []
        self.pdb_name: str | None = None

    # -- identity ------------------------------------------------------------

    def name(self) -> str:
        return self.job.metadata.name

    def fullname(self) -> str:
        return f"{self.job.metadata.namespace}:{self.job.metadata.name}"

    def uid(self) -> str:
        return self.job.metadata.uid

    def scheduler_name(self) -> str:
        return self.job.spec.scheduler_name

    # -- cluster spec --------------------------------------------------------

    def cluster_spec(self) -> dict[str, list[str]]:
        """ClusterSpec (training.go:126-140): type → ['name:port', ...] using
        the deterministic per-index service names."""
        spec: dict[str, list[str]] = {}
        for r in self.replicas:
            rt = r.spec.tf_replica_type.lower()
            spec[rt] = [
                f"{r.gen_name(i)}:{r.spec.tf_port}" for i in range(r.spec.replicas or 1)
            ]
        return spec

    def spmd_process_table(self) -> list[tuple[str, int, str]]:
        """(rtype, index, host:port) triples in process-id order; MASTER (the
        chief) is process 0.  PS is not an SPMD participant."""
        table = []
        by_type = {r.spec.tf_replica_type: r for r in self.replicas}
        for rtype in V1_SPMD_TYPE_ORDER:
            r = by_type.get(rtype)
            if r is None:
                continue
            for i in range(r.spec.replicas or 1):
                table.append((rtype, i, f"{r.gen_name(i)}:{r.spec.tf_port}"))
        return table

    # -- setup ---------------------------------------------------------------

    def setup(self, config: v1alpha1.ControllerConfig) -> None:
        """training.go:214-248."""
        if self.status.phase != v1alpha1.PHASE_NONE:
            log.warning("job %s has already been setup", self.name())
            return
        try:
            register.default_tfjob(self.job)
            validation.validate_v1alpha1_tfjob_spec(self.job.spec)
            helpers.configure_accelerators_for_tfjob_spec(
                self.job.spec, config.accelerators
            )
            if not self.job.spec.runtime_id:
                self.job.spec.runtime_id = rand_string(4)
        except (validation.ValidationError, ValueError) as e:
            self.status.reason = f"invalid job spec: {e}"
            self.status.phase = v1alpha1.PHASE_FAILED
            self.status.state = v1alpha1.STATE_FAILED
            return
        self.status.phase = v1alpha1.PHASE_CREATING
        self.status.state = v1alpha1.STATE_RUNNING

    def setup_replicas(self) -> None:
        """training.go:251-264."""
        if len(self.replicas) != len(self.job.spec.replica_specs):
            self.replicas = [
                TFReplicaSet(self.clientset, self.recorder, spec, self)
                for spec in self.job.spec.replica_specs
            ]

    # -- status --------------------------------------------------------------

    def get_status(self) -> tuple[str, list[v1alpha1.TFReplicaStatus]]:
        """training.go:154-189: the chief replica's state decides success, but
        — a TPU-gang departure from the reference — ANY replica in a
        permanently-Failed state fails the whole job.  An SPMD gang is
        all-or-nothing: with a gang member permanently gone the chief would
        block in the jax.distributed barrier forever, so waiting on the chief
        alone would hang the job while holding TPU capacity."""
        chief = self.job.spec.termination_policy.chief
        chief_state = v1alpha1.REPLICA_STATE_UNKNOWN
        replica_statuses = []
        for r in self.replicas:
            replica_statuses.append(r.get_status())
            if r.spec.tf_replica_type == chief.replica_name:
                chief_state = r.get_single_replica_status(chief.replica_index)

        state = v1alpha1.STATE_UNKNOWN
        if chief_state == v1alpha1.REPLICA_STATE_RUNNING:
            state = v1alpha1.STATE_RUNNING
        elif chief_state == v1alpha1.REPLICA_STATE_FAILED:
            state = v1alpha1.STATE_FAILED
        elif chief_state == v1alpha1.REPLICA_STATE_SUCCEEDED:
            state = v1alpha1.STATE_SUCCEEDED
        spmd_types = {r.spec.tf_replica_type for r in self.replicas} & set(
            V1_SPMD_TYPE_ORDER
        )
        if state != v1alpha1.STATE_SUCCEEDED and any(
            rs.state == v1alpha1.REPLICA_STATE_FAILED
            for rs in replica_statuses
            if rs.tf_replica_type in spmd_types
        ):
            state = v1alpha1.STATE_FAILED
        return state, replica_statuses

    def update_crd_status(self) -> None:
        """training.go:295-311: write only when changed."""
        if self.job.status.to_dict() == self.status.to_dict():
            return
        self.job.status = v1alpha1.TFJobStatus.from_dict(self.status.to_dict())
        try:
            updated = self.clientset.tfjobs(
                self.job.metadata.namespace, self.job.api_version
            ).update(self.job)
            self.job = updated
            self.job.status = v1alpha1.TFJobStatus.from_dict(self.status.to_dict())
        except errors.ApiError as e:
            if errors.is_conflict(e):
                log.info("status update conflict for %s", self.name())
            else:
                raise

    # -- gang scheduling -----------------------------------------------------

    def gen_pdb_name(self) -> str:
        return f"tf-job-pdb-{self.job.metadata.name}"

    def create_pdb(self, nr_replicas: int) -> dict:
        """training.go:450-474."""
        pdb = {
            "metadata": {
                "name": self.gen_pdb_name(),
                "ownerReferences": [helpers.as_owner(self.job).to_dict()],
            },
            "spec": {
                "minAvailable": nr_replicas,
                "selector": {
                    "matchLabels": {
                        "runtime_id": self.job.spec.runtime_id,
                        "tf_job_name": self.job.metadata.name,
                    }
                },
            },
        }
        return self.clientset.pdbs(self.job.metadata.namespace).create(pdb)

    def sync_pdb(self) -> None:
        """training.go:477-511: PDB with minAvailable = Σreplicas when the
        job is distributed."""
        nr_replicas = sum(r.spec.replicas or 1 for r in self.replicas)
        if nr_replicas == 1:
            return
        try:
            self.clientset.pdbs(self.job.metadata.namespace).get(self.gen_pdb_name())
            self.pdb_name = self.gen_pdb_name()
            return
        except errors.ApiError as e:
            if not errors.is_not_found(e):
                raise
        try:
            created = self.create_pdb(nr_replicas)
            self.pdb_name = created["metadata"]["name"]
            self.recorder.eventf(
                self.job.to_dict(), "Normal", "SuccessfulCreate",
                "Created PDB: %s", self.pdb_name,
            )
        except errors.ApiError as e:
            if errors.is_already_exists(e):
                self.pdb_name = self.gen_pdb_name()
                return
            self.recorder.eventf(
                self.job.to_dict(), "Warning", "FailedCreate", "Error creating: %s", e
            )
            raise

    # -- lifecycle -----------------------------------------------------------

    def delete_resources(self) -> None:
        for r in self.replicas:
            r.delete()

    def delete(self) -> None:
        """training.go:267-292: user deletion → CleanUp + resource deletion."""
        log.info("TFJob %s deleted by the user", self.fullname())
        if self.job.status.phase != v1alpha1.PHASE_CLEANUP:
            self.status.phase = v1alpha1.PHASE_CLEANUP
        self.delete_resources()
        if self.pdb_name:
            try:
                self.clientset.pdbs(self.job.metadata.namespace).delete(self.pdb_name)
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("error deleting PDB %s: %s", self.pdb_name, e)

    def reconcile(self, config: v1alpha1.ControllerConfig, enable_gang_scheduling: bool) -> None:
        """training.go:314-428."""
        if self.job.metadata.deletion_timestamp:
            log.info("deletion timestamp set; skipping reconcile")
            return

        if self.job.status.phase == v1alpha1.PHASE_NONE and self.status.phase == v1alpha1.PHASE_NONE:
            self.setup(config)
            self.update_crd_status()

        if self.status.phase == v1alpha1.PHASE_FAILED:
            self.update_crd_status()
            return

        try:
            self.setup_replicas()
        except ValueError as e:
            self.status.reason = f"Could not create in memory datastructures; {e}"
            self.update_crd_status()
            raise

        if enable_gang_scheduling:
            try:
                self.sync_pdb()
            except errors.ApiError as e:
                log.error("SyncPdb error: %s", e)

        if self.status.phase in (v1alpha1.PHASE_CREATING, v1alpha1.PHASE_RUNNING):
            for r in self.replicas:
                r.sync_pods()
            for r in self.replicas:
                r.sync_services()
            self.update_crd_status()

            state, replica_statuses = self.get_status()
            self.status.replica_statuses = replica_statuses
            if state == v1alpha1.STATE_FAILED:
                self.status.phase = v1alpha1.PHASE_CLEANUP
                self.status.state = v1alpha1.STATE_FAILED
            elif state == v1alpha1.STATE_SUCCEEDED:
                self.status.phase = v1alpha1.PHASE_CLEANUP
                self.status.state = v1alpha1.STATE_SUCCEEDED
            elif state == v1alpha1.STATE_RUNNING:
                self.status.phase = v1alpha1.PHASE_RUNNING
                self.status.state = v1alpha1.STATE_RUNNING
            self.update_crd_status()

        if self.status.phase == v1alpha1.PHASE_CLEANUP:
            self.delete_resources()
            self.status.phase = v1alpha1.PHASE_DONE

        self.update_crd_status()

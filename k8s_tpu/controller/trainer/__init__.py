"""Per-job trainer engine (reference: pkg/trainer/)."""

from k8s_tpu.controller.trainer.training import TrainingJob  # noqa: F401
from k8s_tpu.controller.trainer.replicas import TFReplicaSet  # noqa: F401

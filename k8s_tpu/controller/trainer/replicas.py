"""TFReplicaSet — the v1 per-replica engine (reference: pkg/trainer/replicas.go).

One pod + one headless service per replica index; pod identity is the
deterministic service name (`<job40>-<type>-<runtimeid>-<idx>`,
replicas.go:520-526) while pod names get a random suffix.  State is derived
from container termination states with the retryable-exit-code contract
(replicas.go:310-363).

TPU-native change: besides the legacy ``TF_CONFIG`` (with
``environment: cloud``, replicas.go:202-213), SPMD participants (MASTER /
WORKER / TPU_WORKER) get the jax.distributed bootstrap env — the v1 job's
process table orders MASTER first so the chief is process 0.
"""

from __future__ import annotations

import json
import logging

from k8s_tpu.api import helpers, v1alpha1
from k8s_tpu.client import errors
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.util import train_util
from k8s_tpu.util.util import rand_string

log = logging.getLogger(__name__)

FAILED_CREATE_REASON = "FailedCreate"
SUCCESSFUL_CREATE_REASON = "SuccessfulCreate"

# v1 SPMD participants, in process-id order (MASTER ≡ chief ≡ process 0).
V1_SPMD_TYPE_ORDER = (v1alpha1.MASTER, v1alpha1.TPU_WORKER, v1alpha1.WORKER)


class TFReplicaSet:
    def __init__(self, clientset: Clientset, recorder, spec: v1alpha1.TFReplicaSpec, job):
        """NewTFReplicaSet (replicas.go:76-118) including its validations."""
        if spec.tf_replica_type == v1alpha1.MASTER and spec.replicas != 1:
            raise ValueError("The MASTER must have Replicas = 1")
        if spec.tf_port is None:
            raise ValueError("tfReplicaSpec.TFPort can't be None")
        if spec.template is None and spec.tf_replica_type != v1alpha1.PS:
            raise ValueError(
                f"tfReplicaSpec.Template can't be None for replica type {spec.tf_replica_type}"
            )
        if spec.tf_replica_type not in v1alpha1.VALID_REPLICA_TYPES:
            raise ValueError(
                f"tfReplicaSpec.TFReplicaType is {spec.tf_replica_type} but must be one of "
                f"{list(v1alpha1.VALID_REPLICA_TYPES)}"
            )
        self.clientset = clientset
        self.recorder = recorder
        self.spec = spec
        self.job = job

    # -- naming & labels -----------------------------------------------------

    def labels(self) -> dict[str, str]:
        """replicas.go:121-129."""
        return {
            "kubeflow.org": "",
            "job_type": self.spec.tf_replica_type,
            "runtime_id": self.job.job.spec.runtime_id,
            "tf_job_name": self.job.job.metadata.name,
        }

    def labels_by_index(self, index: int) -> dict[str, str]:
        labels = self.labels()
        labels["task_index"] = str(index)
        return labels

    def gen_name(self, index: int) -> str:
        """`<job:.40>-<type>-<runtimeid>-<idx>` (replicas.go:520-526)."""
        name = self.job.job.metadata.name[:40]
        rt = self.spec.tf_replica_type.lower()
        return f"{name}-{rt}-{self.job.job.spec.runtime_id}-{index}"

    def gen_pod_name(self, index: int) -> str:
        return f"{self.gen_name(index)}-{rand_string(5)}"

    @property
    def _namespace(self) -> str:
        return self.job.job.metadata.namespace

    # -- env -----------------------------------------------------------------

    def _env_for_index(self, index: int) -> list[dict]:
        """TF_CONFIG with environment=cloud (replicas.go:202-213) + JAX
        bootstrap env for SPMD participants."""
        tf_config = {
            "cluster": self.job.cluster_spec(),
            "task": {"type": self.spec.tf_replica_type.lower(), "index": index},
            "environment": "cloud",
        }
        env = [{"name": "TF_CONFIG", "value": json.dumps(tf_config, sort_keys=True)}]

        table = self.job.spmd_process_table()
        pid = None
        for i, (rtype, idx, _host) in enumerate(table):
            if rtype == self.spec.tf_replica_type and idx == index:
                pid = i
                break
        if pid is not None and table:
            env += [
                {"name": "JAX_COORDINATOR_ADDRESS", "value": table[0][2]},
                {"name": "JAX_NUM_PROCESSES", "value": str(len(table))},
                {"name": "JAX_PROCESS_ID", "value": str(pid)},
                {"name": "TPU_WORKER_ID", "value": str(index)},
            ]
            tpu = self.job.job.spec.tpu
            if tpu is not None and tpu.accelerator_type:
                env.append({"name": "TPU_ACCELERATOR_TYPE", "value": tpu.accelerator_type})
            if tpu is not None and tpu.topology:
                env.append({"name": "TPU_TOPOLOGY", "value": tpu.topology})
        return env

    # -- create --------------------------------------------------------------

    def create_service_with_index(self, index: int) -> dict:
        """replicas.go:139-169: headless service per index."""
        labels = self.labels_by_index(index)
        service = {
            "metadata": {
                "name": self.gen_name(index),
                "labels": labels,
                "ownerReferences": [helpers.as_owner(self.job.job).to_dict()],
            },
            "spec": {
                "selector": labels,
                "clusterIP": "None",
                "ports": [{"name": "tf-port", "port": self.spec.tf_port}],
            },
        }
        return self.clientset.services(self._namespace).create(service)

    def create_pod_with_index(self, index: int) -> dict:
        """replicas.go:172-240."""
        import copy

        template = self.spec.template or {}
        labels = self.labels_by_index(index)
        pod = {
            "metadata": {
                "name": self.gen_pod_name(index),
                "labels": dict(labels),
                "annotations": {},
                "ownerReferences": [helpers.as_owner(self.job.job).to_dict()],
            },
            "spec": copy.deepcopy(template.get("spec") or {}),
        }
        if self.job.scheduler_name():
            pod["spec"]["schedulerName"] = self.job.scheduler_name()

        for k, v in ((template.get("metadata") or {}).get("labels") or {}).items():
            pod["metadata"]["labels"].setdefault(k, v)
        for k, v in ((template.get("metadata") or {}).get("annotations") or {}).items():
            pod["metadata"]["annotations"].setdefault(k, v)

        env_vars = self._env_for_index(index)
        for c in pod["spec"].get("containers") or []:
            if c.get("name") != v1alpha1.DEFAULT_TF_CONTAINER:
                continue
            c.setdefault("env", []).extend(copy.deepcopy(env_vars))
        return self.clientset.pods(self._namespace).create(pod)

    # -- sync ----------------------------------------------------------------

    def sync_pods(self) -> None:
        """replicas.go:434-485: create the missing (non-Failed) index pods."""
        for index in range(self.spec.replicas or 1):
            pods = self.clientset.pods(self._namespace).list(
                label_selector=self.labels_by_index(index)
            )
            live = [p for p in pods if (p.get("status") or {}).get("phase") != "Failed"]
            if live:
                continue
            failed = [p for p in pods if (p.get("status") or {}).get("phase") == "Failed"]
            if (
                self.spec.tf_replica_type in V1_SPMD_TYPE_ORDER
                and failed
                and replica_status_from_pod_list(failed, v1alpha1.DEFAULT_TF_CONTAINER)
                == v1alpha1.REPLICA_STATE_FAILED
            ):
                # Permanent failure (non-retryable exit code / OOMKilled,
                # training.go:192-206) of an SPMD gang member: leave the
                # failed pod in place so GetStatus surfaces Failed instead of
                # masking it with a fresh pod.  Only retryable failures (e.g.
                # TPU preemption, SIGTERM/143) are recreated.  Non-gang
                # replicas (PS) keep the reference recreate behavior.
                continue
            log.info(
                "job %s missing pod for replica %s index %d, creating",
                self.job.name(), self.spec.tf_replica_type, index,
            )
            try:
                created = self.create_pod_with_index(index)
            except errors.ApiError as e:
                if errors.is_already_exists(e):
                    continue
                self.recorder.eventf(
                    self.job.job.to_dict(), "Warning", FAILED_CREATE_REASON,
                    "Error creating: %s", e,
                )
                raise
            self.recorder.eventf(
                self.job.job.to_dict(), "Normal", SUCCESSFUL_CREATE_REASON,
                "Created pod: %s", created["metadata"]["name"],
            )

    def sync_services(self) -> None:
        """replicas.go:488-517."""
        for index in range(self.spec.replicas or 1):
            try:
                self.clientset.services(self._namespace).get(self.gen_name(index))
                continue
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    raise
            try:
                created = self.create_service_with_index(index)
            except errors.ApiError as e:
                if errors.is_already_exists(e):
                    continue
                self.recorder.eventf(
                    self.job.job.to_dict(), "Warning", FAILED_CREATE_REASON,
                    "Error creating: %s", e,
                )
                raise
            self.recorder.eventf(
                self.job.job.to_dict(), "Normal", SUCCESSFUL_CREATE_REASON,
                "Created Service: %s", created["metadata"]["name"],
            )

    # -- status --------------------------------------------------------------

    def get_single_replica_status(self, index: int) -> str:
        """replicas.go:365-387 + replicaStatusFromPodList (:310-363).

        Departure from the reference (which maps a list error to Failed):
        a transient apiserver error yields Unknown, not Failed — job state
        must only be derived from observed pod state, otherwise one flaky
        List call tears down a healthy job; the workqueue retries anyway."""
        try:
            pods = self.clientset.pods(self._namespace).list(
                label_selector=self.labels_by_index(index)
            )
        except errors.ApiError:
            return v1alpha1.REPLICA_STATE_UNKNOWN
        return replica_status_from_pod_list(pods, v1alpha1.DEFAULT_TF_CONTAINER)

    def get_status(self) -> v1alpha1.TFReplicaStatus:
        """replicas.go:390-432: aggregate per-index states."""
        status = v1alpha1.TFReplicaStatus(
            tf_replica_type=self.spec.tf_replica_type,
            state=v1alpha1.REPLICA_STATE_UNKNOWN,
            replicas_states={},
        )
        for index in range(self.spec.replicas or 1):
            s = self.get_single_replica_status(index)
            status.replicas_states[s] = status.replicas_states.get(s, 0) + 1

        if v1alpha1.REPLICA_STATE_FAILED in status.replicas_states:
            status.state = v1alpha1.REPLICA_STATE_FAILED
        elif v1alpha1.REPLICA_STATE_RUNNING in status.replicas_states:
            status.state = v1alpha1.REPLICA_STATE_RUNNING
        elif status.replicas_states.get(v1alpha1.REPLICA_STATE_SUCCEEDED, 0) == (
            self.spec.replicas or 1
        ):
            status.state = v1alpha1.REPLICA_STATE_SUCCEEDED
        return status

    # -- delete --------------------------------------------------------------

    def delete(self) -> None:
        """replicas.go:244-307: delete owned pods + services by selector."""
        selector = {
            "runtime_id": self.job.job.spec.runtime_id,
            "tf_job_name": self.job.job.metadata.name,
            "job_type": self.spec.tf_replica_type,
        }
        self.clientset.pods(self._namespace).delete_collection(label_selector=selector)
        for index in range(self.spec.replicas or 1):
            try:
                self.clientset.services(self._namespace).delete(self.gen_name(index))
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("deleting service %s: %s", self.gen_name(index), e)


def is_retryable_termination_state(terminated: dict) -> bool:
    """training.go:192-206: OOMKilled is always permanent; otherwise the
    exit-code table decides."""
    if terminated.get("reason") == "OOMKilled":
        return False
    return train_util.is_retryable_exit_code(int(terminated.get("exitCode", -1)))


def replica_status_from_pod_list(pods: list[dict], container_name: str) -> str:
    """replicas.go:310-363: newest pod's container state decides; retryable
    terminations count as Running (kubelet will restart the container)."""
    latest = None
    for p in pods:
        if latest is None:
            latest = p
            continue
        lt = ((latest.get("status") or {}).get("startTime")) or ""
        ct = ((p.get("status") or {}).get("startTime")) or ""
        if lt < ct:
            latest = p
    if latest is None:
        return v1alpha1.REPLICA_STATE_RUNNING

    state: dict = {}
    for cs in ((latest.get("status") or {}).get("containerStatuses")) or []:
        if cs.get("name") != container_name:
            continue
        state = cs.get("state") or {}
        if (cs.get("lastState") or {}).get("terminated"):
            state = cs["lastState"]

    if state.get("running") is not None or state.get("waiting") is not None:
        return v1alpha1.REPLICA_STATE_RUNNING
    terminated = state.get("terminated")
    if terminated is not None:
        if int(terminated.get("exitCode", -1)) == 0:
            return v1alpha1.REPLICA_STATE_SUCCEEDED
        if is_retryable_termination_state(terminated):
            return v1alpha1.REPLICA_STATE_RUNNING
        return v1alpha1.REPLICA_STATE_FAILED
    return v1alpha1.REPLICA_STATE_UNKNOWN

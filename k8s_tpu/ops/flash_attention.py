"""Blockwise fused (flash) attention as Pallas TPU kernels.

Forward and backward passes never materialize the O(L^2) score matrix in
HBM: scores live one (block_q, block_k) tile at a time in VMEM, with the
online-softmax running max/sum carried in VMEM scratch across the inner
k-block grid dimension (TPU grids execute sequentially, last axis fastest,
so scratch accumulators persist across the k loop for a fixed q block).

Layout is [B, H, L, D] inside the kernels so every tile's trailing two dims
are (block, head_dim) — MXU/VPU-friendly (8,128)-tiled.  The public wrapper
accepts the framework-wide [B, L, H, D] convention and transposes at entry.

Backward follows the standard two-kernel flash decomposition:
- ``dq`` kernel: grid (B, H, nq, nk), recompute p from q/k and the saved
  logsumexp, accumulate ``ds @ k`` into a dq scratch tile;
- ``dk/dv`` kernel: grid (B, H, nk, nq), accumulate ``ds^T @ q`` and
  ``p^T @ do`` per k block.
``delta = rowsum(do * o)`` is precomputed in XLA (cheap elementwise fusion).

GQA (kv_heads < heads) is handled in the wrapper by repeating K/V across
the query-head group for the kernels and group-summing dk/dv on the way
out; mapping kv heads via BlockSpec index maps instead (no repeat) is a
known further optimization.

Causal masking skips fully-future blocks via ``pl.when`` and applies a
triangular iota mask on diagonal blocks.  Reference counterpart: none —
the reference's workloads (SURVEY.md §2.3) predate attention entirely;
this kernel serves the transformer family in k8s_tpu.models.transformer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from k8s_tpu.ops._common import auto_interpret as _auto_interpret
from k8s_tpu.ops._common import pick_block as _pick_block

NEG_INF = -1e30
# Measured on v5e (L=2048..4096, D=128): large tiles amortize grid overhead;
# (512, 1024) beats XLA's fused attention 1.6-2.4x on fwd+bwd.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _window_lo(i, block_q, block_k, window):
    """First k block a windowed q block i can see (floor-div on traced
    ints; clamped at 0)."""
    return jnp.maximum(0, (i * block_q - window + 1) // block_k)


def _window_q_lo(j, block_q, block_k):
    """First q block that can causally reach k block j."""
    return (j * block_k) // block_q


def _window_visible(i, j, block_q, block_k, window):
    """Block pair (q block i, k block j) holds >= 1 position pair with
    0 <= q_pos - k_pos < window.  The SINGLE source of truth for the
    windowed mask at block granularity: forward and both backward kernels
    must agree exactly on which blocks participate, or gradients silently
    diverge from the forward.  Callers add their own grid-bounds check."""
    return ((j * block_k < (i + 1) * block_q)
            & ((j + 1) * block_k > i * block_q - window + 1))


def _window_span_k(block_q, block_k, window, nk_total):
    """(n_inner, index_map) for grids whose INNER dim walks k blocks of a
    fixed q block i (fwd, dq)."""
    n_inner = min(nk_total, (block_q + window - 2) // block_k + 2)

    def idx(b, h, i, jj):
        return (b, h, jnp.minimum(
            _window_lo(i, block_q, block_k, window) + jj, nk_total - 1), 0)

    return n_inner, idx


def _window_span_q(block_q, block_k, window, nq_total):
    """(n_inner, index_map) for grids whose INNER dim walks q blocks of a
    fixed k block j (dkv)."""
    n_inner = min(nq_total, (block_k + window - 2) // block_q + 2)

    def idx(b, h, j, ii):
        return (b, h, jnp.minimum(
            _window_q_lo(j, block_q, block_k) + ii, nq_total - 1), 0)

    return n_inner, idx


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, window=None,
                nk_total=None):
    i = pl.program_id(2)  # q block
    jj = pl.program_id(3)  # k step (innermost: sequential on TPU)
    nk = pl.num_programs(3)

    @pl.when(jj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: k block j is visible to q block i iff some (q_pos >= k_pos)
    # pair exists, i.e. j*block_k <= i*block_q + block_q - 1.  Sliding
    # window: the inner grid dim is SHRUNK to the ~window/block_k steps a
    # q block can see (the BlockSpec index map adds the same offset), so
    # out-of-window K/V blocks are never even DMA'd — compute AND traffic
    # drop to O(L*window).
    if window is None:
        j = jj
        visible = True if not causal else (j * block_k < (i + 1) * block_q)
    else:
        j = _window_lo(i, block_q, block_k, window) + jj
        visible = (_window_visible(i, j, block_q, block_k, window)
                   & (j < nk_total))

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = q_pos >= k_pos
            if window is not None:
                keep = keep & (q_pos - k_pos < window)
            s = jnp.where(keep, s, NEG_INF)

        m_prev = m_ref[:, 0]  # [bq]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        # m_new > NEG_INF always in the visible region (causal diagonals have
        # >=1 unmasked column), but guard bidirectional fully-masked rows.
        safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - safe_m[:, None])  # [bq, bk]
        if causal:
            p = jnp.where(keep, p, 0.0)
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, jnp.exp(m_prev - safe_m))
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=-1)
        m_ref[:, 0] = m_new
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jj == nk - 1)
    def _finalize():
        l = l_ref[:, 0]
        o_ref[0, 0] = (acc_ref[...] / jnp.maximum(l, 1e-30)[:, None]).astype(
            o_ref.dtype)
        m = m_ref[:, 0]
        lse = jnp.where(m <= NEG_INF / 2, NEG_INF,
                        m + jnp.log(jnp.maximum(l, 1e-30)))
        # lse is [B, H, L, 1]: Mosaic needs the trailing block dims
        # (bq, 1) to be (8k, full-dim) tiled; a bare (1,1,bq) block is not.
        lse_ref[0, 0] = lse[:, None]


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               window=None):
    """q,k,v: [B,H,L,D].  Returns (o [B,H,L,D], lse [B,H,L,1] f32)."""
    B, H, L, D = q.shape
    Lk = k.shape[2]
    bq = _pick_block(L, block_q)
    bk = _pick_block(Lk, block_k)
    nk_total = Lk // bk
    if window is None:
        n_inner = nk_total
        k_idx = lambda b, h, i, jj: (b, h, jj, 0)  # noqa: E731
    else:
        # only the ~window/bk k blocks a q block can see enter the grid;
        # the index map re-bases each step and clamps (clamped duplicates
        # are predicated off inside the kernel)
        n_inner, k_idx = _window_span_k(bq, bk, window, nk_total)
    grid = (B, H, L // bq, n_inner)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        window=window, nk_total=nk_total)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, jj: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), k_idx),
            pl.BlockSpec((1, 1, bk, D), k_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, jj: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, jj: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, L, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, L, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((bq, D), jnp.float32),
            _vmem((bq, 128), jnp.float32),
            _vmem((bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, block_q, block_k, window=None,
               nk_total=None):
    i = pl.program_id(2)  # q block
    jj = pl.program_id(3)  # k step (inner)
    nk = pl.num_programs(3)

    @pl.when(jj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if window is None:
        j = jj
        visible = True if not causal else (j * block_k < (i + 1) * block_q)
    else:
        j = _window_lo(i, block_q, block_k, window) + jj
        visible = (_window_visible(i, j, block_q, block_k, window)
                   & (j < nk_total))

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]  # [bq] f32
        delta = delta_ref[0, 0, :, 0]  # [bq] f32

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = q_pos >= k_pos
            if window is not None:
                keep = keep & (q_pos - k_pos < window)
            s = jnp.where(keep, s, NEG_INF)
        safe_lse = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
        p = jnp.exp(s - safe_lse[:, None])
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, block_q, block_k, window=None,
                nq_total=None):
    j = pl.program_id(2)  # k block (outer)
    ii = pl.program_id(3)  # q step (inner)
    nq = pl.num_programs(3)

    @pl.when(ii == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # k block j contributes to q block i iff i's max q_pos >= j's min k_pos.
    if window is None:
        i = ii
        visible = True if not causal else ((i + 1) * block_q > j * block_k)
    else:
        # first q block whose positions can reach k block j causally
        i = _window_q_lo(j, block_q, block_k) + ii
        visible = (_window_visible(i, j, block_q, block_k, window)
                   & (i < nq_total))

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = q_pos >= k_pos
            if window is not None:
                keep = keep & (q_pos - k_pos < window)
            s = jnp.where(keep, s, NEG_INF)
        safe_lse = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
        p = jnp.exp(s - safe_lse[:, None])
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)

        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # p^T @ do -> [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # ds^T @ q -> [bk, D]

    @pl.when(ii == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k,
               interpret, window=None):
    """All arrays [B,H,L,D] (lse [B,H,L]).  Returns (dq, dk, dv)."""
    B, H, L, D = q.shape
    Lk = k.shape[2]
    bq = _pick_block(L, block_q)
    bk = _pick_block(Lk, block_k)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [B, H, L, 1]
    nk_total = Lk // bk
    nq_total = L // bq

    if window is None:
        n_inner_k = nk_total
        k_idx = lambda b, h, x, y: (b, h, y, 0)  # noqa: E731
    else:
        n_inner_k, k_idx = _window_span_k(bq, bk, window, nk_total)
    qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, x, y: (b, h, x, 0))
    kspec = pl.BlockSpec((1, 1, bk, D), k_idx)
    rowspec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, x, y: (b, h, x, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, window=window,
                          nk_total=nk_total),
        grid=(B, H, nq_total, n_inner_k),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct((B, H, L, D), q.dtype)],
        scratch_shapes=[_vmem((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)[0]

    # dk/dv: k block is the outer loop, q the inner accumulation loop.
    if window is None:
        n_inner_q = nq_total
        q_idx = lambda b, h, y, x: (b, h, x, 0)  # noqa: E731
    else:
        n_inner_q, q_idx = _window_span_q(bq, bk, window, nq_total)
    qspec2 = pl.BlockSpec((1, 1, bq, D), q_idx)
    kspec2 = pl.BlockSpec((1, 1, bk, D), lambda b, h, y, x: (b, h, y, 0))
    rowspec2 = pl.BlockSpec((1, 1, bq, 1), q_idx)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, window=window,
                          nq_total=nq_total),
        grid=(B, H, nk_total, n_inner_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Lk, D), v.dtype),
        ],
        scratch_shapes=[_vmem((bk, D), jnp.float32),
                        _vmem((bk, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper (public API, [B, L, H, D] layout)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret, window=None):
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                      window)
    return o


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                   window=None):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                        window)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, window,
                   res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, scale, causal,
                            block_q, block_k, interpret, window)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = True, scale: float | None = None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None,
                    window: int | None = None):
    """Fused attention.  q: [B, L, H, D]; k, v: [B, Lk, Hkv, D] with
    Hkv dividing H (grouped-query).  Returns [B, L, H, D] in q.dtype.

    ``window`` (sliding-window attention, Mistral/Gemma-style): each query
    attends only the ``window`` most recent positions including itself
    (0 <= q_pos - k_pos < window).  Causal-only.  The inner grid dimension
    of all three kernels (fwd, dq, dkv) shrinks to the ~window/block_k
    steps a block can see, with index maps re-based per block — so
    out-of-window K/V tiles are never DMA'd and both compute and HBM
    traffic drop from O(L^2) to O(L*window).

    Differentiable (custom VJP with flash backward kernels).  ``interpret``
    defaults to auto: Pallas interpret mode on CPU backends, compiled Mosaic
    on TPU.
    """
    B, L, H, D = q.shape
    Hkv = k.shape[2]
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (sliding-window "
                             "attention is a causal construction)")
        if window < 1:
            raise ValueError(f"window must be >= 1 (got {window})")
    if causal and L != k.shape[1]:
        # The kernels' causal mask assumes q and k positions are both
        # 0-aligned; with Lk != L (e.g. kv-cache decode, where q positions
        # are conventionally offset by Lk - L) it would silently mask the
        # wrong entries.  Self-attention is the only supported causal shape.
        raise ValueError(
            f"causal=True requires L == Lk (got L={L}, Lk={k.shape[1]}); "
            "use causal=False or 0-pad q to the kv length"
        )
    if scale is None:
        scale = D ** -0.5
    if Hkv != H:
        if H % Hkv:
            raise ValueError(f"heads {H} not a multiple of kv_heads {Hkv}")
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # kernels use [B, H, L, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, float(scale), bool(causal), int(block_q),
                 int(block_k), _auto_interpret(interpret),
                 int(window) if window is not None else None)
    return out.transpose(0, 2, 1, 3)

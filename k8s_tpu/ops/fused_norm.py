"""Fused RMSNorm row kernel (Pallas TPU).

One VMEM pass per row block: mean-of-squares, rsqrt, scale — no HBM round
trip for the intermediate variance.  Forward is a Pallas kernel; backward
is a hand-derived XLA VJP (the bwd math is a short elementwise+reduction
chain XLA fuses completely, so a kernel would buy nothing).

Semantics match k8s_tpu.models.transformer.RMSNorm's plain path exactly,
including its dtype promotion: the normalized activation is rounded to
x.dtype, then multiplied by the (typically f32) scale, so the output dtype
is ``result_type(x, scale)``.

Used by the transformer family when ``TransformerConfig.use_fused_norm`` is
set.  Reference counterpart: none (SURVEY.md §2 — the reference has no
accelerator kernels).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from k8s_tpu.ops._common import auto_interpret, pick_block


def _rms_kernel(x_ref, scale_ref, o_ref, *, eps, x_dtype):
    x = x_ref[...].astype(jnp.float32)  # [br, D]
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    # Round the normalized activation to x.dtype before scaling — exact
    # parity with the unfused module's `(...).astype(x.dtype) * scale`.
    y = y.astype(x_dtype).astype(jnp.float32)
    o_ref[...] = (y * scale_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms(x2d, scale, eps, interpret):
    N, D = x2d.shape
    br = pick_block(N, 256)
    out_dtype = jnp.result_type(x2d.dtype, scale.dtype)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps, x_dtype=x2d.dtype),
        grid=(N // br,),
        in_specs=[
            pl.BlockSpec((br, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), out_dtype),
        interpret=interpret,
    )(x2d, scale)


def _rms_fwd(x2d, scale, eps, interpret):
    return _rms(x2d, scale, eps, interpret), (x2d, scale)


def _rms_bwd(eps, interpret, res, g):
    # y_i = xhat_i * s_i with xhat = x * r, r = rsqrt(mean(x^2) + eps).
    # dr/dx_i = -(x_i / D) r^3, which gives
    #   dx = r * (g*s - xhat * mean(g*s * xhat))
    # (verified against jax autodiff across eps scales).
    x2d, scale = res
    x = x2d.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    s32 = scale.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + eps)
    xhat = x * r
    dscale = jnp.sum(g32 * xhat, axis=0).astype(scale.dtype)
    gs = g32 * s32
    dx = r * (gs - xhat * jnp.mean(gs * xhat, axis=-1, keepdims=True))
    return dx.astype(x2d.dtype), dscale


_rms.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, scale, *, eps: float = 1e-6, interpret: bool | None = None):
    """RMSNorm over the last axis.  x: [..., D]; scale: [D].

    Returns ``result_type(x, scale)``; differentiable.  ``interpret`` auto-
    selects Pallas interpret mode on CPU backends.
    """
    orig_shape = x.shape
    D = orig_shape[-1]
    x2d = x.reshape(-1, D)
    out = _rms(x2d, scale, float(eps), auto_interpret(interpret))
    return out.reshape(orig_shape)

"""Shared helpers for the Pallas ops layer."""

from __future__ import annotations

import jax


def pick_block(length: int, preferred: int) -> int:
    """Largest divisor of ``length`` that is <= preferred (>=1)."""
    b = min(preferred, length)
    while length % b:
        b -= 1
    return b


def auto_interpret(interpret: bool | None) -> bool:
    """Resolve the interpret flag: explicit value wins, else Pallas interpret
    mode on CPU backends (tests, driver dryrun) and compiled Mosaic on TPU."""
    if interpret is not None:
        return interpret
    return jax.default_backend() == "cpu"

"""Pallas TPU kernels for the hot ops of the workload layer.

The reference has no accelerator kernels at all (SURVEY.md §2: the operator
is pure K8s plumbing; compute lived in user TF1 graphs).  In the TPU-native
rebuild the workload layer owns the FLOPs, so the hot paths get hand-written
Pallas kernels where XLA's automatic fusion isn't enough:

- :mod:`k8s_tpu.ops.flash_attention` — blockwise fused attention
  (forward + backward, causal + bidirectional, GQA) that never materializes
  the O(L^2) score matrix in HBM;
- :mod:`k8s_tpu.ops.fused_norm` — RMSNorm row kernel;
- :mod:`k8s_tpu.ops.fused_ce` — chunked-vocabulary fused linear +
  cross-entropy (the LM head's [T, vocab] logits never materialize).

All kernels run in Pallas interpret mode on CPU (used by the test suite and
the driver's virtual-device dryrun) and compile to Mosaic on TPU.
"""

from k8s_tpu.ops.flash_attention import flash_attention  # noqa: F401
from k8s_tpu.ops.fused_ce import fused_linear_cross_entropy  # noqa: F401
from k8s_tpu.ops.fused_norm import rms_norm  # noqa: F401

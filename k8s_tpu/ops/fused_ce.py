"""Fused linear + cross-entropy over vocabulary chunks.

The LM loss's logits tensor is the largest activation in training: at
B=8, L=1024, V=32000 the [T, V] f32 logits are ~1 GB and exist only to be
immediately reduced to one scalar.  This op fuses the tied-embedding head
matmul into an online-softmax loss computed chunk-by-chunk over the
vocabulary, so peak memory is [T, vocab_chunk] — the flash-attention idea
applied to the LM head (no reference counterpart; the reference has no
LM path at all).

Semantics match ``train.cross_entropy_loss`` exactly: matmul in the
model dtype with f32 accumulation, loss math in f32, out-of-range targets
(the ``label = -1`` padding idiom) contribute zero loss and zero gradient
while still counting in the mean's denominator.

Forward runs a ``lax.scan`` over vocabulary chunks carrying the online
(max, sum) softmax statistics plus the target logit; backward (custom
VJP) rescans, recomputing each chunk's logits against the saved
log-sum-exp — FLOPs for memory, the same trade flash attention makes.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _flatten(hidden, targets):
    if hidden.ndim == 3:
        B, L, d = hidden.shape
        return hidden.reshape(B * L, d), targets.reshape(B * L)
    return hidden, targets


@lru_cache(maxsize=None)
def _make_fused_ce(vocab_chunk: int, z_loss: float):
    def pad_vocab(emb):
        V = emb.shape[0]
        n_chunks = -(-V // vocab_chunk)
        pad = n_chunks * vocab_chunk - V
        if pad:
            emb = jnp.pad(emb, ((0, pad), (0, 0)))
        return emb, n_chunks

    def chunk_logits(h, emb_pad, c):
        """[T, C] f32 logits of chunk c, padded columns masked to NEG_INF."""
        emb_c = lax.dynamic_slice_in_dim(
            emb_pad, c * vocab_chunk, vocab_chunk, axis=0)
        logits = jnp.einsum(
            "td,vd->tv", h, emb_c.astype(h.dtype),
            preferred_element_type=jnp.float32,
        )
        return logits

    def fwd_stats(h, emb_pad, n_chunks, targets, V):
        T = h.shape[0]
        col = jnp.arange(vocab_chunk)

        def body(carry, c):
            m, s, t = carry
            logits = chunk_logits(h, emb_pad, c)
            logits = jnp.where((c * vocab_chunk + col)[None, :] < V,
                               logits, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
            s = s * jnp.exp(m - m_new) + jnp.sum(
                jnp.exp(logits - m_new[:, None]), axis=-1)
            local = targets - c * vocab_chunk
            in_chunk = (local >= 0) & (local < vocab_chunk)
            picked = jnp.take_along_axis(
                logits, jnp.clip(local, 0, vocab_chunk - 1)[:, None], axis=1
            )[:, 0]
            t = t + jnp.where(in_chunk, picked, 0.0)
            return (m_new, s, t), None

        init = (jnp.full((T,), NEG_INF, jnp.float32),
                jnp.zeros((T,), jnp.float32),
                jnp.zeros((T,), jnp.float32))
        (m, s, t), _ = lax.scan(body, init, jnp.arange(n_chunks))
        lse = m + jnp.log(jnp.maximum(s, 1e-30))
        return lse, t

    def total_loss(lse, t, valid, T):
        per_token = lse - t
        if z_loss:
            # PaLM-style stabilizer: z_loss * log(Z)^2 keeps logits from
            # drifting; lse is already the online log-partition
            per_token = per_token + z_loss * jnp.square(lse)
        return jnp.sum(jnp.where(valid, per_token, 0.0)) / T

    def primal(hidden, emb, targets):
        h, tg = _flatten(hidden, targets)
        V = emb.shape[0]
        emb_pad, n_chunks = pad_vocab(emb)
        lse, t = fwd_stats(h, emb_pad, n_chunks, tg, V)
        valid = (tg >= 0) & (tg < V)
        return total_loss(lse, t, valid, h.shape[0])

    def fwd(hidden, emb, targets):
        h, tg = _flatten(hidden, targets)
        V = emb.shape[0]
        emb_pad, n_chunks = pad_vocab(emb)
        lse, t = fwd_stats(h, emb_pad, n_chunks, tg, V)
        valid = (tg >= 0) & (tg < V)
        loss = total_loss(lse, t, valid, h.shape[0])
        return loss, (hidden, emb, targets, lse)

    def bwd(res, g):
        hidden, emb, targets, lse = res
        h, tg = _flatten(hidden, targets)
        T, d = h.shape
        V = emb.shape[0]
        emb_pad, n_chunks = pad_vocab(emb)
        valid = (tg >= 0) & (tg < V)
        # d loss / d logits[i, v] = valid_i * (softmax_iv - onehot_iv) / T;
        # the z-loss term adds valid_i * 2*z*lse_i * softmax_iv / T
        coeff = (g / T) * valid.astype(jnp.float32)
        p_coeff = coeff * (1.0 + 2.0 * z_loss * lse) if z_loss else coeff
        col = jnp.arange(vocab_chunk)

        def body(carry, c):
            dh, demb_pad = carry
            logits = chunk_logits(h, emb_pad, c)
            logits = jnp.where((c * vocab_chunk + col)[None, :] < V,
                               logits, NEG_INF)
            p = jnp.exp(logits - lse[:, None])  # masked cols -> 0
            local = tg - c * vocab_chunk
            in_chunk = (local >= 0) & (local < vocab_chunk)
            onehot = (col[None, :] == jnp.clip(
                local, 0, vocab_chunk - 1)[:, None]) & in_chunk[:, None]
            dl = p * p_coeff[:, None] - onehot.astype(jnp.float32) * coeff[:, None]  # [T, C]
            emb_c = lax.dynamic_slice_in_dim(
                emb_pad, c * vocab_chunk, vocab_chunk, axis=0)
            dh = dh + jnp.einsum(
                "tv,vd->td", dl, emb_c.astype(jnp.float32),
                preferred_element_type=jnp.float32)
            demb_c = jnp.einsum(
                "tv,td->vd", dl, h.astype(jnp.float32),
                preferred_element_type=jnp.float32)
            demb_pad = lax.dynamic_update_slice_in_dim(
                demb_pad, demb_c, c * vocab_chunk, axis=0)
            return (dh, demb_pad), None

        init = (jnp.zeros((T, d), jnp.float32),
                jnp.zeros_like(emb_pad, dtype=jnp.float32))
        (dh, demb_pad), _ = lax.scan(body, init, jnp.arange(n_chunks))
        dh = dh.astype(hidden.dtype).reshape(hidden.shape)
        demb = demb_pad[:V].astype(emb.dtype)
        return dh, demb, None

    fused = jax.custom_vjp(primal)
    fused.defvjp(fwd, bwd)
    return fused


def fused_linear_cross_entropy(hidden, emb, targets, *,
                               vocab_chunk: int = 8192,
                               z_loss: float = 0.0):
    """Mean next-token-style CE of ``hidden @ emb.T`` against ``targets``
    without materializing the [T, V] logits.

    hidden: [B, L, d] or [T, d] in the model dtype (the matmul runs in
    this dtype with f32 accumulation, like the unfused head);
    emb: [V, d] (any float dtype; cast per chunk);
    targets: int [B, L] or [T]; out-of-range ids contribute zero.
    ``z_loss``: PaLM-style stabilizer weight on log(Z)^2 (0 disables).
    """
    if vocab_chunk < 1:
        raise ValueError(f"vocab_chunk must be >= 1, got {vocab_chunk}")
    return _make_fused_ce(int(vocab_chunk), float(z_loss))(hidden, emb, targets)

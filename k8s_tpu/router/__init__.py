"""Serving fleet front door (ISSUE 13): prefix-affine request router +
metric-driven gang autoscaler — the WRITE side of ROADMAP item 1 (the
fleet plane, PR 8, is the read side).

Two halves:

- :mod:`k8s_tpu.router.router` — a standalone HTTP front-door process
  that discovers a serving TFJob's pod endpoints (informer cache /
  headless-service DNS via a ``targets_fn``, the fleet-discovery
  contract), proxies ``/v1/generate`` with consistent-hash
  **prefix-affine** placement (block-aligned fingerprints, same block
  size as the engine's radix PrefixTree), least-outstanding fallback,
  bounded 503 retries against the next ring candidate, health eviction
  + probe re-admission, clean SIGTERM drain, and its own ``/metrics`` +
  ``/debug/router``.
- :mod:`k8s_tpu.router.autoscale` — an operator-side control loop (off
  by default, ``K8S_TPU_AUTOSCALE``) that reads the fleet plane's
  ``serve_queue_depth`` / ``serve_batch_occupancy`` / SLO burn rollups
  and scales the serving TFJob's replica count inside spec-declared
  min/max bounds with hysteresis + cooldown; scale-up is gang-admitted
  through the PR 4 scheduler (or parked Queued — never partially
  placed) and scale-down drains the victim through the router before
  its chips free.

Mirrors the ``fleet.active()`` pattern: one process-global *active
router* so the metrics server and dashboard serve ``/debug/router``
without a router reference, 404-with-explicit-body while inactive.

Stdlib-only by policy (``harness/py_checks.py`` gates it like
``fleet/``/``flight/``); sibling stdlib-only packages may be imported
(the transitive guarantee holds — ``fleet`` for discovery types and
per-pod rollup reads).
"""

from __future__ import annotations

import os
from typing import Optional

from k8s_tpu.router.autoscale import (  # noqa: F401 (public surface)
    AutoscaleLoop,
    Autoscaler,
    enabled_from_env as autoscale_enabled_from_env,
    interval_from_env as autoscale_interval_from_env,
)
from k8s_tpu.router.debug import (  # noqa: F401
    debug_router_response,
    router_index_entry,
)
from k8s_tpu.router.ring import (  # noqa: F401
    DEFAULT_AFFINITY_BLOCKS,
    HashRing,
    fingerprint_request,
    fingerprint_tokens,
)
from k8s_tpu.router.router import (  # noqa: F401
    DEFAULT_BLOCK_SIZE,
    DEFAULT_RETRY_BUDGET,
    POLICY_AFFINE,
    POLICY_LEAST,
    POLICY_RANDOM,
    VALID_POLICIES,
    Backend,
    Router,
    RouterServer,
)

# -- env knobs ----------------------------------------------------------------

ENV_PORT = "K8S_TPU_ROUTER_PORT"
ENV_BLOCK_SIZE = "K8S_TPU_ROUTER_BLOCK_SIZE"
ENV_AFFINITY_BLOCKS = "K8S_TPU_ROUTER_AFFINITY_BLOCKS"
ENV_RETRY_BUDGET = "K8S_TPU_ROUTER_RETRY_BUDGET"
ENV_POLICY = "K8S_TPU_ROUTER_POLICY"
ENV_PHASE_TOKENS = "K8S_TPU_ROUTER_PHASE_TOKENS"
ENV_HEDGE_S = "K8S_TPU_ROUTER_HEDGE_S"


def _int_from_env(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
    except ValueError:
        return default
    return v if v > 0 else default


def block_size_from_env() -> int:
    """K8S_TPU_ROUTER_BLOCK_SIZE: the engine's KV block size the
    fingerprint aligns to (must match the serving pods' PrefixTree, or
    affinity degrades to approximate prefix grouping — still correct,
    just fewer shared-block hits)."""
    return _int_from_env(ENV_BLOCK_SIZE, DEFAULT_BLOCK_SIZE)


def affinity_blocks_from_env() -> int:
    return _int_from_env(ENV_AFFINITY_BLOCKS, DEFAULT_AFFINITY_BLOCKS)


def retry_budget_from_env() -> int:
    raw = os.environ.get(ENV_RETRY_BUDGET, "")
    try:
        v = int(raw)
    except ValueError:
        return DEFAULT_RETRY_BUDGET
    return v if v >= 0 else DEFAULT_RETRY_BUDGET


def policy_from_env() -> str:
    v = os.environ.get(ENV_POLICY, "").strip().lower()
    return v if v in VALID_POLICIES else POLICY_AFFINE


def phase_tokens_from_env() -> Optional[int]:
    """K8S_TPU_ROUTER_PHASE_TOKENS: prompts of at least this many
    tokens route to the prefill tier (disaggregated phase split,
    ISSUE 15); unset/0 = off.  Only engages while prefill-role pods
    exist, so it is safe to leave set on a collapsed fleet."""
    v = _int_from_env(ENV_PHASE_TOKENS, 0)
    return v or None


def hedge_s_from_env() -> float:
    """K8S_TPU_ROUTER_HEDGE_S: seconds before hedging a stuck
    idempotent request against the next ring candidate (first response
    wins); unset/0 = off — a p99-derived value like 2x the fleet's
    serve_request_duration p99 is the intended setting."""
    raw = os.environ.get(ENV_HEDGE_S, "")
    try:
        v = float(raw)
    except ValueError:
        return 0.0
    return v if v > 0 else 0.0


# -- process-global active router (fleet.active() pattern) --------------------

_ACTIVE: Optional[Router] = None


def set_active(router: Optional[Router]) -> None:
    global _ACTIVE
    _ACTIVE = router


def active() -> Optional[Router]:
    return _ACTIVE


def debug_response(query: str = "") -> tuple[int, str, str]:
    """The /debug/router endpoint body for the active router."""
    return debug_router_response(_ACTIVE, query)

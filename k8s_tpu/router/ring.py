"""Consistent-hash ring + block-aligned prefix fingerprints.

The affinity contract (ISSUE 13): two requests that share a prompt
prefix of at least ``affinity_blocks * block_size`` tokens must hash to
the SAME fingerprint, where ``block_size`` is the serving engine's KV
block size (models/kvblocks.PrefixTree) — because that is the unit the
radix tree caches at.  A fingerprint shorter than one full block is no
fingerprint at all (the tree cannot share a partial block by reference;
routing on it would pin unrelated traffic to one pod for zero reuse).

The ring is classic consistent hashing: ``vnodes`` points per node on a
2^64 circle keyed by ``sha1(node#i)``; a lookup walks clockwise from
``sha1(fingerprint)``.  Properties the tests pin:

- **deterministic**: same membership + key -> same node, across
  processes (sha1, not ``hash()`` — PYTHONHASHSEED must not move
  traffic);
- **minimal remap**: adding/removing one node only remaps keys whose
  clockwise-nearest point belonged to that node (~1/N of the keyspace),
  so a pod join/leave does not reshuffle the whole fleet's warm KV;
- **candidate order**: ``candidates(key)`` yields every node, nearest
  first, each exactly once — the 503-retry walk visits distinct pods.
- **weighted share** (ISSUE 14): a node added with ``weight=w`` plants
  ``round(vnodes * w)`` points (min 1), so its expected keyspace share
  is proportional to ``w`` — heterogeneous pod sizes (a 4-chip
  tensor-parallel pod next to 1-chip pods) get traffic proportional to
  capacity.  Weights flow from the ``kubeflow.org/fleet-serve-weight``
  pod annotation through fleet discovery; a weight CHANGE re-plants
  only that node's points (everyone else's keyspace is untouched — the
  minimal-remap property extends to resizes).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Optional

DEFAULT_VNODES = 64
DEFAULT_AFFINITY_BLOCKS = 2


def _point(data: str) -> int:
    return int.from_bytes(hashlib.sha1(data.encode()).digest()[:8], "big")


def fingerprint_tokens(tokens, block_size: int,
                       affinity_blocks: int = DEFAULT_AFFINITY_BLOCKS
                       ) -> Optional[str]:
    """Block-aligned fingerprint of a token-id prompt, or None when the
    prompt has no full block (affinity would be pure pinning).

    Uses the first ``min(affinity_blocks, full_blocks)`` FULL blocks —
    never a partial block, so the fingerprint only covers tokens the
    target pod's prefix tree can actually share by reference, and a
    unique tail shorter than one block cannot split a shared template
    across pods."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    n_full = len(tokens) // block_size
    if n_full < 1:
        return None
    use = min(max(1, affinity_blocks), n_full) * block_size
    h = hashlib.sha1()
    h.update(f"{block_size}:".encode())
    for t in tokens[:use]:
        h.update(f"{int(t)},".encode())
    return h.hexdigest()


def fingerprint_request(req: dict, block_size: int,
                        affinity_blocks: int = DEFAULT_AFFINITY_BLOCKS
                        ) -> Optional[str]:
    """Fingerprint a /v1/generate JSON body: token requests fingerprint
    their ids directly; text requests fingerprint the UTF-8 byte stream
    (the serving tokenizer is byte-level, so byte runs ARE token runs)."""
    tokens = req.get("tokens")
    if isinstance(tokens, list):
        try:
            return fingerprint_tokens([int(t) for t in tokens], block_size,
                                      affinity_blocks)
        except (TypeError, ValueError):
            return None  # malformed: the backend answers the 400
    text = req.get("text")
    if isinstance(text, str):
        return fingerprint_tokens(text.encode("utf-8", "replace"),
                                  block_size, affinity_blocks)
    return None


class HashRing:
    """Deterministic consistent-hash ring over string node names."""

    def __init__(self, nodes: Iterable = (),
                 vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._nodes: set[str] = set()
        self._weights: dict[str, float] = {}
        self._points: list[int] = []     # sorted ring positions
        self._owners: list[str] = []     # owner of each position
        for n in nodes:
            if isinstance(n, tuple):
                self.add(n[0], weight=n[1])
            else:
                self.add(n)

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node: str) -> bool:
        return node in self._nodes

    @property
    def nodes(self) -> list[str]:
        return sorted(self._nodes)

    def weight(self, node: str) -> float:
        return self._weights.get(node, 0.0)

    def _npoints(self, weight: float) -> int:
        # min 1: a present node must own SOME keyspace or lookup could
        # never reach it even as the only member
        return max(1, int(round(self.vnodes * weight)))

    def add(self, node: str, weight: float = 1.0) -> None:
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        if node in self._nodes:
            if self._weights.get(node) == float(weight):
                return
            # weight change: re-plant ONLY this node's points (minimal
            # remap extends to resizes — nobody else's keyspace moves)
            self.remove(node)
        self._nodes.add(node)
        self._weights[node] = float(weight)
        for i in range(self._npoints(weight)):
            p = _point(f"{node}#{i}")
            idx = bisect.bisect_left(self._points, p)
            # sha1 collisions between distinct (node, vnode) labels are
            # not a correctness hazard, just an owner preference; keep
            # insertion deterministic by ordering equal points by name
            while idx < len(self._points) and self._points[idx] == p \
                    and self._owners[idx] < node:
                idx += 1
            self._points.insert(idx, p)
            self._owners.insert(idx, node)

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._weights.pop(node, None)
        keep = [(p, o) for p, o in zip(self._points, self._owners)
                if o != node]
        self._points = [p for p, _o in keep]
        self._owners = [o for _p, o in keep]

    def replace(self, nodes: Iterable) -> None:
        """Reconcile membership to exactly ``nodes`` — names, or
        ``(name, weight)`` pairs, or a name→weight mapping (minimal
        edits: surviving nodes at an unchanged weight keep their ring
        points, so the minimal-remap property holds across discovery
        refreshes, not just single add/remove calls)."""
        if isinstance(nodes, dict):
            target = {str(k): float(v) for k, v in nodes.items()}
        else:
            target = {}
            for n in nodes:
                if isinstance(n, tuple):
                    target[str(n[0])] = float(n[1])
                else:
                    target[str(n)] = 1.0
        for n in list(self._nodes - set(target)):
            self.remove(n)
        for n in sorted(target):
            if n not in self._nodes or self._weights.get(n) != target[n]:
                self.add(n, weight=target[n])

    def lookup(self, key: str) -> Optional[str]:
        """The key's owner (clockwise-nearest point), or None when empty."""
        if not self._points:
            return None
        idx = bisect.bisect_right(self._points, _point(key))
        if idx == len(self._points):
            idx = 0
        return self._owners[idx]

    def candidates(self, key: str, limit: Optional[int] = None) -> list[str]:
        """Every node in clockwise ring order from the key, nearest
        first, each exactly once — the retry walk for idempotent 503s."""
        if not self._points:
            return []
        limit = len(self._nodes) if limit is None else limit
        out: list[str] = []
        seen: set[str] = set()
        start = bisect.bisect_right(self._points, _point(key))
        n = len(self._points)
        for off in range(n):
            owner = self._owners[(start + off) % n]
            if owner not in seen:
                seen.add(owner)
                out.append(owner)
                if len(out) >= limit:
                    break
        return out

    def state(self) -> dict:
        """The /debug/router ring payload: membership, vnode count, and
        per-node keyspace share (fraction of the circle owned)."""
        shares: dict[str, float] = {n: 0.0 for n in self._nodes}
        if self._points:
            full = 2 ** 64
            prev = self._points[-1] - full
            for p, o in zip(self._points, self._owners):
                shares[o] += (p - prev) / full
                prev = p
        return {
            "nodes": self.nodes,
            "vnodes": self.vnodes,
            "points": len(self._points),
            "weights": {n: self._weights.get(n, 1.0)
                        for n in self.nodes},
            "keyspace_share": {n: round(s, 4)
                               for n, s in sorted(shares.items())},
        }

"""Metric-driven gang autoscaler for serving TFJobs (ISSUE 13).

The write side of the fleet plane: an operator-side control loop reads
``serve_queue_depth`` / ``serve_batch_occupancy`` rollups and the SLO
burn state from the ACTIVE fleet plane and computes a target replica
count inside the spec-declared ``autoscale`` min/max bounds.  Decisions
are deliberately sluggish:

- **hysteresis**: a scale signal must persist for ``hold_evals``
  consecutive evaluations before it acts (burn-rate flicker or one
  queue spike cannot thrash the gang);
- **cooldown**: after any applied change the job is frozen for
  ``cooldown_s`` (the new capacity must show up in the windows before
  it is judged);
- **step**: one replica per action — each step flows through the gang
  scheduler, so capacity changes stay whole-gang-atomic.

Application is hook-based (the controller wires the hooks; this module
stays stdlib-only and knows nothing about TFJobs):

- ``reserve_fn(job, target_replicas)`` — extend the job's chip
  reservation for a scale-UP before the spec is patched.  False parks
  the scale-up: the job keeps its current size (never partially
  placed), the pending target is recorded and surfaced (``parked``
  state + an ``autoscale_parked`` event through ``event_fn``), and the
  loop retries each tick until capacity frees.
- ``drain_fn(job, victims)`` — route the scale-DOWN victims through
  the router's per-backend drain (refuse new placements, finish
  in-flight) BEFORE the patch that releases their chips.
- ``apply_fn(job, target_replicas)`` — patch the serving TFJob's
  replica count; the controller's normal sync then creates/deletes the
  pods and resizes the reservation.

Off by default: the controller only starts the loop when
``K8S_TPU_AUTOSCALE`` is truthy (scrape_enabled_from_env parity).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from k8s_tpu.analysis import checkedlock

log = logging.getLogger(__name__)

ENV_ENABLE = "K8S_TPU_AUTOSCALE"
ENV_INTERVAL = "K8S_TPU_AUTOSCALE_INTERVAL_S"
ENV_UP_QUEUE = "K8S_TPU_AUTOSCALE_UP_QUEUE"
ENV_DOWN_QUEUE = "K8S_TPU_AUTOSCALE_DOWN_QUEUE"
ENV_COOLDOWN = "K8S_TPU_AUTOSCALE_COOLDOWN_S"
ENV_HOLD = "K8S_TPU_AUTOSCALE_HOLD"

DEFAULT_INTERVAL_S = 5.0
DEFAULT_UP_QUEUE_DEPTH = 4.0     # mean queued requests per pod
DEFAULT_DOWN_QUEUE_DEPTH = 0.5
DEFAULT_DOWN_OCCUPANCY = 1.0     # mean active slots per pod
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_HOLD_EVALS = 2


def enabled_from_env() -> bool:
    """K8S_TPU_AUTOSCALE: truthy starts the controller's autoscale loop
    (default off — replica counts stay exactly as specced)."""
    return os.environ.get(ENV_ENABLE, "").lower() in ("1", "true", "on",
                                                      "yes")


def _float_env(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, ""))
    except ValueError:
        return default
    return v if v > 0 else default


def interval_from_env() -> float:
    return _float_env(ENV_INTERVAL, DEFAULT_INTERVAL_S)


def _int_env(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
    except ValueError:
        return default
    return v if v > 0 else default


def autoscaler_kwargs_from_env() -> dict:
    """The threshold knobs as Autoscaler constructor kwargs — read here
    so every documented K8S_TPU_AUTOSCALE_* knob actually steers the
    loop (the controller passes these through)."""
    return {
        "up_queue_depth": _float_env(ENV_UP_QUEUE,
                                     DEFAULT_UP_QUEUE_DEPTH),
        "down_queue_depth": _float_env(ENV_DOWN_QUEUE,
                                       DEFAULT_DOWN_QUEUE_DEPTH),
        "cooldown_s": _float_env(ENV_COOLDOWN, DEFAULT_COOLDOWN_S),
        "hold_evals": _int_env(ENV_HOLD, DEFAULT_HOLD_EVALS),
    }


class Decision:
    """One evaluation's outcome."""

    __slots__ = ("job", "current", "target", "direction", "reason",
                 "signals", "parked")

    def __init__(self, job: str, current: int, target: int,
                 direction: str, reason: str, signals: dict,
                 parked: bool = False):
        self.job = job
        self.current = current
        self.target = target
        self.direction = direction  # "up" | "down" | "hold"
        self.reason = reason
        self.signals = signals
        self.parked = parked

    def to_dict(self) -> dict:
        return {"job": self.job, "current": self.current,
                "target": self.target, "direction": self.direction,
                "reason": self.reason, "signals": self.signals,
                "parked": self.parked}


class Autoscaler:
    """Pure decision engine: plane rollups in, clamped targets out, with
    per-job hysteresis + cooldown state.  Thread-safe; no I/O."""

    def __init__(self, plane_fn: Callable[[], object], *,
                 up_queue_depth: float = DEFAULT_UP_QUEUE_DEPTH,
                 down_queue_depth: float = DEFAULT_DOWN_QUEUE_DEPTH,
                 down_occupancy: float = DEFAULT_DOWN_OCCUPANCY,
                 hold_evals: int = DEFAULT_HOLD_EVALS,
                 cooldown_s: float = DEFAULT_COOLDOWN_S):
        if up_queue_depth <= down_queue_depth:
            raise ValueError(
                "up_queue_depth must exceed down_queue_depth "
                f"(got {up_queue_depth} <= {down_queue_depth}: the "
                "hysteresis band would be empty and the loop would flap)")
        self._plane_fn = plane_fn
        self.up_queue_depth = float(up_queue_depth)
        self.down_queue_depth = float(down_queue_depth)
        self.down_occupancy = float(down_occupancy)
        self.hold_evals = max(1, int(hold_evals))
        self.cooldown_s = float(cooldown_s)
        self._lock = checkedlock.make_lock("router.autoscale")
        # job -> {"streak_up", "streak_down", "last_action", "parked"}
        self._state: dict[str, dict] = {}

    def _signals(self, job: str) -> dict:
        plane = self._plane_fn()
        out: dict = {"queue_mean": None, "occupancy_mean": None,
                     "slo_breached": False}
        if plane is None:
            return out
        try:
            q = plane.aggregator.gauge_stats(job, "serve_queue_depth")
            occ = plane.aggregator.gauge_stats(job, "serve_batch_occupancy")
            out["queue_mean"] = None if q is None else q.get("mean")
            out["occupancy_mean"] = None if occ is None else occ.get("mean")
            out["slo_breached"] = bool(plane.slo.breached(job))
        except Exception:  # noqa: BLE001 - a broken read holds, never scales
            log.exception("autoscale: reading fleet rollups for %s failed",
                          job)
        return out

    def forget(self, job: str) -> None:
        with self._lock:
            self._state.pop(job, None)

    def note_applied(self, job: str, now: Optional[float] = None) -> None:
        """Start the cooldown clock — called by the loop AFTER apply_fn
        succeeds, so a failed patch does not burn the cooldown."""
        now = time.monotonic() if now is None else now
        with self._lock:
            st = self._state.setdefault(
                job, {"streak_up": 0, "streak_down": 0,
                      "last_action": None, "parked": None})
            st["last_action"] = now
            st["streak_up"] = 0
            st["streak_down"] = 0

    def note_parked(self, job: str, target: int) -> None:
        with self._lock:
            st = self._state.setdefault(
                job, {"streak_up": 0, "streak_down": 0,
                      "last_action": None, "parked": None})
            st["parked"] = target

    def clear_parked(self, job: str) -> None:
        with self._lock:
            st = self._state.get(job)
            if st is not None:
                st["parked"] = None

    def parked_target(self, job: str) -> Optional[int]:
        with self._lock:
            st = self._state.get(job)
            return None if st is None else st.get("parked")

    def evaluate(self, job: str, current: int, min_replicas: int,
                 max_replicas: int, now: Optional[float] = None
                 ) -> Decision:
        """One tick for one job: reads the plane, updates hysteresis
        state, returns the (clamped) decision.  ``direction == "hold"``
        means no action this tick."""
        now = time.monotonic() if now is None else now
        signals = self._signals(job)
        queue = signals["queue_mean"]
        occ = signals["occupancy_mean"]
        breached = signals["slo_breached"]
        with self._lock:
            st = self._state.setdefault(
                job, {"streak_up": 0, "streak_down": 0,
                      "last_action": None, "parked": None})
            # a parked scale-up stays wanted until capacity frees or the
            # pressure genuinely subsides
            want_up = breached or (queue is not None
                                   and queue > self.up_queue_depth)
            want_down = (not breached
                         and queue is not None
                         and queue <= self.down_queue_depth
                         and (occ is None or occ < self.down_occupancy))
            if want_up:
                st["streak_up"] += 1
                st["streak_down"] = 0
            elif want_down:
                st["streak_down"] += 1
                st["streak_up"] = 0
            else:
                st["streak_up"] = 0
                st["streak_down"] = 0
                if queue is not None:
                    # pressure OBSERVED gone: drop the pending ask.  A
                    # data gap (no rollup this tick — pod churn, plane
                    # restart) is not calm: the parked target survives
                    # it, or freed chips would find the ask withdrawn
                    # and the job would re-accumulate the whole hold
                    st["parked"] = None
            in_cooldown = (st["last_action"] is not None
                           and now - st["last_action"] < self.cooldown_s)
            parked = st["parked"]
            if parked is not None and want_up:
                # retry the parked target every tick — no hold, no
                # cooldown: admission was the only thing in the way
                target = min(parked, max_replicas)
                if target > current:
                    return Decision(job, current, target, "up",
                                    "retry-parked", signals, parked=True)
                st["parked"] = None
            if in_cooldown:
                return Decision(job, current, current, "hold",
                                "cooldown", signals)
            if want_up and st["streak_up"] >= self.hold_evals:
                target = min(current + 1, max_replicas)
                if target > current:
                    reason = ("slo-burn" if breached
                              else f"queue-depth {queue:.1f} > "
                                   f"{self.up_queue_depth:g}")
                    return Decision(job, current, target, "up", reason,
                                    signals)
                return Decision(job, current, current, "hold",
                                "at-max-replicas", signals)
            if want_down and st["streak_down"] >= self.hold_evals:
                target = max(current - 1, min_replicas)
                if target < current:
                    return Decision(
                        job, current, target, "down",
                        f"idle: queue {queue:.1f} <= "
                        f"{self.down_queue_depth:g}", signals)
                return Decision(job, current, current, "hold",
                                "at-min-replicas", signals)
            return Decision(job, current, current, "hold",
                            "hysteresis", signals)

    def state(self) -> dict:
        with self._lock:
            return {job: dict(st) for job, st in sorted(self._state.items())}


class AutoscaleLoop:
    """The operator-side control loop: evaluates every autoscalable job
    each tick and applies decisions through the controller's hooks.

    ``jobs_fn() -> [(job_key, current_replicas, min, max)]``
    ``reserve_fn(job, target) -> bool`` (None = no admission gate)
    ``drain_fn(job, n_victims) -> bool`` (None = no drain step)
    ``undrain_fn(job)`` — revert a drain whose apply failed (optional)
    ``apply_fn(job, target) -> bool``
    ``event_fn(job, kind, message)`` (None = log only)
    """

    def __init__(self, autoscaler: Autoscaler, jobs_fn, apply_fn, *,
                 reserve_fn=None, drain_fn=None, undrain_fn=None,
                 event_fn=None,
                 interval_s: float = DEFAULT_INTERVAL_S):
        self.autoscaler = autoscaler
        self._jobs_fn = jobs_fn
        self._apply_fn = apply_fn
        self._reserve_fn = reserve_fn
        self._drain_fn = drain_fn
        self._undrain_fn = undrain_fn
        self._event_fn = event_fn
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0
        self.applied: dict[str, int] = {}   # job -> last applied target
        self.last_decisions: dict[str, dict] = {}

    def start(self) -> "AutoscaleLoop":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscale-loop")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("autoscale: tick failed")

    def _event(self, job: str, kind: str, message: str) -> None:
        if self._event_fn is not None:
            try:
                self._event_fn(job, kind, message)
            except Exception:  # noqa: BLE001 - eventing must not stall scaling
                log.exception("autoscale: event sink failed")
        log.info("autoscale %s: %s %s", job, kind, message)

    def tick_once(self, now: Optional[float] = None) -> list[Decision]:
        """One synchronous evaluation pass (tests/benches drive this
        directly); returns every job's decision."""
        self.ticks += 1
        decisions: list[Decision] = []
        for job, current, min_r, max_r in list(self._jobs_fn() or ()):
            d = self.autoscaler.evaluate(job, current, min_r, max_r,
                                         now=now)
            decisions.append(d)
            self.last_decisions[job] = d.to_dict()
            if d.direction == "up" and d.target > d.current:
                self._scale_up(d, now)
            elif d.direction == "down" and d.target < d.current:
                self._scale_down(d, now)
        return decisions

    def _scale_up(self, d: Decision, now: Optional[float]) -> None:
        if self._reserve_fn is not None \
                and not self._reserve_fn(d.job, d.target):
            # gang-atomic or nothing: the whole expansion parks Queued
            # until the chips exist — NEVER a partial placement.  The
            # event fires once per distinct parked target, not per
            # retry tick (the loop re-asks every interval; an Event
            # every 5s per parked job would be a Warning storm)
            already = self.autoscaler.parked_target(d.job)
            self.autoscaler.note_parked(d.job, d.target)
            self.last_decisions[d.job]["parked"] = True
            if already != d.target:
                self._event(d.job, "ScaleUpQueued",
                            f"scale-up to {d.target} replicas parked: "
                            f"insufficient chips ({d.reason})")
            return
        if self._apply_fn(d.job, d.target):
            self.autoscaler.clear_parked(d.job)
            self.autoscaler.note_applied(d.job, now=now)
            self.applied[d.job] = d.target
            self._event(d.job, "ScaledUp",
                        f"{d.current} -> {d.target} replicas ({d.reason})")

    def _scale_down(self, d: Decision, now: Optional[float]) -> None:
        drained = True
        if self._drain_fn is not None:
            # the victim drains through the router BEFORE the patch
            # that releases its chips — no request is mid-flight on a
            # pod whose deletion is already committed
            drained = bool(self._drain_fn(d.job, d.current - d.target))
        if self._apply_fn(d.job, d.target):
            self.autoscaler.note_applied(d.job, now=now)
            self.applied[d.job] = d.target
            self._event(d.job, "ScaledDown",
                        f"{d.current} -> {d.target} replicas ({d.reason}"
                        f"{'' if drained else '; drain timed out'})")
        elif self._drain_fn is not None and self._undrain_fn is not None:
            # the patch failed: the drained victims must take traffic
            # again, not sit refused-forever behind a spec that never
            # shrank
            try:
                self._undrain_fn(d.job)
            except Exception:  # noqa: BLE001 - best-effort revert
                log.exception("autoscale: undrain of %s failed", d.job)

    def state(self) -> dict:
        return {
            "interval_s": self.interval_s,
            "ticks": self.ticks,
            "applied": dict(self.applied),
            "last_decisions": dict(self.last_decisions),
            "hysteresis": self.autoscaler.state(),
        }

"""The serving fleet front door: a prefix-affine reverse proxy.

One ``Router`` fronts one serving TFJob's pods and proxies
``POST /v1/generate`` with **prefix-affine** placement: the request's
block-aligned template-prefix fingerprint (ring.fingerprint_request —
same block size as the engine's radix PrefixTree) is consistent-hashed
onto the healthy-pod ring, so requests sharing a prompt land on the pod
whose KV pool already holds those blocks, turning N private prefix
caches into one fleet-wide asset.  Placement falls back to
least-outstanding-requests — live in-flight counts per backend,
tie-broken by the fleet plane's per-pod ``serve_queue_depth`` rollup —
when the request has no full-block prefix, the affine pod is shedding
(recent 503 / over the in-flight bound), or it is unhealthy/draining.

Reliability contract:

- idempotent 503s (and transport errors) retry against the NEXT ring
  candidate, bounded by ``retry_budget`` — each attempt a distinct pod;
- a backend is evicted from the ring after ``fail_threshold``
  consecutive transport failures and re-admitted when its ``/healthz``
  probes green again (a 503 is shedding, not unhealth);
- ``drain()`` refuses new requests (503 + Retry-After) while completing
  the in-flight ones — the SIGTERM path, and the per-backend variant
  the autoscaler uses before releasing a victim pod's chips;
- the inbound W3C ``traceparent`` is forwarded verbatim, so the PR 12
  caller -> ingress -> engine trace join survives the extra hop;
- optional **request hedging** (ISSUE 15 satellite, ``hedge_s`` /
  ``K8S_TPU_ROUTER_HEDGE_S``, off by default): a first attempt with no
  response after the hedge delay races the next ring candidate, first
  response wins (``router_hedges_total{outcome}``).

Disaggregated phase split (ISSUE 15): with prefill-role backends
present (``kubeflow.org/serve-role`` annotation via fleet discovery)
and ``phase_split_tokens`` set, prompts at/above the threshold plan
over the prefill tier's OWN prefix-affine ring and carry the decode
destination (``kv_dest`` — the ``kubeflow.org/kvxfer-port``-derived
address of the decode pod chosen affine on the serving ring with the
SAME fingerprint) in the forwarded body; short prompts and collapsed
fleets are untouched, and prefill-role pods take no normal placements.

Discovery is a ``targets_fn`` callable (the standalone entrypoint wires
``fleet.targets_from_pods`` over its own pod informer cache; benches
pass a static list), so the router itself never touches the apiserver —
the same zero-apiserver-call resolution the fleet plane proved.

Observability: ``/metrics`` (router_requests_total{outcome,affine},
router_affinity_hits_total, router_backend_inflight, router_retries_total),
``/healthz``, and ``/debug/router`` (ring state, per-backend
health/in-flight, recent placements) — served here AND by the operator's
metrics server + dashboard through the shared responder in
:mod:`k8s_tpu.router.debug`, 404-when-inactive like every other
``/debug`` route.

Stdlib-only by policy (harness/py_checks.py gates ``k8s_tpu.router``
like ``fleet/``/``flight/``); it may import sibling stdlib-only
packages (``fleet`` for discovery types and rollup reads) — the
transitive guarantee holds.
"""

from __future__ import annotations

import http.client
import json
import logging
import queue
import random
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import urlsplit

from k8s_tpu.analysis import checkedlock
from k8s_tpu.router import ring as ring_mod

log = logging.getLogger(__name__)

DEFAULT_BLOCK_SIZE = 8
DEFAULT_RETRY_BUDGET = 2
DEFAULT_FAIL_THRESHOLD = 2
DEFAULT_REFRESH_S = 1.0
DEFAULT_SHED_S = 1.0
DEFAULT_PROBE_TIMEOUT_S = 1.0
DEFAULT_REQUEST_TIMEOUT_S = 300.0
PLACEMENT_RING = 256

POLICY_AFFINE = "affine"
POLICY_LEAST = "least"
POLICY_RANDOM = "random"
VALID_POLICIES = (POLICY_AFFINE, POLICY_LEAST, POLICY_RANDOM)


class Backend:
    """One serving pod behind the front door."""

    __slots__ = ("name", "base_url", "healthy", "draining", "inflight",
                 "consecutive_failures", "last_error", "requests",
                 "shed_until", "weight", "role", "kvxfer")

    def __init__(self, name: str, base_url: str, weight: float = 1.0,
                 role: str = "", kvxfer: Optional[str] = None):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.healthy = True
        self.draining = False
        self.inflight = 0
        self.consecutive_failures = 0
        self.last_error = ""
        self.requests = 0
        self.shed_until = 0.0
        # relative capacity from discovery (the fleet-serve-weight pod
        # annotation): scales this backend's hash-ring keyspace share
        self.weight = weight
        # disaggregated tier membership (ISSUE 15): "prefill"/"decode"
        # from the kubeflow.org/serve-role pod annotation ("" = the
        # collapsed single-role pod), and the decode pod's kv-transfer
        # address (host:port) long requests follow their blocks to
        self.role = role
        self.kvxfer = kvxfer

    def to_dict(self, now: float) -> dict:
        return {
            "name": self.name,
            "url": self.base_url,
            "healthy": self.healthy,
            "draining": self.draining,
            "inflight": self.inflight,
            "weight": self.weight,
            "role": self.role,
            "kvxfer": self.kvxfer,
            "requests": self.requests,
            "consecutive_failures": self.consecutive_failures,
            "shedding": now < self.shed_until,
            "last_error": self.last_error,
        }


def _base_url(url: str) -> str:
    """scheme://host:port of any target URL (discovery hands the router
    /metrics URLs; the generate endpoint lives on the same listener —
    the genjob --serve contract)."""
    parts = urlsplit(url)
    if parts.scheme and parts.netloc:
        return f"{parts.scheme}://{parts.netloc}"
    return url.rstrip("/")


class Router:
    """Placement + health state for one serving job's pod fleet.

    ``targets_fn`` yields objects with ``pod`` and ``url`` attributes
    (fleet.ScrapeTarget) or plain ``(name, base_url)`` pairs.  All HTTP
    I/O happens OUTSIDE the state lock."""

    def __init__(self, targets_fn: Callable[[], list], *,
                 job: Optional[str] = None,
                 block_size: int = DEFAULT_BLOCK_SIZE,
                 affinity_blocks: int = ring_mod.DEFAULT_AFFINITY_BLOCKS,
                 vnodes: int = ring_mod.DEFAULT_VNODES,
                 policy: str = POLICY_AFFINE,
                 retry_budget: int = DEFAULT_RETRY_BUDGET,
                 fail_threshold: int = DEFAULT_FAIL_THRESHOLD,
                 max_inflight: Optional[int] = None,
                 shed_s: float = DEFAULT_SHED_S,
                 refresh_interval_s: float = DEFAULT_REFRESH_S,
                 request_timeout_s: float = DEFAULT_REQUEST_TIMEOUT_S,
                 probe_timeout_s: float = DEFAULT_PROBE_TIMEOUT_S,
                 phase_split_tokens: Optional[int] = None,
                 hedge_s: float = 0.0):
        if policy not in VALID_POLICIES:
            raise ValueError(
                f"policy {policy!r} must be one of {VALID_POLICIES}")
        self.job = job
        self.block_size = int(block_size)
        self.affinity_blocks = int(affinity_blocks)
        self.policy = policy
        self.retry_budget = max(0, int(retry_budget))
        self.fail_threshold = max(1, int(fail_threshold))
        self.max_inflight = max_inflight
        self.shed_s = float(shed_s)
        self.refresh_interval_s = float(refresh_interval_s)
        self.request_timeout_s = float(request_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        # disaggregated phase split (ISSUE 15): prompts of at least
        # this many tokens route to the prefill tier (then follow their
        # blocks to a decode pod); None/0 = off — and it only engages
        # while prefill-role backends actually exist, so a collapsed
        # fleet never changes behavior
        self.phase_split_tokens = (int(phase_split_tokens)
                                   if phase_split_tokens else None)
        # request hedging (ISSUE 13 headroom): after this many seconds
        # without a response, race the idempotent request against the
        # next ring candidate, first response wins; 0 = off (default)
        self.hedge_s = max(0.0, float(hedge_s))
        self._targets_fn = targets_fn
        self._ring = ring_mod.HashRing(vnodes=vnodes)
        # the prefill tier's own ring: prefix-affine placement there
        # keeps that tier's radix trees composing exactly like the
        # serving ring does
        self._prefill_ring = ring_mod.HashRing(vnodes=vnodes)
        self._backends: dict[str, Backend] = {}
        self._lock = checkedlock.make_lock("router.state")
        self._draining = False
        self._started_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (under the state lock; rendered by /metrics)
        self.requests_total: dict[tuple[str, str], int] = {}
        self.affinity_hits_total = 0
        self.retries_total = 0
        self.prefill_routed_total = 0
        self.hedges_total: dict[str, int] = {}
        # fleet prefix cache index (ISSUE 17): placements upgraded onto
        # a pod that ADVERTISES the fingerprint (vs "probably cached
        # there"), and requests sent with a kv_src fetch-on-miss hint
        self.index_hits_total = 0
        self.kv_src_routed_total = 0
        self._placements: deque = deque(maxlen=PLACEMENT_RING)
        self._rng = random.Random()
        # keep-alive connection pool per backend netloc: a fresh TCP
        # connect (and a fresh server-side handler thread) per proxied
        # request costs more than the proxying itself at fleet request
        # rates; stale pooled sockets are retried once on a fresh
        # connection before counting as a backend transport failure
        self._pool: dict[str, list] = {}
        self._pool_cap = 32

    # -- lifecycle ------------------------------------------------------------

    @property
    def active(self) -> bool:
        return self._started_at is not None

    @property
    def draining(self) -> bool:
        return self._draining

    def start(self) -> "Router":
        self._started_at = time.time()
        self.refresh_once()
        if self.refresh_interval_s > 0:
            self._thread = threading.Thread(
                target=self._maintenance_loop, daemon=True,
                name="router-refresh")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._started_at = None
        with self._lock:
            pools, self._pool = self._pool, {}
        for idle in pools.values():
            for conn in idle:
                conn.close()

    def drain(self) -> None:
        """Refuse new requests; in-flight ones complete (SIGTERM path)."""
        self._draining = True

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """True when every in-flight request finished within the budget."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                busy = sum(b.inflight for b in self._backends.values())
            if busy == 0:
                return True
            time.sleep(0.02)
        return False

    def _maintenance_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            try:
                self.refresh_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("router: discovery refresh failed")

    # -- discovery / health ---------------------------------------------------

    def refresh_once(self) -> int:
        """Reconcile the backend table + ring to the discovered targets
        and probe unhealthy backends; returns the live backend count."""
        targets = list(self._targets_fn() or ())
        resolved: dict[str, tuple] = {}
        for t in targets:
            name = getattr(t, "pod", None)
            url = getattr(t, "url", None)
            role = None
            kvxfer = None
            if name is None and isinstance(t, (tuple, list)) \
                    and len(t) >= 2:
                # static target forms: (name, url) or
                # (name, url, role[, kvxfer]) — benches and tests
                name, url = t[0], t[1]
                role = t[2] if len(t) >= 3 else None
                kvxfer = t[3] if len(t) >= 4 else None
            else:
                role = getattr(t, "role", None)
                kvxfer = getattr(t, "kvxfer", None)
            if not name or not url:
                continue
            # the cross-process drain protocol: an operator that cannot
            # reach this router in-process annotates the victim pod
            # (fleet.ANNOTATION_ROUTER_DRAIN) and discovery carries the
            # flag; None leaves the locally-set drain state alone
            try:
                weight = float(getattr(t, "weight", 1.0) or 1.0)
            except (TypeError, ValueError):
                weight = 1.0
            role = str(role).strip().lower() if role else ""
            if role not in ("prefill", "decode"):
                role = ""
            resolved[str(name)] = (_base_url(str(url)),
                                   getattr(t, "draining", None),
                                   weight if weight > 0 else 1.0,
                                   role,
                                   str(kvxfer) if kvxfer else None)
        with self._lock:
            for name in list(self._backends):
                if name not in resolved:
                    del self._backends[name]
            for name, (base, draining, weight, role,
                       kvxfer) in resolved.items():
                b = self._backends.get(name)
                if b is None:
                    b = self._backends[name] = Backend(name, base,
                                                       weight=weight,
                                                       role=role,
                                                       kvxfer=kvxfer)
                elif b.base_url != base:
                    b.base_url = base
                if draining is not None:
                    b.draining = draining
                # a weight change (pod resized / re-annotated) re-plants
                # only that backend's ring points on the rebuild below
                b.weight = weight
                b.role = role
                b.kvxfer = kvxfer
            probe_list = [(b.name, b.base_url)
                          for b in self._backends.values() if not b.healthy]
            self._rebuild_ring_locked()
            count = len(self._backends)
        for name, base in probe_list:  # I/O outside the lock
            self._probe(name, base)
        return count

    def _rebuild_ring_locked(self) -> None:
        # prefill-role pods serve the phase-split prefill leg only:
        # they never take normal placements (a long prompt's decode leg
        # and every short prompt stay on the serving ring)
        self._ring.replace({b.name: b.weight
                            for b in self._backends.values()
                            if b.healthy and not b.draining
                            and b.role != "prefill"})
        self._prefill_ring.replace({b.name: b.weight
                                    for b in self._backends.values()
                                    if b.healthy and not b.draining
                                    and b.role == "prefill"})

    def _probe(self, name: str, base_url: str) -> None:
        """Active /healthz recheck of an evicted backend — success
        re-admits it to the ring."""
        ok = False
        try:
            parts = urlsplit(base_url)
            conn = http.client.HTTPConnection(parts.netloc,
                                              timeout=self.probe_timeout_s)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            finally:
                conn.close()
        except (OSError, http.client.HTTPException):
            # a garbled/non-HTTP answer (crash-looping container) is an
            # unhealthy probe, not an exception that may abort the rest
            # of the refresh cycle's probe list
            ok = False
        if ok:
            with self._lock:
                b = self._backends.get(name)
                if b is not None and not b.healthy:
                    b.healthy = True
                    b.consecutive_failures = 0
                    b.last_error = ""
                    self._rebuild_ring_locked()

    def set_draining(self, name: str, draining: bool = True) -> bool:
        """Per-backend drain (the autoscaler's scale-down hook): a
        draining pod takes no new placements; its in-flight requests
        finish.  True when the backend exists."""
        with self._lock:
            b = self._backends.get(name)
            if b is None:
                return False
            b.draining = draining
            self._rebuild_ring_locked()
            return True

    def backend_inflight(self, name: str) -> Optional[int]:
        with self._lock:
            b = self._backends.get(name)
            return None if b is None else b.inflight

    # -- placement ------------------------------------------------------------

    def _fleet_depths(self) -> dict[str, float]:
        """Per-pod ``serve_queue_depth`` from the active fleet plane's
        rollups (the least-outstanding tie-break); empty when no plane
        is active or the job is unknown — in-flight counts then decide
        alone."""
        if not self.job:
            return {}
        try:
            import k8s_tpu.fleet as fleet

            plane = fleet.active()
            if plane is None:
                return {}
            return plane.aggregator.pod_gauge_latest(
                self.job, "serve_queue_depth") or {}
        except Exception:  # noqa: BLE001 - a broken tie-break must not drop traffic
            return {}

    def _index_holders(self, fp: str) -> dict[str, float]:
        """Pods advertising ``fp`` in the fleet prefix cache index
        (``serve_kv_prefix_cached{fp=...}``, ISSUE 17) — pods whose
        radix tree or spill tier actually HOLDS the prefix right now,
        as of the last scrape.  Empty when no plane is active, the job
        is unknown, or nobody advertises it — placement then falls
        back to "probably cached there" ring affinity alone."""
        if not self.job:
            return {}
        try:
            import k8s_tpu.fleet as fleet

            plane = fleet.active()
            if plane is None:
                return {}
            return plane.aggregator.pod_gauge_latest(
                self.job, "serve_kv_prefix_cached",
                (("fp", fp),)) or {}
        except Exception:  # noqa: BLE001 - a stale index must not drop traffic
            return {}

    def _index_kv_src(self, fp: Optional[str],
                      target: Optional[str]) -> Optional[str]:
        """kvxfer address of an index-advertised holder of ``fp`` when
        the placed ``target`` is not itself a holder: the serving pod
        fetches the prefix blocks on miss instead of recomputing them.
        None when the index is cold, the target already holds the
        prefix, or no holder exposes a kvxfer listener."""
        if fp is None or target is None:
            return None
        holders = self._index_holders(fp)
        if not holders or target in holders:
            return None
        with self._lock:
            for name in holders:
                b = self._backends.get(name)
                if b is not None and b.healthy and b.kvxfer \
                        and name != target:
                    self.kv_src_routed_total += 1
                    return b.kvxfer
        return None

    def _eligible_locked(self) -> list[Backend]:
        # prefill-role pods are not placement candidates for normal
        # traffic (they only take the phase-split prefill leg)
        return [b for b in self._backends.values()
                if b.healthy and not b.draining and b.role != "prefill"]

    def _prefill_eligible_locked(self) -> list[Backend]:
        return [b for b in self._backends.values()
                if b.healthy and not b.draining and b.role == "prefill"]

    def _available(self, b: Backend, now: float) -> bool:
        if now < b.shed_until:
            return False
        if self.max_inflight is not None and b.inflight >= self.max_inflight:
            return False
        return True

    @staticmethod
    def _prompt_tokens(req: dict) -> int:
        """Estimated prompt length in engine tokens: token requests
        count ids; text requests count UTF-8 bytes (the byte-tokenizer
        contract the fingerprint already relies on)."""
        tokens = req.get("tokens")
        if isinstance(tokens, list):
            return len(tokens)
        text = req.get("text")
        if isinstance(text, str):
            return len(text.encode("utf-8", "replace"))
        return 0

    def plan_disagg(self, req: dict) -> Optional[tuple[
            list[str], bool, Optional[str], list[str]]]:
        """Phase-split placement for a long prompt (ISSUE 15), or None
        when the request stays on the normal plan: ``(prefill order,
        affine, fingerprint, kv_dests)`` — the prefill leg is
        prefix-affine over the prefill tier's own ring (that tier's
        radix trees compose), and the request then follows its blocks
        to a decode pod chosen affine on the SERVING ring with the
        same fingerprint (so the migrated prefix lands where later
        short requests with the same template will hash).
        ``kv_dests`` is the ORDERED decode candidate walk, affine
        first: a decode pod refusing (pool exhausted → the prefill pod
        answers 503) must not pin every retry to the same exhausted
        destination."""
        if not self.phase_split_tokens \
                or self._prompt_tokens(req) < self.phase_split_tokens \
                or req.get("kv_dest"):
            return None
        fp = ring_mod.fingerprint_request(req, self.block_size,
                                          self.affinity_blocks)
        now = time.monotonic()
        with self._lock:
            prefill = self._prefill_eligible_locked()
            if not prefill:
                return None  # collapsed fleet: normal plan
            by_name = {b.name: b for b in prefill}
            if fp is not None:
                order = [n for n in self._prefill_ring.candidates(fp)
                         if n in by_name]
                affine = bool(order) and self._available(
                    by_name[order[0]], now)
            else:
                order, affine = [], False
            if not order:
                order = [b.name for b in sorted(
                    prefill, key=lambda b: (
                        not self._available(b, now), b.inflight,
                        b.name))]
            # decode destinations: affine ring walk over kvxfer-capable
            # candidates first, then the least-outstanding remainder —
            # every candidate appears exactly once
            decode = [b for b in self._eligible_locked()
                      if b.kvxfer]
            if not decode:
                return None  # nobody can receive blocks: serve locally
            by_decode = {b.name: b for b in decode}
            dests: list[str] = []
            if fp is not None:
                for n in self._ring.candidates(fp):
                    cand = by_decode.get(n)
                    if cand is not None and self._available(cand, now):
                        dests.append(cand.kvxfer)
            for b in sorted(decode, key=lambda b: (
                    not self._available(b, now), b.inflight, b.name)):
                if b.kvxfer not in dests:
                    dests.append(b.kvxfer)
            return order, affine, fp, dests

    def plan(self, req: dict) -> tuple[list[str], bool, Optional[str]]:
        """(ordered backend names to try, affine, fingerprint) for one
        request — pure placement, no I/O.  The first entry is the
        placement; the rest are the retry walk."""
        now = time.monotonic()
        fp = None
        if self.policy == POLICY_AFFINE:
            fp = ring_mod.fingerprint_request(req, self.block_size,
                                              self.affinity_blocks)
            # affine fast path — the warm-fleet common case pays no
            # fleet-rollup read and no least-outstanding sort
            with self._lock:
                eligible = self._eligible_locked()
                if not eligible:
                    return [], False, fp
                if fp is not None:
                    by_name = {b.name: b for b in eligible}
                    ring_order = [n for n in self._ring.candidates(fp)
                                  if n in by_name]
                    if ring_order and self._available(
                            by_name[ring_order[0]], now):
                        # affine placement; retries walk the ring so
                        # shared prefixes re-land deterministically
                        # after a failure
                        return ring_order, True, fp
        # fallback / least / random: the per-pod fleet tie-break reads
        # the aggregator (its own lock) OUTSIDE the router state lock,
        # as does the prefix cache index (ISSUE 17) — a pod that
        # ADVERTISES the fingerprint beats the plain least-outstanding
        # pick when the ring-designated pod is cold or shedding
        depths = self._fleet_depths()
        holders = self._index_holders(fp) if fp is not None else {}
        with self._lock:
            eligible = self._eligible_locked()
            if not eligible:
                return [], False, fp
            by_name = {b.name: b for b in eligible}
            # availability partitions the least-outstanding order: a
            # shedding backend rejects fast, so its in-flight count is
            # LOW — ordering on inflight alone would send the fallback
            # straight back to the pod that just 503'd.  Shed/full pods
            # stay in the order as a last resort (if everyone is
            # shedding, someone still has to answer the 503).
            least = sorted(
                eligible,
                key=lambda b: (not self._available(b, now), b.inflight,
                               depths.get(b.name, 0.0), b.name))
            if fp is not None:
                # affine pod cold/shedding/absent: least-outstanding
                # fallback, then the ring walk minus the fallback pick
                ring_order = [n for n in self._ring.candidates(fp)
                              if n in by_name]
                pick = least[0].name
                if holders and pick not in holders:
                    # fleet index upgrade: an available pod that holds
                    # the prefix (tree or spill tier) serves it without
                    # recompute — worth leaving the least-outstanding
                    # pick for
                    for b in least:
                        if b.name in holders \
                                and self._available(b, now):
                            pick = b.name
                            self.index_hits_total += 1
                            break
                elif holders and pick in holders:
                    self.index_hits_total += 1
                order = [pick] + [
                    n for n in (ring_order or
                                [b.name for b in least])
                    if n != pick]
                return order, False, fp
            if self.policy == POLICY_RANDOM:
                names = [b.name for b in eligible]
                self._rng.shuffle(names)
                return names, False, None
            return [b.name for b in least], False, None

    # -- proxying -------------------------------------------------------------

    def handle_generate(self, body: bytes, headers: dict) -> tuple[
            int, dict, bytes, dict]:
        """Proxy one /v1/generate: returns (status, response_headers,
        body, placement_info).  All failures are mapped to a response —
        this never raises."""
        t0 = time.monotonic()
        try:
            req = json.loads(body or b"{}")
            if not isinstance(req, dict):
                req = {}
        except (ValueError, json.JSONDecodeError):
            req = {}  # the backend answers the 400; no affinity
        disagg = self.plan_disagg(req) if req else None
        kv_dests: Optional[list] = None
        if disagg is not None:
            # phase split (ISSUE 15): the prefill tier serves this one,
            # then streams its blocks to a decode pod — the destination
            # rides the body, ROTATING through the decode candidates on
            # retries (an exhausted decode pod refuses as a 503 on the
            # prefill side; re-sending the identical destination would
            # shed every healthy prefill pod without ever trying the
            # other decode pods)
            order, affine, fp, kv_dests = disagg
            with self._lock:
                self.prefill_routed_total += 1
        else:
            order, affine, fp = self.plan(req)
        if not order:
            self._finish(None, "no_backends", affine, fp, 0, t0)
            return (503, {"Retry-After": "1"},
                    json.dumps({"error": "no healthy backends"}).encode(),
                    {"outcome": "no_backends", "affine": affine})
        if disagg is None and not affine and fp is not None and req \
                and not req.get("kv_dest") and not req.get("kv_src"):
            # cold placement (ISSUE 17): when another pod advertises
            # this prefix in the fleet index, ride its kvxfer address
            # on the body so the serving pod fetches the blocks instead
            # of recomputing them (never alongside kv_dest — the server
            # treats the two as mutually exclusive)
            kv_src = self._index_kv_src(fp, order[0])
            if kv_src is not None:
                body = json.dumps({**req, "kv_src": kv_src}).encode()
        attempts = min(len(order), 1 + self.retry_budget)
        last_status, last_headers, last_body = 503, {}, json.dumps(
            {"error": "all retry candidates failed"}).encode()
        hedge_loser: Optional[str] = None
        for i, name in enumerate(order[:attempts]):
            if kv_dests:
                body = json.dumps(
                    {**req,
                     "kv_dest": kv_dests[i % len(kv_dests)]}).encode()
            if i > 0 and name == hedge_loser:
                # the hedged attempt already burned this candidate (it
                # answered the losing/failing response); walk past it
                continue
            if i == 0 and self.hedge_s > 0 and attempts > 1:
                name, status, resp_headers, resp_body, err = \
                    self._forward_hedged(order[0], order[1], body,
                                         headers)
                if name != order[0] and (err is not None
                                         or status >= 500):
                    hedge_loser = name
            else:
                status, resp_headers, resp_body, err = self._forward(
                    name, body, headers)
            if err is not None:
                self._note_transport_failure(name, err)
                if i + 1 < attempts:
                    self._count_retry()
                last_status, last_headers, last_body = 502, {}, json.dumps(
                    {"error": f"backend {name}: {err}"}).encode()
                continue
            if status >= 500:
                # /v1/generate is idempotent (pure function of the
                # payload), so EVERY 5xx walks to the next ring
                # candidate: 503 is shedding (healthy — reset failures,
                # mark the shed window); other 5xx mean the backend's
                # ENGINE is sick behind a live listener (a crashed
                # engine still drains keep-alive sockets and answers
                # 500) — those count toward health eviction WITHOUT a
                # success-reset first, or the counter would saturate at
                # 1 and never reach fail_threshold; /healthz probes
                # (which the serving pod fails while its engine is
                # dead) gate re-admission
                if status == 503:
                    self._note_success(name, status)
                else:
                    self._note_transport_failure(
                        name, f"HTTP {status} from backend")
                if i + 1 < attempts:
                    self._count_retry()
                last_status, last_headers, last_body = (
                    status, resp_headers, resp_body)
                continue
            self._note_success(name, status)
            outcome = "ok" if status < 400 else "bad_request"
            # "affine" means SERVED affine: the first attempt landed on
            # the ring-designated pod (a retry hop — or a won hedge to
            # the next candidate — is not a hit)
            served_affine = affine and i == 0 and name == order[0]
            self._finish(name, outcome, served_affine, fp, i, t0)
            resp_headers["X-Router-Backend"] = name
            resp_headers["X-Router-Affine"] = "1" if served_affine \
                else "0"
            return status, resp_headers, resp_body, {
                "outcome": outcome, "affine": served_affine,
                "backend": name, "attempts": i + 1}
        outcome = "shed" if last_status == 503 else "error"
        self._finish(order[0], outcome, affine, fp,
                     attempts - 1, t0, exhausted=True)
        last_headers.setdefault("Retry-After", "1")
        return last_status, last_headers, last_body, {
            "outcome": outcome, "affine": False, "attempts": attempts}

    def _checkout_conn(self, netloc: str):
        """(connection, reused) — a pooled keep-alive connection when one
        is idle, else a fresh one."""
        with self._lock:
            idle = self._pool.get(netloc)
            if idle:
                return idle.pop(), True
        return http.client.HTTPConnection(
            netloc, timeout=self.request_timeout_s), False

    def _checkin_conn(self, netloc: str, conn) -> None:
        with self._lock:
            idle = self._pool.setdefault(netloc, [])
            if len(idle) < self._pool_cap:
                idle.append(conn)
                return
        conn.close()

    def _attempt(self, netloc: str, body: bytes, fwd: dict) -> tuple[
            int, dict, bytes, Optional[str]]:
        """One POST on one (possibly pooled) connection.  A failure on a
        REUSED connection is retried once on a fresh socket — a server
        closing an idle keep-alive is not a backend failure."""
        for only_fresh in (False, True):
            conn, reused = (self._checkout_conn(netloc) if not only_fresh
                            else (http.client.HTTPConnection(
                                netloc, timeout=self.request_timeout_s),
                                False))
            try:
                conn.request("POST", "/v1/generate", body=body,
                             headers=fwd)
                resp = conn.getresponse()
                resp_body = resp.read()
                out_headers = {}
                ra = resp.getheader("Retry-After")
                if ra:
                    out_headers["Retry-After"] = ra
                if resp.will_close:
                    conn.close()
                else:
                    self._checkin_conn(netloc, conn)
                return resp.status, out_headers, resp_body, None
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                if reused:
                    continue  # stale keep-alive: one fresh retry
                return 0, {}, b"", f"{type(e).__name__}: {e}"
        return 0, {}, b"", "unreachable"  # pragma: no cover

    def _forward(self, name: str, body: bytes, headers: dict) -> tuple[
            int, dict, bytes, Optional[str]]:
        """One attempt against one backend; (status, headers, body,
        transport_error)."""
        with self._lock:
            b = self._backends.get(name)
            if b is None:
                return 0, {}, b"", "backend vanished"
            b.inflight += 1
            b.requests += 1
            netloc = urlsplit(b.base_url).netloc
        try:
            fwd = {"Content-Type": "application/json"}
            tp = headers.get("traceparent")
            if tp:
                fwd["traceparent"] = tp  # PR 12 trace join survives
            return self._attempt(netloc, body, fwd)
        finally:
            with self._lock:
                b2 = self._backends.get(name)
                if b2 is not None:
                    b2.inflight = max(0, b2.inflight - 1)

    def _forward_hedged(self, primary: str, candidate: str, body: bytes,
                        headers: dict) -> tuple[
            str, int, dict, bytes, Optional[str]]:
        """Hedged first attempt (ISSUE 15 satellite, off by default):
        forward to ``primary``; if no response lands within
        ``hedge_s``, race the same idempotent request against
        ``candidate`` (the next ring member) and take whichever answers
        FIRST — a pod wedged mid-GC or mid-compile stops defining the
        fleet's p99.  The loser runs to completion in the background
        (its own in-flight accounting unwinds normally); a first-won
        failure still falls through to the ordinary retry walk.
        Returns ``(winner, status, headers, body, err)``."""
        results: queue.Queue = queue.Queue()

        def attempt(n: str) -> None:
            results.put((n,) + self._forward(n, body, headers))

        threading.Thread(target=attempt, args=(primary,), daemon=True,
                         name="router-hedge-primary").start()
        try:
            return results.get(timeout=self.hedge_s)
        except queue.Empty:
            pass  # primary is stuck: fire the hedge
        threading.Thread(target=attempt, args=(candidate,), daemon=True,
                         name="router-hedge").start()
        try:
            winner = results.get(timeout=self.request_timeout_s + 5.0)
        except queue.Empty:  # both wedged past the transport timeout
            winner = (primary, 0, {}, b"", "hedged request timed out")
        outcome = "primary" if winner[0] == primary else "hedge"
        if winner[4] is not None:
            outcome = "failed"
        with self._lock:
            self.hedges_total[outcome] = \
                self.hedges_total.get(outcome, 0) + 1
        return winner

    # -- accounting -----------------------------------------------------------

    def _note_transport_failure(self, name: str, err: str) -> None:
        with self._lock:
            b = self._backends.get(name)
            if b is None:
                return
            b.consecutive_failures += 1
            b.last_error = err[:200]
            if b.healthy and b.consecutive_failures >= self.fail_threshold:
                b.healthy = False  # evicted until a /healthz probe greens
                self._rebuild_ring_locked()

    def _note_success(self, name: str, status: int) -> None:
        with self._lock:
            b = self._backends.get(name)
            if b is None:
                return
            b.consecutive_failures = 0
            if status == 503:
                # shedding is not unhealth: keep it in the ring but skip
                # it for placement until the shed window passes
                b.shed_until = time.monotonic() + self.shed_s

    def _count_retry(self) -> None:
        with self._lock:
            self.retries_total += 1

    def _finish(self, backend: Optional[str], outcome: str, affine: bool,
                fp: Optional[str], retries: int, t0: float,
                exhausted: bool = False) -> None:
        with self._lock:
            key = (outcome, "true" if affine and not exhausted else "false")
            self.requests_total[key] = self.requests_total.get(key, 0) + 1
            if affine and not exhausted and outcome == "ok" and retries == 0:
                self.affinity_hits_total += 1
            self._placements.append({
                "ts": round(time.time(), 3),
                "backend": backend,
                "outcome": outcome,
                "affine": bool(affine and not exhausted and retries == 0),
                "fingerprint": (fp[:12] if fp else None),
                "attempts": retries + 1,
                "elapsed_s": round(time.monotonic() - t0, 4),
            })

    # -- reads ----------------------------------------------------------------

    def backends(self) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            return [b.to_dict(now)
                    for b in sorted(self._backends.values(),
                                    key=lambda b: b.name)]

    def placements(self, n: int = 50) -> list[dict]:
        if n <= 0:
            return []  # entries[-0:] would invert the bound to "all"
        with self._lock:
            entries = list(self._placements)
        return entries[-n:]

    def counters(self) -> dict:
        with self._lock:
            return {
                "requests_total": {
                    f"{outcome}:{affine}": v
                    for (outcome, affine), v in
                    sorted(self.requests_total.items())},
                "affinity_hits_total": self.affinity_hits_total,
                "retries_total": self.retries_total,
                "prefill_routed_total": self.prefill_routed_total,
                "hedges_total": dict(self.hedges_total),
                "index_hits_total": self.index_hits_total,
                "kv_src_routed_total": self.kv_src_routed_total,
            }

    def debug_state(self, n_placements: int = 50) -> dict:
        """The /debug/router payload."""
        with self._lock:
            ring_state = self._ring.state()
            prefill_ring_state = self._prefill_ring.state() \
                if len(self._prefill_ring) else None
        return {
            "job": self.job,
            "policy": self.policy,
            "draining": self._draining,
            "started_at": self._started_at,
            "block_size": self.block_size,
            "affinity_blocks": self.affinity_blocks,
            "retry_budget": self.retry_budget,
            "phase_split_tokens": self.phase_split_tokens,
            "hedge_s": self.hedge_s,
            "ring": ring_state,
            "prefill_ring": prefill_ring_state,
            "backends": self.backends(),
            "counters": self.counters(),
            "placements": self.placements(n_placements),
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition 0.0.4 of the router families."""
        with self._lock:
            totals = dict(self.requests_total)
            hits = self.affinity_hits_total
            retries = self.retries_total
            prefill_routed = self.prefill_routed_total
            hedges = dict(self.hedges_total)
            index_hits = self.index_hits_total
            kv_src_routed = self.kv_src_routed_total
            inflight = [(b.name, b.inflight)
                        for b in sorted(self._backends.values(),
                                        key=lambda b: b.name)]
            healthy = sum(1 for b in self._backends.values() if b.healthy)
            total_backends = len(self._backends)
        lines = [
            "# HELP router_requests_total Proxied /v1/generate requests "
            "by outcome and affine placement.",
            "# TYPE router_requests_total counter",
        ]
        for (outcome, affine), v in sorted(totals.items()):
            lines.append(
                f'router_requests_total{{outcome="{outcome}",'
                f'affine="{affine}"}} {v}')
        lines += [
            "# HELP router_affinity_hits_total Requests served by their "
            "ring-affine backend on the first attempt.",
            "# TYPE router_affinity_hits_total counter",
            f"router_affinity_hits_total {hits}",
            "# HELP router_retries_total Retry attempts against a next "
            "ring candidate (idempotent 503s and transport errors).",
            "# TYPE router_retries_total counter",
            f"router_retries_total {retries}",
            "# HELP router_prefill_routed_total Long-prompt requests "
            "phase-split onto the prefill tier (disaggregated serving).",
            "# TYPE router_prefill_routed_total counter",
            f"router_prefill_routed_total {prefill_routed}",
            "# HELP router_index_hits_total Cold placements upgraded "
            "onto a pod advertising the prefix in the fleet cache "
            "index.",
            "# TYPE router_index_hits_total counter",
            f"router_index_hits_total {index_hits}",
            "# HELP router_kv_src_routed_total Requests forwarded with "
            "a kv_src fetch-on-miss hint naming an index-advertised "
            "holder.",
            "# TYPE router_kv_src_routed_total counter",
            f"router_kv_src_routed_total {kv_src_routed}",
            "# HELP router_hedges_total Fired request hedges by outcome "
            "(primary = original won after the hedge fired, hedge = the "
            "raced candidate won, failed = first response was an error).",
            "# TYPE router_hedges_total counter",
        ]
        for outcome in sorted(hedges):
            lines.append(
                f'router_hedges_total{{outcome="{outcome}"}} '
                f"{hedges[outcome]}")
        lines += [
            "# HELP router_backend_inflight Live in-flight requests per "
            "backend pod.",
            "# TYPE router_backend_inflight gauge",
        ]
        for name, n in inflight:
            lines.append(f'router_backend_inflight{{backend="{name}"}} {n}')
        lines += [
            "# HELP router_backends Known backends by health.",
            "# TYPE router_backends gauge",
            f'router_backends{{state="healthy"}} {healthy}',
            f'router_backends{{state="unhealthy"}} '
            f"{total_backends - healthy}",
        ]
        return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "k8s-tpu-router"
    # one TCP segment per response (the models/server.py rationale):
    # buffered writes + no Nagle, or keep-alive clients stall 40-200ms
    wbufsize = -1
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        log.debug("router: " + fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str,
              headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            if k.lower() not in ("content-type", "content-length"):
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        router: Router = self.server.router  # type: ignore[attr-defined]
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            return self._send(200, router.metrics_text().encode(),
                              "text/plain; version=0.0.4; charset=utf-8")
        if path == "/healthz":
            with_backends = any(b["healthy"] for b in router.backends())
            status = ("draining" if router.draining
                      else "ok" if with_backends else "no backends")
            code = 200 if with_backends and not router.draining else 503
            return self._send(code, json.dumps(
                {"status": status,
                 "backends": len(router.backends())}).encode(),
                "application/json")
        if path == "/debug/router":
            from k8s_tpu.router.debug import debug_router_response

            code, body, ctype = debug_router_response(router, query)
            return self._send(code, body.encode(), ctype)
        if path in ("/debug", "/debug/"):
            # the router process serves a minimal index of its own
            # endpoints (the full cross-subsystem index lives on the
            # operator's metrics server / dashboard, which aggregate
            # every active subsystem in that process)
            from k8s_tpu.router.debug import router_index_entry

            body = json.dumps(
                {"endpoints": [router_index_entry(active=True)]},
                indent=2) + "\n"
            return self._send(200, body.encode(), "application/json")
        return self._send(404, json.dumps(
            {"error": f"unknown path {path}"}).encode(), "application/json")

    def do_POST(self):  # noqa: N802
        router: Router = self.server.router  # type: ignore[attr-defined]
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self.close_connection = True
            return self._send(400, json.dumps(
                {"error": "bad Content-Length"}).encode(), "application/json")
        body = self.rfile.read(length) if length > 0 else b""
        if self.path.partition("?")[0] != "/v1/generate":
            return self._send(404, json.dumps(
                {"error": f"unknown path {self.path}"}).encode(),
                "application/json")
        if router.draining:
            return self._send(503, json.dumps(
                {"error": "router draining"}).encode(), "application/json",
                headers={"Retry-After": "1"})
        status, headers, resp_body, _info = router.handle_generate(
            body, {k.lower(): v for k, v in self.headers.items()})
        return self._send(status, resp_body, "application/json",
                          headers=headers)


class RouterServer:
    """The front-door HTTP process: a Router plus its listener."""

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.router = router  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "RouterServer":
        self.router.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="router-server")
        self._thread.start()
        log.info("router front door on :%d (POST /v1/generate)", self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.router.stop()

    def drain_and_stop(self, timeout_s: float = 30.0) -> bool:
        """SIGTERM path: refuse new requests, finish in-flight ones,
        then stop; True when the drain completed inside the budget."""
        self.router.drain()
        idle = self.router.wait_idle(timeout_s)
        self.stop()
        return idle

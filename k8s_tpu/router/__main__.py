"""Standalone front-door entrypoint (stdlib-only):

    python -m k8s_tpu.router --port 8080 \\
        --backend pod-0=http://10.0.0.4:8000 \\
        --backend pod-1=http://10.0.0.5:8000

or against a serving TFJob's per-index headless-service DNS names (the
controller's gen_general_name contract — zero apiserver calls):

    python -m k8s_tpu.router --port 8080 \\
        --dns-job default/serve-lm --dns-rtype worker --dns-replicas 4 \\
        --dns-port 8000

For informer-cache discovery against a live cluster (targets tracked as
pods come and go) use ``python -m k8s_tpu.cmd.router`` — that wrapper
carries the client-layer imports this stdlib-only package may not.

SIGTERM drains cleanly: new requests get 503 + Retry-After while every
in-flight request completes, then the process exits.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

import k8s_tpu.router as router_mod

log = logging.getLogger(__name__)


def dns_targets(job: str, rtype: str, replicas: int, port: int
                ) -> list[tuple[str, str]]:
    """Static per-index headless-service DNS targets for one serving
    job: ``<ns>-<name>-<rtype>-<i>.<ns>.svc.cluster.local`` (the
    fleet.discovery._dns_host contract, rebuilt from flags instead of
    pod labels)."""
    ns, _, name = job.partition("/")
    if not name:
        ns, name = "default", ns
    key = f"{ns}-{name}"
    return [
        (f"{key}-{rtype}-{i}",
         f"http://{key}-{rtype}-{i}.{ns}.svc.cluster.local:{port}")
        for i in range(replicas)
    ]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default loopback; set 0.0.0.0 "
                   "explicitly for pod exposure)")
    p.add_argument("--port", type=int,
                   default=router_mod._int_from_env(router_mod.ENV_PORT,
                                                    8080))
    p.add_argument("--backend", action="append", default=[],
                   metavar="NAME=URL",
                   help="static backend (repeatable)")
    p.add_argument("--dns-job", default=None,
                   help="serving TFJob key (ns/name) whose per-index "
                   "headless-service DNS names are the backends")
    p.add_argument("--dns-rtype", default="worker")
    p.add_argument("--dns-replicas", type=int, default=1)
    p.add_argument("--dns-port", type=int, default=8000)
    p.add_argument("--policy", choices=router_mod.VALID_POLICIES,
                   default=router_mod.policy_from_env())
    p.add_argument("--block-size", type=int,
                   default=router_mod.block_size_from_env(),
                   help="engine KV block size the affinity fingerprint "
                   "aligns to (K8S_TPU_ROUTER_BLOCK_SIZE)")
    p.add_argument("--affinity-blocks", type=int,
                   default=router_mod.affinity_blocks_from_env())
    p.add_argument("--retry-budget", type=int,
                   default=router_mod.retry_budget_from_env())
    p.add_argument("--phase-split-tokens", type=int,
                   default=router_mod.phase_tokens_from_env() or 0,
                   help="route prompts of at least this many tokens to "
                   "the prefill tier (disaggregated phase split, "
                   "K8S_TPU_ROUTER_PHASE_TOKENS; 0 = off)")
    p.add_argument("--hedge-s", type=float,
                   default=router_mod.hedge_s_from_env(),
                   help="hedge a stuck idempotent request against the "
                   "next ring candidate after this many seconds "
                   "(K8S_TPU_ROUTER_HEDGE_S; 0 = off)")
    p.add_argument("--drain-timeout", type=float, default=30.0)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    targets: list[tuple[str, str]] = []
    for spec in args.backend:
        name, _, url = spec.partition("=")
        if not name or not url:
            p.error(f"--backend must be NAME=URL, got {spec!r}")
        targets.append((name, url))
    if args.dns_job:
        targets.extend(dns_targets(args.dns_job, args.dns_rtype,
                                   args.dns_replicas, args.dns_port))
    if not targets:
        p.error("no backends: give --backend and/or --dns-job")

    router = router_mod.Router(
        lambda: targets, job=args.dns_job, policy=args.policy,
        block_size=args.block_size, affinity_blocks=args.affinity_blocks,
        retry_budget=args.retry_budget,
        phase_split_tokens=args.phase_split_tokens or None,
        hedge_s=args.hedge_s)
    server = router_mod.RouterServer(router, host=args.host,
                                     port=args.port)
    router_mod.set_active(router)
    server.start()
    done = threading.Event()

    def _sigterm(_signum, _frame):
        log.info("router: SIGTERM — draining")
        threading.Thread(
            target=lambda: (server.drain_and_stop(args.drain_timeout),
                            done.set()),
            daemon=True, name="router-drain").start()

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)
    print(f"READY http://{args.host}:{server.port}", flush=True)
    done.wait()
    router_mod.set_active(None)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""/debug/router responder — ONE implementation shared by the router's
own listener, the operator's metrics server, and the dashboard backend
(the fleet.debug_fleet_response pattern), so every process speaks the
same contract.

Routes:

- ``/debug/router``            — full state: ring membership + keyspace
  shares, per-backend health/in-flight/shed state, counters, recent
  placements
- ``?n=<limit>``               — most recent N placements (default 50)
- ``?backends=1``              — backends + counters only (no placements)

404 with an explicit body while no router is active in this process —
the same contract as every other /debug route.
"""

from __future__ import annotations

import json
from urllib.parse import parse_qs


def router_index_entry(active: bool) -> dict:
    """The /debug index row for the router responder (consumed by
    util.debug_index on the operator servers and by the router's own
    minimal /debug index)."""
    return {
        "path": "/debug/router",
        "subsystem": "serving front-door router (k8s_tpu.router)",
        "active": active,
        "activation": "a router process starts (python -m k8s_tpu.router) "
                      "or a bench/test activates one in-process",
        "params": ["n", "backends"],
    }


def debug_router_response(router, query: str = "") -> tuple[int, str, str]:
    """(status_code, body, content_type) for GET /debug/router."""
    if router is None or not router.active:
        return (404,
                "router inactive (start the front door with "
                "python -m k8s_tpu.router, or a bench/test activates one "
                "in-process)\n",
                "text/plain")
    params = parse_qs(query or "")
    limit = 50
    raw = (params.get("n") or [None])[0]
    if raw is not None:
        try:
            limit = max(0, int(raw))
        except ValueError:
            pass
    state = router.debug_state(n_placements=limit)
    if (params.get("backends") or [""])[0] in ("1", "true"):
        state.pop("placements", None)
    body = json.dumps(state, indent=2, default=str)
    return 200, body + "\n", "application/json"

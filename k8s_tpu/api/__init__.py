"""TFJob CRD API layer (reference: pkg/apis/tensorflow/)."""

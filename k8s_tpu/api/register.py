"""Scheme registration (reference: pkg/apis/tensorflow/*/register.go).

The Go scheme machinery (type registration + defaulting function dispatch)
reduces in Python to a version-keyed registry mapping apiVersion to the typed
TFJob class and its defaulting function.  ``default_tfjob`` is the analogue of
``Scheme.Default(obj)`` as called by the controllers
(pkg/controller/controller.go via trainer setup, pkg/controller.v2/controller.go:361).
"""

from __future__ import annotations

from typing import Callable

from k8s_tpu.api import v1alpha1, v1alpha2

GROUP_NAME = "kubeflow.org"

_REGISTRY: dict[str, tuple[type, Callable]] = {
    v1alpha1.CRD_API_VERSION: (v1alpha1.TFJob, v1alpha1.set_defaults_tfjob),
    v1alpha2.CRD_API_VERSION: (v1alpha2.TFJob, v1alpha2.set_defaults_tfjob),
}


def tfjob_class_for(api_version: str) -> type:
    try:
        return _REGISTRY[api_version][0]
    except KeyError:
        raise ValueError(f"unregistered apiVersion {api_version!r}") from None


def default_tfjob(tfjob) -> None:
    """Apply the registered defaulting function for the object's version."""
    try:
        fn = _REGISTRY[tfjob.api_version][1]
    except KeyError:
        raise ValueError(f"unregistered apiVersion {tfjob.api_version!r}") from None
    fn(tfjob)


def tfjob_from_unstructured(obj: dict):
    """Parse an unstructured TFJob dict into the typed class for its version
    (the conversion seam of pkg/controller.v2/informer.go:83-96)."""
    api_version = obj.get("apiVersion", v1alpha2.CRD_API_VERSION)
    return tfjob_class_for(api_version).from_dict(obj)

"""v1alpha2 constants (reference: pkg/apis/tensorflow/v1alpha2/constants.go).

Port 2222 and the container/port names are kept verbatim for manifest and
harness compatibility; in the TPU rebuild the port carries the
``jax.distributed`` coordinator service on process 0 instead of a per-replica
TF gRPC server.
"""

# ENV for the operator namespace (constants.go:18-19); single source of truth
# in k8s_tpu.util.util, re-exported here to mirror the reference layout.
from k8s_tpu.util.util import ENV_KUBEFLOW_NAMESPACE  # noqa: F401

# Port name/number used for inter-process bootstrap (constants.go:21-27).
DEFAULT_PORT_NAME = "tfjob-port"
DEFAULT_CONTAINER_NAME = "tensorflow"
DEFAULT_PORT = 2222

# --- TPU-native additions ---

# Resource-limit prefix that marks a container as a TPU slice host, the
# analogue of `nvidia.com/gpu` in examples/tf_job_gpu.yaml.  e.g.
# `cloud-tpus.google.com/v5e: 4` (4 chips per host).
TPU_RESOURCE_PREFIX = "cloud-tpus.google.com/"

# Env injected into every replica pod (replaces the TF_CONFIG contract of
# pkg/controller.v2/controller_tensorflow.go / pkg/trainer/replicas.go:202-234).
ENV_JAX_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_JAX_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_JAX_PROCESS_ID = "JAX_PROCESS_ID"
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_TPU_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_TPU_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_TPU_CONFIG = "TPU_CONFIG"  # JSON summary, kept TF_CONFIG-shaped for tooling

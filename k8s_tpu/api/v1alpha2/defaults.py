"""v1alpha2 defaulting (reference: pkg/apis/tensorflow/v1alpha2/defaults.go:33-69)."""

from __future__ import annotations

from k8s_tpu.api.v1alpha2 import constants, types


def _set_default_port(pod_spec: dict) -> None:
    """Ensure the `tensorflow` container exposes the tfjob-port
    (defaults.go:33-56).  Falls back to container 0 if none is named
    `tensorflow`, matching the reference's index-0 fallback."""
    containers = pod_spec.setdefault("containers", [])
    if not containers:
        return
    index = 0
    for i, c in enumerate(containers):
        if c.get("name") == constants.DEFAULT_CONTAINER_NAME:
            index = i
            break
    ports = containers[index].setdefault("ports", [])
    if not any(p.get("name") == constants.DEFAULT_PORT_NAME for p in ports):
        ports.append(
            {"name": constants.DEFAULT_PORT_NAME, "containerPort": constants.DEFAULT_PORT}
        )


def set_defaults_tfjob(tfjob: types.TFJob) -> None:
    """SetDefaults_TFJob (defaults.go:64-69) + restart-policy default.

    The reference defaulted only replicas and the container port; the
    RestartPolicy doc comment promised an Always default (types.go:75-78),
    applied here."""
    for spec in tfjob.spec.tf_replica_specs.values():
        if spec.replicas is None:
            spec.replicas = 1
        if spec.template is not None:
            _set_default_port(spec.template.setdefault("spec", {}))
        if not spec.restart_policy:
            spec.restart_policy = types.RestartPolicyAlways
    # gang-admission knobs (ISSUE 4): every job schedules at priority 0 in
    # the "default" queue unless the spec says otherwise
    if tfjob.spec.priority is None:
        tfjob.spec.priority = 0
    if not tfjob.spec.queue:
        tfjob.spec.queue = types.DEFAULT_SCHEDULING_QUEUE
    # autoscale bounds (ISSUE 13): the scaled type defaults to Worker —
    # the serving-job shape genjob --serve emits
    if tfjob.spec.autoscale is not None \
            and not tfjob.spec.autoscale.replica_type:
        tfjob.spec.autoscale.replica_type = types.TFReplicaTypeWorker

"""v1alpha2 TFJob types (reference: pkg/apis/tensorflow/v1alpha2/types.go).

The v1alpha2 shape: replica specs are a *map* keyed by replica type
(types.go:44-54), restart behavior is a per-replica ``RestartPolicy``
including the ExitCode contract (types.go:81-92), and status is
conditions + per-type counters + timestamps (types.go:115-149).

TPU-native addition: replica type ``TPU`` (a gang of slice hosts running one
SPMD program) and a job-level ``TPUSpec`` for slice topology.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from k8s_tpu.api.common import TPUSpec
from k8s_tpu.api.meta import ObjectMeta

CRD_KIND = "TFJob"
CRD_KIND_PLURAL = "tfjobs"
CRD_GROUP = "kubeflow.org"
CRD_VERSION = "v1alpha2"
CRD_API_VERSION = f"{CRD_GROUP}/{CRD_VERSION}"

# Restart policies (types.go:75-92)
RestartPolicyAlways = "Always"
RestartPolicyOnFailure = "OnFailure"
RestartPolicyNever = "Never"
RestartPolicyExitCode = "ExitCode"

# Terminal-job pod cleanup (the capability upstream added immediately
# after this snapshot's era; here opt-in).  "None" — the default —
# preserves snapshot behavior: pods of finished jobs are kept for log
# retrieval.  "Running" deletes only still-running pods (e.g. PS-style
# replicas that never exit on their own); "All" deletes the whole gang.
CleanPodPolicyNone = "None"
CleanPodPolicyRunning = "Running"
CleanPodPolicyAll = "All"
VALID_RESTART_POLICIES = (
    RestartPolicyAlways,
    RestartPolicyOnFailure,
    RestartPolicyNever,
    RestartPolicyExitCode,
)

# Replica types (types.go:94-112) + TPU gang type + the disaggregated
# serving tiers (ISSUE 15): a serving TFJob may split into a Prefill
# tier (compute-bound prompt ingestion, exports KV block chains) and a
# Decode tier (latency-bound token emission, imports them) — the same
# multi-role replica machinery PS/Worker topologies use, priced
# per-role by the capacity scheduler.
TFReplicaTypePS = "PS"
TFReplicaTypeWorker = "Worker"
TFReplicaTypeChief = "Chief"
TFReplicaTypeEval = "Eval"
TFReplicaTypeTPU = "TPU"
TFReplicaTypePrefill = "Prefill"
TFReplicaTypeDecode = "Decode"
VALID_REPLICA_TYPES = (
    TFReplicaTypePS,
    TFReplicaTypeWorker,
    TFReplicaTypeChief,
    TFReplicaTypeEval,
    TFReplicaTypeTPU,
    TFReplicaTypePrefill,
    TFReplicaTypeDecode,
)

# Condition types (types.go:168-196) + Queued (gang admission, ISSUE 4:
# a job parked by the capacity scheduler carries Queued=True and owns
# zero pods until the whole slice's worth of chips can be reserved)
TFJobCreated = "Created"
TFJobRunning = "Running"
TFJobRestarting = "Restarting"
TFJobSucceeded = "Succeeded"
TFJobFailed = "Failed"
TFJobQueued = "Queued"

# Gang-admission scheduling knobs (TFJobSpec.priority / .queue): priority
# defaults to 0 via SetDefaults, higher wins; the queue name is a logical
# grouping label for /debug/scheduler and multi-tenant reporting.
DEFAULT_SCHEDULING_QUEUE = "default"
# |priority| bound: enough headroom for any tiering scheme while keeping
# the aging boost (a handful of steps) meaningful arithmetic, and rejecting
# obvious garbage like timestamps.
MAX_PRIORITY_ABS = 1_000_000

# v1.ConditionStatus
ConditionTrue = "True"
ConditionFalse = "False"
ConditionUnknown = "Unknown"


@dataclass
class TFReplicaSpec:
    """types.go:56-73.  ``template`` is an unstructured PodTemplateSpec dict."""

    replicas: Optional[int] = None
    template: Optional[dict] = None
    restart_policy: str = ""

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.replicas is not None:
            d["replicas"] = self.replicas
        if self.template is not None:
            d["template"] = self.template
        if self.restart_policy:
            d["restartPolicy"] = self.restart_policy
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TFReplicaSpec":
        return cls(
            replicas=d.get("replicas"),
            template=d.get("template"),
            restart_policy=d.get("restartPolicy", ""),
        )


@dataclass
class AutoscaleSpec:
    """Serving-fleet autoscale bounds (ISSUE 13): the operator's
    metric-driven autoscaler may move ``replicaType``'s replica count
    inside ``[minReplicas, maxReplicas]`` — and nowhere else.  Absent
    spec = that job is never autoscaled (the compatibility default);
    the loop itself is additionally gated by ``K8S_TPU_AUTOSCALE``."""

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    # which replica type scales; SetDefaults fills "Worker"
    replica_type: str = ""

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.min_replicas is not None:
            d["minReplicas"] = self.min_replicas
        if self.max_replicas is not None:
            d["maxReplicas"] = self.max_replicas
        if self.replica_type:
            d["replicaType"] = self.replica_type
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "AutoscaleSpec":
        d = d or {}
        return cls(
            min_replicas=d.get("minReplicas"),
            max_replicas=d.get("maxReplicas"),
            replica_type=d.get("replicaType", ""),
        )


@dataclass
class TFJobSpec:
    """types.go:44-54 + TPU slice topology."""

    tf_replica_specs: dict[str, TFReplicaSpec] = field(default_factory=dict)
    tpu: Optional[TPUSpec] = None
    # None (unset) behaves as CleanPodPolicyNone — snapshot-era behavior
    clean_pod_policy: Optional[str] = None
    # wall-clock budget from StartTime (all replicas running): exceeded ->
    # the job fails with reason DeadlineExceeded (+ cleanPodPolicy applies)
    active_deadline_seconds: Optional[int] = None
    # gang-admission knobs (ISSUE 4): higher priority is admitted first and
    # may preempt strictly-lower-priority running gangs; queue is a logical
    # grouping label.  None = unset; SetDefaults fills 0 / "default".
    priority: Optional[int] = None
    queue: Optional[str] = None
    # serving autoscale bounds (ISSUE 13); None = never autoscaled
    autoscale: Optional[AutoscaleSpec] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "tfReplicaSpecs": {k: v.to_dict() for k, v in self.tf_replica_specs.items()}
        }
        if self.tpu is not None:
            d["tpu"] = self.tpu.to_dict()
        if self.clean_pod_policy is not None:
            d["cleanPodPolicy"] = self.clean_pod_policy
        if self.active_deadline_seconds is not None:
            d["activeDeadlineSeconds"] = self.active_deadline_seconds
        if self.priority is not None:
            d["priority"] = self.priority
        if self.queue is not None:
            d["queue"] = self.queue
        if self.autoscale is not None:
            d["autoscale"] = self.autoscale.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TFJobSpec":
        d = d or {}
        return cls(
            tf_replica_specs={
                k: TFReplicaSpec.from_dict(v) for k, v in (d.get("tfReplicaSpecs") or {}).items()
            },
            tpu=TPUSpec.from_dict(d["tpu"]) if d.get("tpu") else None,
            clean_pod_policy=d.get("cleanPodPolicy"),
            active_deadline_seconds=d.get("activeDeadlineSeconds"),
            priority=d.get("priority"),
            queue=d.get("queue"),
            autoscale=(AutoscaleSpec.from_dict(d["autoscale"])
                       if d.get("autoscale") else None),
        )


@dataclass
class TFJobCondition:
    """types.go:151-166."""

    type: str = ""
    status: str = ConditionUnknown
    reason: str = ""
    message: str = ""
    last_update_time: str = ""
    last_transition_time: str = ""

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastUpdateTime": self.last_update_time,
            "lastTransitionTime": self.last_transition_time,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TFJobCondition":
        return cls(
            type=d.get("type", ""),
            status=d.get("status", ConditionUnknown),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=d.get("lastUpdateTime", ""),
            last_transition_time=d.get("lastTransitionTime", ""),
        )


@dataclass
class TFReplicaStatus:
    """types.go:139-149: active/succeeded/failed pod counts."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.active:
            d["active"] = self.active
        if self.succeeded:
            d["succeeded"] = self.succeeded
        if self.failed:
            d["failed"] = self.failed
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TFReplicaStatus":
        d = d or {}
        return cls(
            active=int(d.get("active", 0)),
            succeeded=int(d.get("succeeded", 0)),
            failed=int(d.get("failed", 0)),
        )


@dataclass
class TFJobStatus:
    """types.go:114-137."""

    conditions: list[TFJobCondition] = field(default_factory=list)
    tf_replica_statuses: dict[str, TFReplicaStatus] = field(default_factory=dict)
    start_time: Optional[str] = None
    completion_time: Optional[str] = None
    last_reconcile_time: Optional[str] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "conditions": [c.to_dict() for c in self.conditions],
            "tfReplicaStatuses": {k: v.to_dict() for k, v in self.tf_replica_statuses.items()},
        }
        if self.start_time:
            d["startTime"] = self.start_time
        if self.completion_time:
            d["completionTime"] = self.completion_time
        if self.last_reconcile_time:
            d["lastReconcileTime"] = self.last_reconcile_time
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TFJobStatus":
        d = d or {}
        return cls(
            conditions=[TFJobCondition.from_dict(c) for c in d.get("conditions") or []],
            tf_replica_statuses={
                k: TFReplicaStatus.from_dict(v)
                for k, v in (d.get("tfReplicaStatuses") or {}).items()
            },
            start_time=d.get("startTime"),
            completion_time=d.get("completionTime"),
            last_reconcile_time=d.get("lastReconcileTime"),
        )


@dataclass
class TFJob:
    """types.go:27-42."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TFJobSpec = field(default_factory=TFJobSpec)
    status: TFJobStatus = field(default_factory=TFJobStatus)

    api_version: str = CRD_API_VERSION
    kind: str = CRD_KIND

    def to_dict(self) -> dict:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TFJob":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=TFJobSpec.from_dict(d.get("spec")),
            status=TFJobStatus.from_dict(d.get("status")),
            api_version=d.get("apiVersion", CRD_API_VERSION),
            kind=d.get("kind", CRD_KIND),
        )

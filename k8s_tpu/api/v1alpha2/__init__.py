"""v1alpha2 TFJob API (reference: pkg/apis/tensorflow/v1alpha2/)."""

from k8s_tpu.api.v1alpha2 import constants  # noqa: F401
from k8s_tpu.api.v1alpha2.types import *  # noqa: F401,F403
from k8s_tpu.api.v1alpha2.defaults import set_defaults_tfjob  # noqa: F401

"""Version-neutral API types shared by v1alpha1 and v1alpha2."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class TPUSpec:
    """Slice topology for TPU worker gangs (TPU-native addition; cf.
    BASELINE.json north_star).  ``accelerator_type`` is the Cloud TPU type
    (e.g. ``v5litepod-16``); ``topology`` the chip layout (e.g. ``4x4``);
    ``num_slices`` > 1 enables multi-slice (DCN) jobs."""

    accelerator_type: str = ""
    topology: str = ""
    num_slices: int = 1
    runtime_version: str = ""

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.accelerator_type:
            d["acceleratorType"] = self.accelerator_type
        if self.topology:
            d["topology"] = self.topology
        if self.num_slices != 1:
            d["numSlices"] = self.num_slices
        if self.runtime_version:
            d["runtimeVersion"] = self.runtime_version
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TPUSpec":
        d = d or {}
        return cls(
            accelerator_type=d.get("acceleratorType", ""),
            topology=d.get("topology", ""),
            num_slices=int(d.get("numSlices", 1)),
            runtime_version=d.get("runtimeVersion", ""),
        )

"""v1alpha1 defaulting (reference: pkg/apis/tensorflow/v1alpha1/defaults.go:27-58)."""

from __future__ import annotations

from k8s_tpu.api.v1alpha1 import types


def set_defaults_tfjob(job: types.TFJob) -> None:
    """SetDefaults_TFJob: image, per-replica port/type/count, chief policy."""
    spec = job.spec
    if not spec.tf_image:
        spec.tf_image = types.DEFAULT_TF_IMAGE

    for r in spec.replica_specs:
        if r.tf_port is None:
            r.tf_port = types.TF_PORT
        if not r.tf_replica_type:
            r.tf_replica_type = types.MASTER
        if r.replicas is None:
            r.replicas = types.REPLICAS

    if spec.termination_policy is None:
        # Chief defaults to MASTER:0 (defaults.go:49-56).  For pure
        # TPU_WORKER jobs (no MASTER replica) validation later retargets the
        # chief to TPU_WORKER:0 == JAX process 0.
        spec.termination_policy = types.TerminationPolicySpec(
            chief=types.ChiefSpec(replica_name=types.MASTER, replica_index=0)
        )
        if spec.replica_specs and not any(
            r.tf_replica_type == types.MASTER for r in spec.replica_specs
        ):
            tpu_specs = [r for r in spec.replica_specs if r.tf_replica_type == types.TPU_WORKER]
            if tpu_specs:
                spec.termination_policy.chief.replica_name = types.TPU_WORKER

"""v1alpha1 TFJob API (reference: pkg/apis/tensorflow/v1alpha1/)."""

from k8s_tpu.api.v1alpha1.types import *  # noqa: F401,F403
from k8s_tpu.api.v1alpha1.defaults import set_defaults_tfjob  # noqa: F401

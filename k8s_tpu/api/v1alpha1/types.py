"""v1alpha1 TFJob types (reference: pkg/apis/tensorflow/v1alpha1/types.go).

The v1alpha1 shape: a job is a *list* of replica specs, status is a *phase*
plus per-replica states, and the chief-based termination policy decides job
completion.  TPU-native changes relative to the reference:

- A ``TPU_WORKER`` replica type joins MASTER/PS/WORKER (types.go:80-84): a
  gang of slice hosts running one SPMD program.  PS remains accepted for
  legacy manifests but the trainer never provisions gRPC servers for it.
- ``TFJobSpec.tpu`` carries slice topology (accelerator type, topology
  string, slice count) — the TPU analogue of ``AcceleratorConfig`` host
  mounts (types.go:176-198), which TPU VMs do not need.
- The default image is a JAX image, not tensorflow/tensorflow:1.3.0.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from k8s_tpu.api.common import TPUSpec  # noqa: F401  (re-exported; wire shape shared)
from k8s_tpu.api.meta import ObjectMeta

# CRD identity (types.go:22-32)
CRD_KIND = "TFJob"
CRD_KIND_LOWER = "tfjob"
CRD_KIND_PLURAL = "tfjobs"
CRD_GROUP = "kubeflow.org"
CRD_VERSION = "v1alpha1"
CRD_API_VERSION = f"{CRD_GROUP}/{CRD_VERSION}"

# Value of the APP label applied to owned entities (types.go:28-29).
APP_LABEL = "tensorflow-job"

# Spec defaults (types.go:30-32, 87-90).  The default port is kept at 2222 so
# legacy manifests/services keep working; it now carries the JAX coordinator
# bootstrap rather than a TF gRPC server.
TF_PORT = 2222
REPLICAS = 1
DEFAULT_TF_CONTAINER = "tensorflow"
DEFAULT_TF_IMAGE = "ghcr.io/k8s-tpu/jax-tpu:latest"

# Replica types (types.go:80-84) + the TPU slice-host gang type.
MASTER = "MASTER"
PS = "PS"
WORKER = "WORKER"
TPU_WORKER = "TPU_WORKER"
VALID_REPLICA_TYPES = (MASTER, PS, WORKER, TPU_WORKER)

# Job phases (types.go:107-116)
PHASE_NONE = ""
PHASE_CREATING = "Creating"
PHASE_RUNNING = "Running"
PHASE_CLEANUP = "CleanUp"
PHASE_FAILED = "Failed"
PHASE_DONE = "Done"

# Job / replica states (types.go:118-127, 141-148)
STATE_UNKNOWN = "Unknown"
STATE_RUNNING = "Running"
STATE_SUCCEEDED = "Succeeded"
STATE_FAILED = "Failed"

REPLICA_STATE_UNKNOWN = "Unknown"
REPLICA_STATE_RUNNING = "Running"
REPLICA_STATE_FAILED = "Failed"
REPLICA_STATE_SUCCEEDED = "Succeeded"


@dataclass
class ChiefSpec:
    """Which replica's exit decides the job (types.go:72-75)."""

    replica_name: str = ""
    replica_index: int = 0

    def to_dict(self) -> dict:
        return {"replicaName": self.replica_name, "replicaIndex": self.replica_index}

    @classmethod
    def from_dict(cls, d: dict) -> "ChiefSpec":
        return cls(d.get("replicaName", ""), int(d.get("replicaIndex", 0)))


@dataclass
class TerminationPolicySpec:
    """types.go:66-69 — only the Chief policy exists."""

    chief: Optional[ChiefSpec] = None

    def to_dict(self) -> dict:
        return {"chief": self.chief.to_dict()} if self.chief else {}

    @classmethod
    def from_dict(cls, d: dict) -> "TerminationPolicySpec":
        c = d.get("chief")
        return cls(chief=ChiefSpec.from_dict(c) if c else None)


@dataclass
class TFReplicaSpec:
    """One replica group (types.go:92-104).  ``template`` is an unstructured
    PodTemplateSpec dict in wire format."""

    replicas: Optional[int] = None
    template: Optional[dict] = None
    tf_port: Optional[int] = None
    tf_replica_type: str = ""

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"tfReplicaType": self.tf_replica_type}
        if self.replicas is not None:
            d["replicas"] = self.replicas
        if self.template is not None:
            d["template"] = self.template
        if self.tf_port is not None:
            d["tfPort"] = self.tf_port
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TFReplicaSpec":
        return cls(
            replicas=d.get("replicas"),
            template=d.get("template"),
            tf_port=d.get("tfPort"),
            tf_replica_type=d.get("tfReplicaType", ""),
        )


@dataclass
class TFJobSpec:
    """types.go:47-64 + TPU slice topology."""

    runtime_id: str = ""
    replica_specs: list[TFReplicaSpec] = field(default_factory=list)
    tf_image: str = ""
    termination_policy: Optional[TerminationPolicySpec] = None
    scheduler_name: str = ""
    tpu: Optional[TPUSpec] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"replicaSpecs": [r.to_dict() for r in self.replica_specs]}
        if self.runtime_id:
            d["RuntimeId"] = self.runtime_id  # field had no json tag in the reference
        if self.tf_image:
            d["tfImage"] = self.tf_image
        if self.termination_policy is not None:
            d["terminationPolicy"] = self.termination_policy.to_dict()
        if self.scheduler_name:
            d["schedulerName"] = self.scheduler_name
        if self.tpu is not None:
            d["tpu"] = self.tpu.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TFJobSpec":
        d = d or {}
        return cls(
            runtime_id=d.get("RuntimeId", d.get("runtimeId", "")),
            replica_specs=[TFReplicaSpec.from_dict(r) for r in d.get("replicaSpecs") or []],
            tf_image=d.get("tfImage", ""),
            termination_policy=(
                TerminationPolicySpec.from_dict(d["terminationPolicy"])
                if d.get("terminationPolicy")
                else None
            ),
            scheduler_name=d.get("schedulerName", ""),
            tpu=TPUSpec.from_dict(d["tpu"]) if d.get("tpu") else None,
        )


@dataclass
class TFReplicaStatus:
    """types.go:150-160."""

    tf_replica_type: str = ""
    state: str = REPLICA_STATE_UNKNOWN
    replicas_states: dict[str, int] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "tf_replica_type": self.tf_replica_type,
            "state": self.state,
            "ReplicasStates": dict(self.replicas_states),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TFReplicaStatus":
        return cls(
            tf_replica_type=d.get("tf_replica_type", ""),
            state=d.get("state", REPLICA_STATE_UNKNOWN),
            replicas_states=dict(d.get("ReplicasStates") or {}),
        )


@dataclass
class TFJobStatus:
    """types.go:129-139."""

    phase: str = PHASE_NONE
    reason: str = ""
    state: str = STATE_UNKNOWN
    replica_statuses: list[TFReplicaStatus] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "phase": self.phase,
            "reason": self.reason,
            "state": self.state,
            "replicaStatuses": [r.to_dict() for r in self.replica_statuses],
        }

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TFJobStatus":
        d = d or {}
        return cls(
            phase=d.get("phase", PHASE_NONE),
            reason=d.get("reason", ""),
            state=d.get("state", STATE_UNKNOWN),
            replica_statuses=[TFReplicaStatus.from_dict(r) for r in d.get("replicaStatuses") or []],
        )


@dataclass
class TFJob:
    """types.go:39-45."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TFJobSpec = field(default_factory=TFJobSpec)
    status: TFJobStatus = field(default_factory=TFJobStatus)

    api_version: str = CRD_API_VERSION
    kind: str = CRD_KIND

    def to_dict(self) -> dict:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TFJob":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=TFJobSpec.from_dict(d.get("spec")),
            status=TFJobStatus.from_dict(d.get("status")),
            api_version=d.get("apiVersion", CRD_API_VERSION),
            kind=d.get("kind", CRD_KIND),
        )


# Accelerator config (types.go:176-212): volume/env injection keyed on a
# container resource-limit name, loaded from the operator's --controller-config-file.
@dataclass
class AcceleratorVolume:
    name: str = ""
    host_path: str = ""
    mount_path: str = ""


@dataclass
class EnvironmentVariableConfig:
    name: str = ""
    value: str = ""


@dataclass
class AcceleratorConfig:
    volumes: list[AcceleratorVolume] = field(default_factory=list)
    env_vars: list[EnvironmentVariableConfig] = field(default_factory=list)


@dataclass
class ControllerConfig:
    """types.go:176-185.  ``grpc_server_file_path`` is retained for manifest
    compatibility but unused: the PS default-server concept is deleted in the
    TPU rebuild (SURVEY.md §2.4)."""

    accelerators: dict[str, AcceleratorConfig] = field(default_factory=dict)
    grpc_server_file_path: str = ""

"""Manifest loading: YAML documents -> typed, defaulted, validated TFJobs.

The reference has no loader of its own — `kubectl create -f examples/tf_job.yaml`
feeds the apiserver, which defaults via the scheme (zz_generated.defaults.go)
and rejects on the CRD's openAPIV3Schema (examples/crd/crd-v1alpha2.yaml).
Here the same pipeline is a library function so the dashboard deploy handler
(dashboard/backend/handler/api_handler.go:117-266 analogue), the e2e harness,
and tests all share one ingest path.
"""

from __future__ import annotations

import io
from typing import Iterator

import yaml

from k8s_tpu.api import register, v1alpha1, v1alpha2, validation


def load_yaml_documents(text: str) -> Iterator[dict]:
    """Yield the non-empty YAML documents in ``text`` (--- separated)."""
    for doc in yaml.safe_load_all(io.StringIO(text)):
        if doc:
            yield doc


def load_tfjob(doc: dict, default: bool = True, validate: bool = True):
    """Unstructured dict -> typed TFJob for its apiVersion, optionally
    defaulted (scheme dispatch, register.py) and validated
    (pkg/apis/tensorflow/validation/validation.go analogue)."""
    kind = doc.get("kind")
    if kind != "TFJob":
        raise ValueError(f"expected kind TFJob, got {kind!r}")
    job = register.tfjob_from_unstructured(doc)
    if default:
        register.default_tfjob(job)
    if validate:
        if job.api_version == v1alpha1.CRD_API_VERSION:
            validation.validate_v1alpha1_tfjob_spec(job.spec)
        elif job.api_version == v1alpha2.CRD_API_VERSION:
            validation.validate_v1alpha2_tfjob_spec(job.spec)
        else:
            raise ValueError(f"unvalidatable apiVersion {job.api_version!r}")
    return job


def load_tfjobs_from_file(path: str, default: bool = True, validate: bool = True) -> list:
    """Load every TFJob document from a manifest file; non-TFJob documents
    (e.g. the CRD itself) are skipped, matching kubectl's multi-doc apply."""
    with open(path) as f:
        text = f.read()
    jobs = []
    for doc in load_yaml_documents(text):
        if doc.get("kind") == "TFJob":
            jobs.append(load_tfjob(doc, default=default, validate=validate))
    return jobs

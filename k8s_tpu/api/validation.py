"""TFJob spec validation (reference: pkg/apis/tensorflow/validation/validation.go).

Both API versions are validated here, like the reference keeps validation in
its own package.  Errors are raised as ``ValidationError`` so callers can map
them to the Failed phase/condition (pkg/trainer/training.go:220-228).
"""

from __future__ import annotations

from k8s_tpu.api import v1alpha1
from k8s_tpu.api.v1alpha2 import constants as v2c
from k8s_tpu.api.v1alpha2 import types as v2


class ValidationError(ValueError):
    """Invalid TFJob spec."""


def validate_v1alpha1_tfjob_spec(spec: v1alpha1.TFJobSpec) -> None:
    """ValidateTFJobSpec (validation.go:26-79): chief policy present, every
    replica has a template/port/valid type and a container named
    ``tensorflow``; the chief's replica type must exist."""
    if spec.termination_policy is None or spec.termination_policy.chief is None:
        raise ValidationError(f"invalid termination policy: {spec.termination_policy}")

    chief_name = spec.termination_policy.chief.replica_name
    chief_exists = False

    for r in spec.replica_specs:
        if r.template is None:
            raise ValidationError(f"Replica is missing Template; {r}")
        if r.tf_replica_type == chief_name:
            chief_exists = True
        if r.tf_port is None:
            raise ValidationError("tfReplicaSpec.TFPort can't be None")
        if r.tf_replica_type not in v1alpha1.VALID_REPLICA_TYPES:
            raise ValidationError(
                f"tfReplicaSpec.TFReplicaType is {r.tf_replica_type} but must be one of "
                f"{list(v1alpha1.VALID_REPLICA_TYPES)}"
            )
        _require_container(r.template, v1alpha1.DEFAULT_TF_CONTAINER, r.tf_replica_type)
        if r.tf_replica_type == v1alpha1.TPU_WORKER:
            _validate_tpu_replica(r.template, r.tf_replica_type)

    if not chief_exists:
        raise ValidationError(f"Missing ReplicaSpec for chief: {chief_name}")


def validate_v1alpha2_tfjob_spec(spec: v2.TFJobSpec) -> None:
    """v1alpha2 analogue (upstream added it post-snapshot; semantics follow
    the CRD openAPIV3Schema in examples/crd/crd-v1alpha2.yaml: known replica
    types, replicas >= 1, at most one Chief, container present)."""
    if not spec.tf_replica_specs:
        raise ValidationError("TFJobSpec.tfReplicaSpecs must not be empty")
    if spec.clean_pod_policy is not None and spec.clean_pod_policy not in (
            v2.CleanPodPolicyNone, v2.CleanPodPolicyRunning,
            v2.CleanPodPolicyAll):
        raise ValidationError(
            f"cleanPodPolicy {spec.clean_pod_policy!r} must be one of "
            "None, Running, All")
    if spec.active_deadline_seconds is not None \
            and spec.active_deadline_seconds <= 0:
        raise ValidationError(
            f"activeDeadlineSeconds must be > 0, "
            f"got {spec.active_deadline_seconds}")
    _validate_scheduling_fields(spec)
    _validate_autoscale(spec)
    for rtype, r in spec.tf_replica_specs.items():
        if rtype not in v2.VALID_REPLICA_TYPES:
            raise ValidationError(
                f"tfReplicaType {rtype} must be one of {list(v2.VALID_REPLICA_TYPES)}"
            )
        if r.replicas is not None and r.replicas < 1:
            raise ValidationError(f"replicas for {rtype} must be >= 1")
        if rtype == v2.TFReplicaTypeChief and (r.replicas or 1) > 1:
            raise ValidationError("TFJobSpec must not have more than 1 Chief replica")
        if r.template is None:
            raise ValidationError(f"Replica {rtype} is missing Template")
        _require_container(r.template, v2c.DEFAULT_CONTAINER_NAME, rtype)
        _require_port(r.template, rtype)
        if rtype == v2.TFReplicaTypeTPU:
            _validate_tpu_replica(r.template, rtype)


_QUEUE_NAME_RE = None  # compiled lazily; validation is import-hot


def _validate_scheduling_fields(spec: v2.TFJobSpec) -> None:
    """Gang-admission knobs (ISSUE 4): ``priority`` must be a genuine int
    within +/-MAX_PRIORITY_ABS (bool is an int subclass but means a typo'd
    manifest, so it is rejected), ``queue`` a label-shaped name."""
    if spec.priority is not None:
        if isinstance(spec.priority, bool) or not isinstance(spec.priority, int):
            raise ValidationError(
                f"priority must be an integer, got {spec.priority!r}")
        if abs(spec.priority) > v2.MAX_PRIORITY_ABS:
            raise ValidationError(
                f"priority must be within +/-{v2.MAX_PRIORITY_ABS}, "
                f"got {spec.priority}")
    if spec.queue is not None:
        global _QUEUE_NAME_RE
        if _QUEUE_NAME_RE is None:
            import re

            # DNS-label-shaped: alphanumeric ends, [-._] allowed inside
            _QUEUE_NAME_RE = re.compile(
                r"[A-Za-z0-9]([A-Za-z0-9._-]{0,61}[A-Za-z0-9])?")
        if (not isinstance(spec.queue, str)
                or not _QUEUE_NAME_RE.fullmatch(spec.queue)):
            raise ValidationError(
                f"queue must be a label-shaped name (<= 63 chars, "
                f"alphanumeric ends), got {spec.queue!r}")


def _validate_autoscale(spec: v2.TFJobSpec) -> None:
    """Autoscale bounds (ISSUE 13): genuine ints with
    1 <= min <= max, and the scaled replica type must exist in the spec
    (after SetDefaults filled "Worker") — a bound on a phantom type
    would make the autoscaler a no-op that LOOKS configured."""
    a = spec.autoscale
    if a is None:
        return
    for field_name, value in (("minReplicas", a.min_replicas),
                              ("maxReplicas", a.max_replicas)):
        if value is None:
            raise ValidationError(
                f"autoscale.{field_name} is required when autoscale is set")
        if isinstance(value, bool) or not isinstance(value, int):
            raise ValidationError(
                f"autoscale.{field_name} must be an integer, got {value!r}")
        if value < 1:
            raise ValidationError(
                f"autoscale.{field_name} must be >= 1, got {value}")
    if a.min_replicas > a.max_replicas:
        raise ValidationError(
            f"autoscale.minReplicas {a.min_replicas} must be <= "
            f"maxReplicas {a.max_replicas}")
    if a.replica_type and a.replica_type not in spec.tf_replica_specs:
        raise ValidationError(
            f"autoscale.replicaType {a.replica_type!r} has no replica spec "
            f"(have {sorted(spec.tf_replica_specs)})")


def _require_container(template: dict, container_name: str, rtype: str) -> None:
    containers = ((template.get("spec") or {}).get("containers")) or []
    if not any(c.get("name") == container_name for c in containers):
        raise ValidationError(
            f"Replica type {rtype} is missing a container named {container_name}"
        )


def _require_port(template: dict, rtype: str) -> None:
    """The bootstrap port must exist (the v1alpha2 analogue of v1alpha1's
    TFPort nil check, validation.go:44-46).  Defaulting adds it, so only
    un-defaulted specs fail here — terminally, instead of the controller
    hot-looping on PortNotFoundError during env generation."""
    for c in ((template.get("spec") or {}).get("containers")) or []:
        for p in c.get("ports") or []:
            if p.get("name") == v2c.DEFAULT_PORT_NAME:
                return
    raise ValidationError(
        f"Replica type {rtype} has no container port named {v2c.DEFAULT_PORT_NAME!r} "
        "(defaulting adds it; was SetDefaults skipped?)"
    )


def _validate_tpu_replica(template: dict, rtype: str) -> None:
    """TPU gangs must declare a TPU resource limit so the scheduler can place
    them on slice hosts (the TPU analogue of the nvidia.com/gpu limit in
    examples/tf_job_gpu.yaml)."""
    for c in ((template.get("spec") or {}).get("containers")) or []:
        limits = ((c.get("resources") or {}).get("limits")) or {}
        if any(k.startswith(v2c.TPU_RESOURCE_PREFIX) for k in limits):
            return
    raise ValidationError(
        f"Replica type {rtype} must set a '{v2c.TPU_RESOURCE_PREFIX}*' resource limit"
    )

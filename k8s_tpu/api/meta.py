"""Object metadata machinery (the slice of k8s.io/apimachinery the API needs).

Kubernetes resources other than the TFJob CRD itself (pods, services,
pod-disruption budgets, pod templates) are handled throughout this codebase as
**unstructured dicts** in wire format (camelCase JSON) — the same choice the
reference converged on for CRDs (pkg/util/unstructured/informer.go, motivated
by kubeflow/tf-operator#561).  Only the TFJob types are strongly typed; this
module provides the shared ObjectMeta/OwnerReference dataclasses they embed.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Optional


def now_rfc3339() -> str:
    """Current UTC time in the RFC3339 second-resolution form K8s uses."""
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def parse_rfc3339(ts: Any) -> Optional[datetime]:
    """RFC3339 → aware datetime, or None on junk.

    Timezone-naive inputs (no 'Z'/offset — hand-edited statuses, foreign
    clients) are pinned to UTC rather than left naive: a naive datetime
    subtracted from an aware one raises TypeError, which once hot-looped a
    controller sync.  The ONE parse used everywhere timestamps are read.
    """
    if not ts:
        return None
    try:
        parsed = datetime.fromisoformat(str(ts).replace("Z", "+00:00"))
    except ValueError:
        return None
    if parsed.tzinfo is None:
        parsed = parsed.replace(tzinfo=timezone.utc)
    return parsed


@dataclass
class OwnerReference:
    """metav1.OwnerReference."""

    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: Optional[bool] = None
    block_owner_deletion: Optional[bool] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "name": self.name,
            "uid": self.uid,
        }
        if self.controller is not None:
            d["controller"] = self.controller
        if self.block_owner_deletion is not None:
            d["blockOwnerDeletion"] = self.block_owner_deletion
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "OwnerReference":
        return cls(
            api_version=d.get("apiVersion", ""),
            kind=d.get("kind", ""),
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            controller=d.get("controller"),
            block_owner_deletion=d.get("blockOwnerDeletion"),
        )


@dataclass
class ObjectMeta:
    """metav1.ObjectMeta — the subset the operator reads and writes."""

    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    owner_references: list[OwnerReference] = field(default_factory=list)
    creation_timestamp: str = ""
    deletion_timestamp: Optional[str] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.name:
            d["name"] = self.name
        if self.namespace:
            d["namespace"] = self.namespace
        if self.uid:
            d["uid"] = self.uid
        if self.resource_version:
            d["resourceVersion"] = self.resource_version
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.owner_references:
            d["ownerReferences"] = [o.to_dict() for o in self.owner_references]
        if self.creation_timestamp:
            d["creationTimestamp"] = self.creation_timestamp
        if self.deletion_timestamp:
            d["deletionTimestamp"] = self.deletion_timestamp
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ObjectMeta":
        d = d or {}
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", ""),
            uid=d.get("uid", ""),
            resource_version=d.get("resourceVersion", ""),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            owner_references=[OwnerReference.from_dict(o) for o in d.get("ownerReferences") or []],
            creation_timestamp=d.get("creationTimestamp", ""),
            deletion_timestamp=d.get("deletionTimestamp"),
        )


def get_controller_of(obj_meta: dict) -> Optional[dict]:
    """Return the controlling ownerReference of an unstructured object's
    metadata dict, like metav1.GetControllerOf."""
    for ref in obj_meta.get("ownerReferences") or []:
        if ref.get("controller"):
            return ref
    return None


def deep_copy(obj: Any) -> Any:
    """DeepCopy equivalent for unstructured objects (zz_generated.deepcopy.go)."""
    return copy.deepcopy(obj)
